"""Workload tests on the virtual 8-device CPU mesh (conftest.py forces
--xla_force_host_platform_device_count=8): the flagship LM forward/train
step, the scheduler->mesh bridge, and sharded-vs-single-device numerical
equivalence — the same Mesh/pjit/shard_map paths a real slice runs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tputopo.workloads import (
    ModelConfig, build_mesh, forward, init_params, make_train_state,
    plan_mesh, train_step,
)
from tputopo.workloads import sharding as shardlib
from tputopo.workloads.collective import measure_allreduce
from tputopo.workloads.train import loss_fn, make_sharded_state, make_sharded_train_step

# CPU tests compare sharded vs unsharded bit-patterns; keep f32 so the
# comparison is meaningful (bf16 on CPU is emulated and slow anyway).
TINY = ModelConfig(vocab_size=64, d_model=32, n_layers=2, n_heads=4,
                   n_kv_heads=2, d_ff=64, max_seq=32,
                   compute_dtype=jnp.float32)


def make_batch(config, batch=4, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, config.vocab_size, (batch, seq)))


def test_sp_impl_typo_is_rejected_at_construction():
    """A bad sp_impl must error eagerly in __post_init__, not only when a
    context-parallel plan happens to be active (ADVICE r5)."""
    with pytest.raises(ValueError, match="sp_impl"):
        ModelConfig(sp_impl="a2A")
    ModelConfig(sp_impl="a2a")  # both valid strategies still construct
    ModelConfig(sp_impl="ring")


def test_forward_shapes_and_dtype():
    params = init_params(TINY, jax.random.key(0))
    tokens = make_batch(TINY)
    logits = forward(params, tokens, TINY)
    assert logits.shape == (4, 16, TINY.vocab_size)
    assert logits.dtype == jnp.float32
    assert bool(jnp.isfinite(logits).all())


def test_causality():
    """Changing a future token must not change past logits."""
    params = init_params(TINY, jax.random.key(0))
    tokens = make_batch(TINY)
    a = forward(params, tokens, TINY)
    mutated = tokens.at[:, -1].set((tokens[:, -1] + 1) % TINY.vocab_size)
    b = forward(params, mutated, TINY)
    np.testing.assert_allclose(a[:, :-1], b[:, :-1], rtol=1e-5)
    assert not np.allclose(a[:, -1], b[:, -1])


def test_train_step_reduces_loss():
    state = make_train_state(TINY, jax.random.key(1), lr=1e-2)
    tokens = make_batch(TINY)
    step = jax.jit(lambda s, t: train_step(s, t, TINY, lr=1e-2))
    _, first = step(state, tokens)
    for _ in range(10):
        state, loss = step(state, tokens)
    assert float(loss) < float(first)
    assert int(state.step) == 10


def test_plan_mesh_policy():
    def axes(**kw):
        return {"pp": 1, "dp": 1, "sp": 1, "ep": 1, "tp": 1, **kw}

    assert plan_mesh(8, heads=4) == axes(dp=2, tp=4)
    assert plan_mesh(8, heads=2) == axes(dp=4, tp=2)
    assert plan_mesh(8, tp=2, sp=2) == axes(dp=2, sp=2, tp=2)
    assert plan_mesh(1) == axes()
    assert plan_mesh(8, pp=2, ep=2, tp=2) == axes(pp=2, ep=2, tp=2)
    assert plan_mesh(16, pp=2, ep=2, heads=4) == axes(pp=2, ep=2, tp=4)
    with pytest.raises(ValueError):
        plan_mesh(8, tp=3)
    with pytest.raises(ValueError):
        plan_mesh(8, pp=3)


@pytest.mark.slow
def test_remat_policies_agree():
    """remat is a memory policy, not math: block/dots/none forwards and
    grads must agree up to f32 noise."""
    import dataclasses

    toks = make_batch(TINY, batch=2, seq=16)
    grads = {}
    for remat in ("block", "dots", "none"):
        cfg = dataclasses.replace(TINY, remat=remat)
        params = init_params(cfg, jax.random.key(0))
        loss, g = jax.value_and_grad(loss_fn)(params, toks, cfg)
        grads[remat] = (float(loss), jax.tree.leaves(g))
    for remat in ("dots", "none"):
        assert grads[remat][0] == pytest.approx(grads["block"][0], rel=1e-6)
        for a, b in zip(grads[remat][1], grads["block"][1]):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)
    cfg = dataclasses.replace(TINY, remat="bogus")
    with pytest.raises(ValueError, match="remat"):
        jax.eval_shape(lambda p: loss_fn(p, toks, cfg),
                       init_params(cfg, jax.random.key(0)))


def test_constrain_is_noop_without_plan():
    x = jnp.ones((4, 4))
    assert shardlib.constrain(x, "dp", None) is x


def test_sharded_matches_single_device():
    """The DP x TP sharded train step must compute the same loss as the
    single-device step — sharding is layout, not math."""
    plan = build_mesh({"dp": 2, "sp": 1, "tp": 4})
    assert plan.n_devices == 8
    tokens = make_batch(TINY, batch=4, seq=16)

    ref_state = make_train_state(TINY, jax.random.key(2), lr=1e-2)
    ref_loss = float(loss_fn(ref_state.params, tokens, TINY))

    sh_state = make_sharded_state(plan, TINY, jax.random.key(2), lr=1e-2)
    step = make_sharded_train_step(plan, TINY, lr=1e-2)
    sh_state, sh_loss = step(sh_state, tokens)
    assert float(sh_loss) == pytest.approx(ref_loss, rel=2e-4)

    # And the updated params agree with the unsharded update.
    ref_state, _ = jax.jit(lambda s, t: train_step(s, t, TINY, lr=1e-2))(
        ref_state, tokens)
    ref_flat, _ = jax.tree.flatten(ref_state.params)
    sh_flat, _ = jax.tree.flatten(jax.device_get(sh_state.params))
    for r, s in zip(ref_flat, sh_flat):
        np.testing.assert_allclose(r, s, rtol=2e-3, atol=2e-5)


def test_param_shardings_land_on_mesh():
    plan = build_mesh({"dp": 2, "sp": 1, "tp": 4})
    state = make_sharded_state(plan, TINY, jax.random.key(0))
    wq = state.params["layers"]["wq"]
    # Column-parallel: last axis split over tp=4.
    assert wq.sharding.spec == shardlib.P(None, None, "tp")
    shard_shapes = {s.data.shape for s in wq.addressable_shards}
    L, D, H = TINY.n_layers, TINY.d_model, TINY.n_heads * TINY.head_dim
    assert shard_shapes == {(L, D, H // 4)}


@pytest.mark.slow
def test_sp_sequence_sharding_runs():
    """SP (sequence) axis active: activations split along seq dim."""
    plan = build_mesh({"dp": 2, "sp": 2, "tp": 2})
    tokens = make_batch(TINY, batch=2, seq=16)
    state = make_sharded_state(plan, TINY, jax.random.key(3))
    step = make_sharded_train_step(plan, TINY)
    state, loss = step(state, tokens)
    assert bool(jnp.isfinite(loss))


def test_allreduce_microbench_runs():
    res = measure_allreduce(payload_mb=0.5, iters=3, warmup=1)
    assert res.n_devices == 8
    assert res.algbw_gbps > 0
    d = res.to_dict()
    assert set(d) == {"n_devices", "payload_mb", "time_ms", "algbw_gbps",
                      "busbw_gbps"}


def test_validate_slice_reports_efficiency():
    """The predicted-vs-measured loop runs end to end on the CPU mesh: the
    report carries both numbers and a finite efficiency (absolute parity is
    a hardware acceptance criterion, not a CPU CI one)."""
    from tputopo.workloads.validate import validate_slice

    report = validate_slice("v5e:4x2", payload_mb=0.5, iters=3)
    d = report.to_dict()
    assert d["predicted_gbps"] > 0
    assert d["measured_gbps"] > 0
    assert 0 < d["efficiency"] < 1e6


def test_calibrate_cost_model_roundtrips():
    """Calibration must make the model reproduce the measured number
    exactly — the closing of the reference's open weight-table TODO."""
    from tputopo.topology.model import parse_topology
    from tputopo.topology.score import predict_allreduce_gbps
    from tputopo.workloads.validate import calibrate_cost_model

    topo = parse_topology("v5p:2x2x4:wrap=000")
    measured = 123.4
    cal = calibrate_cost_model(topo, measured)
    assert predict_allreduce_gbps(topo, topo.dims, cal) == pytest.approx(measured)

    single = parse_topology("v5p:1x1x1:wrap=000")
    with pytest.raises(ValueError, match="no multi-chip axis"):
        calibrate_cost_model(single, 10.0)


def test_calibrate_both_ici_and_hbm_roundtrips_through_config():
    """VERDICT r3 #4: the HBM half of the weight table calibrates too, and
    the whole calibrated model round-trips through ExtenderConfig's cost
    override — the deployable artifact that closes design.md:47's TODO
    for both axes."""
    from tputopo.extender.config import ExtenderConfig
    from tputopo.topology.generations import get_generation
    from tputopo.topology.model import parse_topology
    from tputopo.topology.score import predict_allreduce_gbps
    from tputopo.workloads.validate import (calibrate_cost_model,
                                            measured_vs_spec)

    topo = parse_topology("v5e:4x4:wrap=00")
    cal = calibrate_cost_model(topo, 88.8, measured_hbm_gbps=578.0)
    assert predict_allreduce_gbps(topo, topo.dims, cal) == pytest.approx(88.8)
    assert cal.hbm_gbps == 578.0

    # HBM-only calibration works on a single chip (no ICI axis needed).
    single = parse_topology("v5e:1x1:wrap=00")
    hbm_only = calibrate_cost_model(single, measured_hbm_gbps=578.0)
    assert hbm_only.hbm_gbps == 578.0
    assert hbm_only.ici_link_gbps == get_generation("v5e").ici_link_gbps

    with pytest.raises(ValueError, match="nothing to calibrate"):
        calibrate_cost_model(topo)
    with pytest.raises(ValueError, match="measured_hbm_gbps"):
        calibrate_cost_model(topo, measured_hbm_gbps=-1.0)

    # The measured-vs-spec record documents the delta per field.
    rec = measured_vs_spec(cal, "v5e")
    assert rec["hbm_gbps"]["spec"] == get_generation("v5e").hbm_gbps
    assert rec["hbm_gbps"]["calibrated_over_spec"] == pytest.approx(
        578.0 / get_generation("v5e").hbm_gbps, abs=1e-3)

    # Round-trip through the config override surface.
    cfg = ExtenderConfig(cost_overrides={"v5e": {
        "ici_link_gbps": cal.ici_link_gbps, "hbm_gbps": cal.hbm_gbps}})
    assert cfg.cost_model("v5e") == cal


def test_train_cli_profile_writes_trace(tmp_path):
    """--profile captures a steady-state jax.profiler trace (SURVEY aux
    5.1's workload leg): the XProf-openable artifacts must land in DIR."""
    import subprocess
    import sys

    code = (
        "import jax, sys; jax.config.update('jax_platforms', 'cpu'); "
        f"sys.argv = ['x', 'train', '--steps', '3', '--seq', '32', "
        f"'--batch', '2', '--profile', {str(tmp_path)!r}]; "
        "from tputopo.workloads.__main__ import main; "
        "raise SystemExit(main())")
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=240)
    assert proc.returncode == 0, proc.stderr[-2000:]
    traces = [p for p in (tmp_path / "plugins" / "profile").rglob("*")
              if p.is_file()]
    assert any(p.name.endswith(".xplane.pb") for p in traces), traces


def test_grad_accumulation_matches_full_batch():
    """accum_steps=2 must produce the full-batch gradient exactly for the
    dense model (cross-entropy means over equal chunks average to the
    full-batch mean), so one step from the same state lands on the same
    params and loss."""
    from tputopo.workloads.train import make_train_state, train_step

    cfg = ModelConfig(vocab_size=64, d_model=32, n_layers=2, n_heads=4,
                      n_kv_heads=2, d_ff=64, max_seq=32,
                      compute_dtype=jnp.float32)
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 64, (4, 16)))
    s0 = make_train_state(cfg, jax.random.key(0))
    s1, l1 = jax.jit(lambda s, t: train_step(s, t, cfg))(s0, tokens)
    s0b = make_train_state(cfg, jax.random.key(0))
    s2, l2 = jax.jit(lambda s, t: train_step(s, t, cfg, accum_steps=2))(
        s0b, tokens)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-5),
        s1.params, s2.params)


@pytest.mark.slow
def test_sharded_grad_accumulation_runs_and_converges():
    from tputopo.workloads.train import (make_sharded_state,
                                         make_sharded_train_step)

    cfg = ModelConfig(vocab_size=64, d_model=32, n_layers=2, n_heads=4,
                      n_kv_heads=2, d_ff=64, max_seq=32,
                      compute_dtype=jnp.float32)
    plan = build_mesh({"dp": 2, "tp": 2, "sp": 2})
    state = make_sharded_state(plan, cfg, jax.random.key(0))
    step = make_sharded_train_step(plan, cfg, accum_steps=2)
    toks = jnp.asarray(np.random.default_rng(1).integers(0, 64, (8, 32)))
    prev = None
    for _ in range(3):
        state, loss = step(state, toks)
        assert bool(jnp.isfinite(loss))
        if prev is not None:
            assert float(loss) < prev
        prev = float(loss)


@pytest.mark.slow
def test_pipelined_grad_accumulation_composes():
    """accum's lax.scan of value_and_grad over the shard_map pipeline
    (pp>1) must stay differentiable and converge — the CLI advertises the
    composition, so it gets its own regression test."""
    from tputopo.workloads.train import (make_sharded_state,
                                         make_sharded_train_step)

    cfg = ModelConfig(vocab_size=64, d_model=32, n_layers=2, n_heads=4,
                      n_kv_heads=2, d_ff=64, max_seq=32,
                      compute_dtype=jnp.float32)
    plan = build_mesh({"pp": 2, "dp": 2, "tp": 2})
    state = make_sharded_state(plan, cfg, jax.random.key(0))
    step = make_sharded_train_step(plan, cfg, accum_steps=2)
    # batch quantum: dp * pp * accum = 8.
    toks = jnp.asarray(np.random.default_rng(2).integers(0, 64, (8, 32)))
    prev = None
    for _ in range(3):
        state, loss = step(state, toks)
        assert bool(jnp.isfinite(loss))
        if prev is not None:
            assert float(loss) < prev
        prev = float(loss)
