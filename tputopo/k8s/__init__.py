"""Minimal Kubernetes object model + in-memory API server test double.

The reference keeps all durable state in Kubernetes objects — topology in
node annotations (design.md:76-82), assignments in pod annotations
(design.md:223-234) — and rebuilds everything else from the API server
(SURVEY.md §5.4 statelessness posture).  This package gives the framework
that state plane: dict-shaped Node/Pod objects matching the real API
surface, and a FakeApiServer with patch/bind/watch semantics so the whole
stack tests without a cluster (SURVEY.md §4.3-4.4).
"""

from tputopo.k8s.objects import (  # noqa: F401
    Annotations,
    make_node,
    make_pod,
    pod_requested_chips,
)
from tputopo.k8s.fakeapi import FakeApiServer, Conflict, NotFound  # noqa: F401
