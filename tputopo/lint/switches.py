"""The ``kill-switch-audit`` checker: every perf leg's kill switch is
registered, live in BOTH directions, and byte-invisible when off.

The fleet hot-path PRs put every performance leg behind a kill switch
whose off-path must stay byte-identical to the historical behavior
(``ClusterState.FOLD_INPLACE``, ``ExtenderScheduler.SCORE_INDEX``,
``AssumptionGC.WATERMARK``, ``SimEngine.NOCOPY_WRITES``,
``BaselinePolicy.delta_fold``, the fake API's ``nocopy_writes``
constructor switch).  That contract is only falsifiable while the off
path is actually reachable — a switch nobody reads, or one whose reads
all have a dead off-direction, silently stops being a switch.  This rule
audits the whole vocabulary:

- **Discovery**: a class-level plain ``NAME = True/False`` assignment
  whose attribute name is defined in exactly ONE class across the tree
  is a mode switch (the same attribute defined in several classes —
  ``Tracer.enabled`` / ``NullTracer.enabled`` — is polymorphic dispatch,
  not a switch, and is ignored).  Every discovered switch must be
  registered: centrally in :data:`SWITCH_REGISTRY` below, or in-file
  with a ``# kill-switch: <reason>`` directive on the assignment line.
- **Registry hygiene**: a registry entry whose definition vanished from
  its module is a dead entry — retire it in the same PR.
- **Liveness**: a switch with zero reads is dead weight; a switch whose
  reads never cover BOTH branch directions (an ``if FLAG:`` that is the
  last statement of its block with no else, a bare pass-through) has an
  unfalsifiable off-path.  A ternary / guarded-early-return / followed
  ``if`` covers both; so does delegating the value into ANOTHER
  registered switch's constructor keyword (``SimEngine.NOCOPY_WRITES``
  feeding ``FakeApiServer(nocopy_writes=...)`` — the ctor switch's own
  reads are audited instead).
- **Presence gating**: a counter incremented ONLY under a switch's
  positive arm must not be eagerly seeded in a literal counters dict —
  the seed makes the key appear (at 0) in off-path reports, so flipping
  the switch is no longer byte-invisible.  (Report-KEY additivity is the
  ``schema-additivity`` rule's half of this contract.)
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from tputopo.lint.core import Checker, Finding, Module

_DIRECTIVE_RE = re.compile(r"#\s*kill-switch:\s*(?P<reason>.*\S)")

#: The project's registered kill switches: (relpath, class qualname,
#: attribute).  The last entry is a CONSTRUCTOR switch — enabled per
#: instance via a keyword, audited through its ``self.<attr>`` reads.
SWITCH_REGISTRY: tuple[tuple[str, str, str], ...] = (
    ("tputopo/extender/state.py", "ClusterState", "FOLD_INPLACE"),
    ("tputopo/extender/scheduler.py", "ExtenderScheduler", "SCORE_INDEX"),
    ("tputopo/extender/gc.py", "AssumptionGC", "WATERMARK"),
    ("tputopo/sim/engine.py", "SimEngine", "NOCOPY_WRITES"),
    ("tputopo/sim/engine.py", "SimEngine", "BATCH_ADMISSION"),
    ("tputopo/sim/engine.py", "SimEngine", "FEASIBILITY_WATERMARK"),
    ("tputopo/extender/scheduler.py", "ExtenderScheduler",
     "VECTOR_GANG_PLAN"),
    ("tputopo/extender/scheduler.py", "ExtenderScheduler",
     "VECTOR_CAP_MEMO"),
    ("tputopo/extender/scheduler.py", "ExtenderScheduler", "DIRTY_FOLD"),
    ("tputopo/extender/scheduler.py", "ExtenderScheduler",
     "BIND_ANN_TEMPLATE"),
    ("tputopo/extender/scheduler.py", "ExtenderScheduler",
     "MASK_GANG_PROBE"),
    ("tputopo/extender/state.py", "ClusterState", "PA_CACHE"),
    ("tputopo/sim/engine.py", "SimEngine", "PLAN_STATE_REUSE"),
    ("tputopo/sim/engine.py", "SimEngine", "TIMELINE"),
    ("tputopo/sim/engine.py", "SimEngine", "ELASTIC"),
    ("tputopo/sim/policies.py", "BaselinePolicy", "delta_fold"),
    ("tputopo/k8s/fakeapi.py", "FakeApiServer", "nocopy_writes"),
)

#: Method names that record a counter by string literal — the presence-
#: gating check's increment vocabulary (shared with counter-drift's).
_INC_METHODS = frozenset({"inc", "inc_chaos", "_pcount"})


class _Switch:
    __slots__ = ("attr", "relpath", "cls", "line", "registered",
                 "reads", "covered")

    def __init__(self, attr, relpath, cls, line, registered):
        self.attr = attr
        self.relpath = relpath
        self.cls = cls
        self.line = line          # definition line (0 = not in this run)
        self.registered = registered
        self.reads: list[tuple[str, int]] = []   # (relpath, line)
        self.covered = False


class KillSwitchChecker(Checker):
    rule = "kill-switch-audit"
    description = ("class-level feature kill switches must be registered "
                   "(lint/switches.py SWITCH_REGISTRY or a # kill-switch: "
                   "directive), read with both branch directions live "
                   "(a dead off-path makes byte-identity unfalsifiable), "
                   "and must not eagerly seed switch-guarded counters")

    version = 1

    def __init__(self, registry=SWITCH_REGISTRY) -> None:
        self.registry = tuple(registry)
        self._mods: list[Module] = []

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("tputopo/")

    def check_module(self, mod: Module) -> Iterable[Finding]:
        self._mods.append(mod)
        return ()

    # ---- discovery ---------------------------------------------------------

    @staticmethod
    def _class_bool_assigns(mod: Module):
        """(class qualname, attr, line) for plain class-level boolean
        assignments (AnnAssign dataclass fields are config defaults, not
        mode switches)."""
        out = []

        def visit(body, qual):
            for node in body:
                if isinstance(node, ast.ClassDef):
                    visit(node.body, f"{qual}{node.name}.")
                elif isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, bool):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            out.append((qual.rstrip("."), t.id,
                                        node.lineno))

        visit(getattr(mod.tree, "body", []), "")
        return out

    def _discover(self, mods) -> tuple[dict[str, _Switch], list[Finding]]:
        findings: list[Finding] = []
        registered = {(rel, cls, attr) for rel, cls, attr in self.registry}
        switches: dict[str, _Switch] = {}
        # Pass 1: every class-level bool assignment, counted per attr so
        # polymorphic flag families (defined in >1 class) drop out.
        sites: dict[str, list] = {}
        for mod in mods:
            for cls, attr, line in self._class_bool_assigns(mod):
                sites.setdefault(attr, []).append((mod, cls, line))
        for attr, defs in sites.items():
            if len(defs) != 1:
                continue  # polymorphic dispatch family, not a switch
            mod, cls, line = defs[0]
            key = (mod.relpath, cls, attr)
            directive = _DIRECTIVE_RE.search(
                mod.comment_on_or_above(line))
            if key not in registered and directive is None:
                findings.append(Finding(
                    mod.relpath, line, 0, self.rule,
                    f"unregistered kill switch {cls}.{attr} — register "
                    "it in tputopo/lint/switches.py SWITCH_REGISTRY or "
                    "annotate the assignment with `# kill-switch: "
                    "<reason>` so its off-path stays audited"))
            switches[attr] = _Switch(attr, mod.relpath, cls, line,
                                     key in registered
                                     or directive is not None)
        # Pass 2: registry entries — constructor switches join the audit;
        # class-level entries whose definition vanished are dead.
        by_path = {m.relpath: m for m in mods}
        for rel, cls, attr in self.registry:
            if attr in switches:
                continue
            mod = by_path.get(rel)
            if mod is None:
                continue  # canonical module not in this run's file set
            if self._ctor_switch_line(mod, cls, attr) is not None:
                sw = _Switch(attr, rel, cls,
                             self._ctor_switch_line(mod, cls, attr), True)
                switches[attr] = sw
            else:
                findings.append(Finding(
                    rel, 1, 0, self.rule,
                    f"dead registry entry: SWITCH_REGISTRY names "
                    f"{cls}.{attr} but {rel} no longer defines it — "
                    "retire the entry in the same PR"))
        return switches, findings

    @staticmethod
    def _ctor_switch_line(mod: Module, cls: str, attr: str) -> int | None:
        """Line of a constructor-keyword switch: a ``<attr>`` parameter
        with a boolean default on the class's ``__init__``."""
        for node in mod.nodes():
            if not (isinstance(node, ast.ClassDef) and node.name
                    == cls.rsplit(".", 1)[-1]):
                continue
            for sub in node.body:
                if isinstance(sub, ast.FunctionDef) \
                        and sub.name == "__init__":
                    a = sub.args
                    params = list(a.posonlyargs) + list(a.args) \
                        + list(a.kwonlyargs)
                    for p in params:
                        if p.arg == attr:
                            return p.lineno
        return None

    # ---- read/branch analysis ----------------------------------------------

    @staticmethod
    def _reads_in(expr: ast.AST, attrs) -> set[str]:
        """Switch reads in an expression — ATTRIBUTE access only
        (``self.X`` / ``Cls.X``).  A bare Name matching a switch's
        attribute is almost always an unrelated local or parameter (the
        fakeapi constructor's ``nocopy_writes`` argument), and counting
        it would let a pass-through satisfy the liveness/coverage audit
        without any real branch read."""
        return {node.attr for node in ast.walk(expr)
                if isinstance(node, ast.Attribute) and node.attr in attrs}

    def _scan_reads(self, mod: Module,
                    switches: dict[str, _Switch]) -> None:
        attrs = set(switches)
        if not any(a in mod.source for a in attrs):
            return
        # Every read site (for liveness), every covering context, and
        # delegation into another registered switch's ctor keyword.
        for node in mod.nodes():
            if isinstance(node, ast.Attribute):
                sw = switches.get(node.attr)
                if sw is not None:
                    sw.reads.append((mod.relpath, node.lineno))
            if isinstance(node, (ast.IfExp, ast.While)):
                # A ternary always has both arms; a while-test's off
                # direction is the loop exit — both directions live.
                for name in self._reads_in(node.test, attrs):
                    switches[name].covered = True
        # Statement-level Ifs need sibling context (is the If the last
        # statement of its block?), so walk bodies structurally.
        self._scan_if_blocks(getattr(mod.tree, "body", []), attrs,
                             switches)
        # Delegation: passing switch X as the value of registered switch
        # Y's constructor keyword audits Y instead — X counts covered.
        # Judged against the registry's attribute names (not just this
        # run's discovered switches), so a scoped run still recognizes
        # the handoff into a constructor switch defined elsewhere.
        # A switch can NOT delegate into itself: `nocopy_writes=
        # nocopy_writes` at a construction site is the ctor switch being
        # set, not its off-path being consumed — its coverage must come
        # from its own branch reads.
        delegatable = attrs | {a for _, _, a in self.registry}
        for node in mod.nodes():
            if isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg in delegatable:
                        for name in self._reads_in(kw.value, attrs):
                            if name != kw.arg:
                                switches[name].covered = True

    def _scan_if_blocks(self, body: list, attrs, switches) -> None:
        for i, node in enumerate(body):
            if isinstance(node, ast.If):
                names = self._reads_in(node.test, attrs)
                if names:
                    covered = bool(node.body) and (
                        bool(node.orelse) or i < len(body) - 1)
                    if covered:
                        for name in names:
                            switches[name].covered = True
            for sub_body in self._sub_bodies(node):
                self._scan_if_blocks(sub_body, attrs, switches)

    @staticmethod
    def _sub_bodies(node: ast.AST):
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(node, field, None)
            if isinstance(sub, list):
                yield sub
        for h in getattr(node, "handlers", ()) or ():
            yield h.body

    # ---- presence gating ---------------------------------------------------

    def _eager_seeds(self, mod: Module) -> dict[str, int]:
        """Counter names eagerly seeded in a literal dict assigned to a
        ``self.<...counter...>`` attribute: {name: seed line}."""
        out: dict[str, int] = {}
        for node in mod.nodes():
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Dict)):
                continue
            for t in node.targets:
                if isinstance(t, ast.Attribute) \
                        and "count" in t.attr.lower():
                    for k in node.value.keys:
                        if isinstance(k, ast.Constant) \
                                and isinstance(k.value, str):
                            out.setdefault(k.value, k.lineno)
        return out

    def _guarded_incs(self, mod: Module, attrs) -> list[tuple[str, int]]:
        """(counter literal, line) for ``.inc("...")``-family calls in
        the POSITIVE arm of a switch conditional."""
        out: list[tuple[str, int]] = []

        def collect(stmts):
            for node in stmts:
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call) \
                            and isinstance(sub.func, ast.Attribute) \
                            and sub.func.attr in _INC_METHODS \
                            and sub.args \
                            and isinstance(sub.args[0], ast.Constant) \
                            and isinstance(sub.args[0].value, str):
                        out.append((sub.args[0].value, sub.lineno))

        def visit(body):
            for i, node in enumerate(body):
                if isinstance(node, ast.If) \
                        and self._reads_in(node.test, attrs):
                    negated = isinstance(node.test, ast.UnaryOp) \
                        and isinstance(node.test.op, ast.Not)
                    if not negated:
                        collect(node.body)
                        visit(node.orelse)
                    else:
                        collect(node.orelse)
                        visit(node.body)
                        # `if not FLAG: return ...` — the statements
                        # after the early exit ARE the positive arm.
                        if node.body and isinstance(
                                node.body[-1], (ast.Return, ast.Raise,
                                                ast.Continue, ast.Break)):
                            collect(body[i + 1:])
                    continue
                for sub_body in self._sub_bodies(node):
                    visit(sub_body)

        visit(getattr(mod.tree, "body", []))
        return out

    # ---- the analysis ------------------------------------------------------

    def finalize(self) -> Iterable[Finding]:
        mods, self._mods = self._mods, []
        switches, findings = self._discover(mods)
        yield from findings
        for mod in mods:
            self._scan_reads(mod, switches)
        for sw in sorted(switches.values(), key=lambda s: s.attr):
            if not sw.registered:
                continue  # already flagged as unregistered above
            if not sw.reads:
                yield Finding(
                    sw.relpath, sw.line or 1, 0, self.rule,
                    f"kill switch {sw.cls}.{sw.attr} is never read — a "
                    "switch nothing consults gates nothing; delete it "
                    "or wire the legs it was meant to guard")
            elif not sw.covered:
                path, line = sw.reads[0]
                yield Finding(
                    path, line, 0, self.rule,
                    f"kill switch {sw.cls}.{sw.attr} is read in only "
                    "one branch direction — the off-path is dead, so "
                    "the byte-identity contract is unfalsifiable; give "
                    "every leg a live both-ways branch (or delegate "
                    "into a registered constructor switch)")
        # Presence gating: switch-guarded counters vs eager seeds, per
        # module (seeds and incs live next to each other in this tree).
        attrs = set(switches)
        for mod in mods:
            if not any(a in mod.source for a in attrs):
                continue
            seeds = self._eager_seeds(mod)
            if not seeds:
                continue
            for name, line in self._guarded_incs(mod, attrs):
                if name in seeds:
                    yield Finding(
                        mod.relpath, line, 0, self.rule,
                        f"switch-guarded counter '{name}' is eagerly "
                        f"seeded (line {seeds[name]}) — the off-path "
                        "report emits the key at 0, so flipping the "
                        "switch is not byte-invisible; drop the seed "
                        "and let presence-gating carry it")
