"""TPU device plugin: node agent advertising chips to the kubelet and
injecting visibility env vars at Allocate (reference components 2.4/2.5/2.9,
design.md:57-86, 237-246)."""

from tputopo.deviceplugin.api import (  # noqa: F401
    Device,
    AllocateRequest,
    AllocateResponse,
    ContainerAllocateResponse,
    DeviceSpec,
    FakeKubelet,
)
from tputopo.deviceplugin.plugin import TpuDevicePlugin  # noqa: F401
from tputopo.deviceplugin.reporter import node_annotations_for_probe, node_object_for_probe  # noqa: F401
