# lint-corpus-relpath: tputopo/sim/report.py
"""KNOWN-BAD schema-additivity corpus (masquerading as the canonical
report module): a pinned key no builder emits any more, a feature-gated
key emitted unconditionally, and an inline version literal that never
became a contract constant."""

SCHEMA = "tputopo.sim/v2"

SCHEMA_KEY_MANIFEST = {
    "tputopo.sim/v2": {
        # BAD: 'removed_block' is pinned here but build_report below no
        # longer emits it — a consumer pinned to v2 just lost a key
        "top": ("schema", "policies", "removed_block"),
        "top_gated": ("throughput",),
        "policy": ("jobs",),
    },
}


def build_report(policies, throughput=None):
    out = {
        "schema": SCHEMA,
        "policies": policies,
    }
    # BAD: 'throughput' is feature-gated in the manifest but emitted
    # unconditionally — the feature-off report gains the key
    out["throughput"] = dict(throughput or {})
    return out


class MetricsCollector:
    def report(self):
        return {"jobs": 0}


def emit_next():
    # BAD: a new version literal typed inline instead of being routed
    # through a SCHEMA_* contract constant
    return {"schema": "tputopo.sim/v9"}
