"""Migration planner: fragmentation pressure detection + bounded-cost
eviction plans that restore a contiguous free box.

Pressure is *demand-relative*: a domain is fragmented when the pending
(or typical) gang shapes cannot place — no free box of the right shape
exists — while the domain holds enough free chips that compaction would
fit them.  The planner then searches the precomputed box vocabulary
(:func:`tputopo.topology.slices._boxes_for` masks — the same geometry
the allocator places with) for a target box whose occupants form the
*cheapest* evictable set: fewest chips moved, fewest jobs touched, best
restored bandwidth as the tiebreak, under a hard budget
(``max_moves`` jobs / ``max_chips_moved`` chips).  No candidate within
budget means **do nothing** — a plan is always optional.

Placeability is host-aware, not just chip-contiguous: a pod's chips
must live on one node, so a single-pod demand needs a box inside ONE
host, and a gang of ``r`` members needs a HOST-ALIGNED box (a union of
whole hosts — the host-grid box the gang planner binds into).  A
restored box that crosses host boundaries the wrong way would look free
and still place nothing; the planner never proposes one.
"""

from __future__ import annotations

from dataclasses import dataclass

from tputopo.extender.scheduler import (LABEL_ALLOW_MULTISLICE, _gang_of,
                                        _host_grid)
from tputopo.extender.state import ClusterState, SliceDomain
from tputopo.extender.state import list_pods_nocopy as list_pods_nocopy
from tputopo.k8s import objects as ko
from tputopo.topology.model import ChipTopology, Coord
from tputopo.topology.slices import (Allocator, _boxes_for, _chip_masks,
                                     _topo_key, chips_mask, enumerate_shapes)


@dataclass(frozen=True)
class Victim:
    """One running job (a whole gang, or a lone pod) the plan evicts.
    Gangs are atomic — evicting one member evicts them all — so the
    victim's cost counts every chip the job holds, in every domain."""

    key: str                       # "namespace/gang-id" or "namespace/pod"
    namespace: str
    gang_id: str | None
    pods: tuple[str, ...]          # member pod names, sorted
    chips_held: int                # total chips freed by evicting this job

    def describe(self) -> dict:
        return {"key": self.key, "namespace": self.namespace,
                "gang": self.gang_id, "pods": list(self.pods),
                "chips_held": self.chips_held}


@dataclass(frozen=True)
class MigrationPlan:
    """The cheapest within-budget eviction set restoring one target box."""

    slice_id: str
    demand: tuple[int, int]        # (replicas, chips_per_member) served
    target_dims: tuple[int, ...]
    box_chips: tuple[Coord, ...]
    box_mask: int
    victims: tuple[Victim, ...]
    chips_moved: int               # total chips the evicted jobs held
    chips_to_clear: int            # occupied chips inside the target box
    predicted_gbps: float          # bandwidth of the restored box
    # Checkpoint-charged disruption cost of the victim set, virtual
    # seconds (tputopo.elastic) — None when the plan was ranked by the
    # pre-elastic chips-moved key, which keeps every existing describe()
    # byte pinned.
    charged_cost_s: float | None = None

    def describe(self) -> dict:
        """JSON-safe plan record (the /debug/defrag and explain shape)."""
        out = {
            "slice": self.slice_id,
            "demand": {"replicas": self.demand[0],
                       "chips_per_member": self.demand[1]},
            "target_dims": list(self.target_dims),
            "box_chips": [list(c) for c in self.box_chips],
            "victims": [v.describe() for v in self.victims],
            "jobs_evicted": len(self.victims),
            "chips_moved": self.chips_moved,
            "chips_to_clear": self.chips_to_clear,
            "predicted_gbps": round(self.predicted_gbps, 3),
        }
        if self.charged_cost_s is not None:
            out["charged_cost_s"] = round(self.charged_cost_s, 6)
        return out


# ---- demand -----------------------------------------------------------------


def dedupe_demands(pairs) -> list[tuple[int, int]]:
    """Distinct (replicas, chips_per_member) demand shapes, largest total
    first (restoring the biggest box serves every smaller shape too)."""
    return sorted(set(pairs), key=lambda rk: (-(rk[0] * rk[1]), -rk[0]))


# list_pods_nocopy moved to tputopo.extender.state (the GC sweep shares
# it now); re-exported above for the existing defrag-side importers.


def pending_demand(pods) -> list[tuple[int, int]]:
    """Demand shapes of the Pending (unbound) pods: per gang, the
    REMAINING members still waiting to place (the scheduler extends a
    partially-bound gang — it never re-places the bound members, so a
    gang with 3 of 4 bound demands a 1-host box, not 4); ``(1, k)`` per
    lone pod.  Multislice-labeled gangs are excluded — they can split
    across domains, so no single contiguous box gates them.  Malformed
    gang labels are skipped (a hand-written pod must not wedge the
    planner)."""
    out: set[tuple[int, int]] = set()
    # (namespace, gang_id) -> [declared size, k, bound members seen]
    gangs: dict[tuple[str, str], list] = {}
    multislice: set[tuple[str, str]] = set()
    for p in pods:
        k = ko.pod_requested_chips(p)
        if k <= 0:
            continue
        md = p.get("metadata", {})
        meta = {**md.get("annotations", {}), **md.get("labels", {})}
        try:
            gang = _gang_of(p)
        except ValueError:
            continue
        bound = bool(p.get("spec", {}).get("nodeName"))
        if gang is None:
            if not bound and meta.get(LABEL_ALLOW_MULTISLICE) != "true":
                out.add((1, k))
            continue
        ns, gid, size = gang
        rec = gangs.setdefault((ns, gid), [size, k, 0])
        if bound:
            rec[2] += 1
        if meta.get(LABEL_ALLOW_MULTISLICE) == "true":
            multislice.add((ns, gid))
    for key, (size, k, bound) in gangs.items():
        if key in multislice:
            continue
        remaining = size - bound
        if remaining >= 1:
            out.add((remaining, k))
    return dedupe_demands(out)


def target_demands(state: ClusterState, chips: int) -> list[tuple[int, int]]:
    """Translate an explicit chip-volume target (``defrag_target_chips``,
    ``/debug/defrag?target=K``) into demand shapes: a within-host box
    where a host can hold it, else a gang of whole hosts — per domain,
    since chips-per-host varies across generations."""
    out: set[tuple[int, int]] = set()
    for dom in state.domains.values():
        cph = _chips_per_host(dom.topology)
        if chips <= cph:
            out.add((1, chips))
        else:
            out.add((-(-chips // cph), cph))
    return dedupe_demands(out)


# ---- placeable-box geometry -------------------------------------------------
#
# Cached per (topology value, dims, mode) like the allocator's own box
# tables: "chip" keeps only boxes inside ONE host (single-pod demand),
# "host" keeps only host-aligned boxes (gang demand — a union of whole
# hosts, i.e. a host-grid box).

_USABLE_CACHE: dict[tuple, list[tuple[tuple[Coord, ...], int, int]]] = {}


def _usable_boxes(topo: ChipTopology, dims: tuple[int, ...],
                  mode: str) -> list[tuple[tuple[Coord, ...], int, int]]:
    """[(chips, box_mask, neighbor_mask)] of the placeable boxes."""
    key = (_topo_key(topo), dims, mode)
    got = _USABLE_CACHE.get(key)
    if got is None:
        _, host_mask = _chip_masks(topo)
        got = []
        for _o, chips, mask, nbr in _boxes_for(topo, dims):
            if mode == "chip":
                i = (mask & -mask).bit_length() - 1
                if mask & ~host_mask[i]:
                    continue  # straddles hosts — one pod cannot hold it
            else:  # "host": every touched host fully inside the box
                union = 0
                m = mask
                while m:
                    b = m & -m
                    union |= host_mask[b.bit_length() - 1]
                    m &= ~union
                if union != mask:
                    continue
            got.append((chips, mask, nbr))
        _USABLE_CACHE[key] = got
    return got


def _chips_per_host(topo: ChipTopology) -> int:
    return topo.num_chips // max(1, topo.num_hosts)


def _demand_box(dom: SliceDomain,
                demand: tuple[int, int]) -> tuple[int, str] | None:
    """(box volume, mode) a demand needs in ``dom``, or None when the
    domain can never host it (too many replicas / chips per host)."""
    replicas, k = demand
    topo = dom.topology
    cph = _chips_per_host(topo)
    if k > cph or k < 1 or replicas < 1:
        return None
    if replicas == 1:
        return k, "chip"
    if replicas > topo.num_hosts:
        return None
    # A gang box is replicas WHOLE hosts: members take k <= cph chips
    # each, but the restored region must align to host boundaries.
    return replicas * cph, "host"


def placeable_free_box(dom: SliceDomain, demand: tuple[int, int]) -> bool:
    """True when ``demand`` can place in ``dom`` RIGHT NOW — judged with
    the placer's OWN search (per-host ``Allocator.find`` with its blob
    fallback; the gang planner's host-grid search for multi-replica
    demands), never a stricter geometric shortcut: pressure declared for
    a demand the scheduler could already place would evict running jobs
    for nothing.  The *restored-box target* stays box-shaped and
    host-aligned (that is the defrag goal); only this gate is
    placer-exact."""
    replicas, k = demand
    topo = dom.topology
    if (k < 1 or replicas < 1 or k > _chips_per_host(topo)
            or replicas > topo.num_hosts):
        return False
    alloc = dom.allocator
    free_mask = alloc.free_mask
    hosts: set[Coord] = set()
    for host in sorted(dom.node_by_host):
        node = dom.node_by_host[host]
        node_mask = dom.node_masks.get(node, 0)
        node_free = node_mask & free_mask
        if node_free.bit_count() < k:
            continue
        if alloc.find(k, free_mask=node_free,
                      within_mask=node_mask) is not None:
            if replicas == 1:
                return True
            hosts.add(host)
    if replicas == 1 or len(hosts) < replicas:
        return False
    # The gang planner's own host-grid search (scheduler._plan_gang):
    # prefer-a-box with connected-blob fallback over the feasible hosts.
    hb = topo.generation.host_bounds
    grid_dims = tuple(max(1, d // b) for d, b in zip(topo.dims, hb))
    host_grid = _host_grid(topo.generation, grid_dims, topo.wrap)
    host_alloc = Allocator(host_grid, alloc.cost)
    host_alloc.mark_used([h for h in host_grid.chips if h not in hosts])
    return host_alloc.find(replicas) is not None


# ---- pressure + planning ----------------------------------------------------


class _VictimRec:
    """Internal victim accumulator: per-domain chip masks + identity."""

    __slots__ = ("key", "namespace", "gang_id", "pods", "masks", "chips")

    def __init__(self, key: str, namespace: str, gang_id: str | None) -> None:
        self.key = key
        self.namespace = namespace
        self.gang_id = gang_id
        self.pods: set[str] = set()
        self.masks: dict[str, int] = {}
        self.chips = 0

    def to_victim(self) -> Victim:
        return Victim(key=self.key, namespace=self.namespace,
                      gang_id=self.gang_id, pods=tuple(sorted(self.pods)),
                      chips_held=self.chips)


def _victim_index(state: ClusterState) -> dict[str, _VictimRec]:
    """Evictable-unit index over the state's occupancy: one record per
    gang (all members — gangs are atomic) or lone pod, keyed
    "namespace/gang-id" / "namespace/pod-name".  Deterministic: built
    from the sorted occupancy records."""
    recs: dict[str, _VictimRec] = {}
    for ns, name, sid, held, gang_id, _assigned in state.occupancy_records():
        key = f"{ns}/{gang_id}" if gang_id else f"{ns}/{name}"
        rec = recs.get(key)
        if rec is None:
            rec = recs[key] = _VictimRec(key, ns, gang_id)
        rec.pods.add(name)
        dom = state.domains[sid]
        rec.masks[sid] = rec.masks.get(sid, 0) | chips_mask(dom.topology,
                                                            held)
        rec.chips += len(held)
    return recs


def pressure_report(state: ClusterState, demands: list[tuple[int, int]],
                    placeable: dict | None = None) -> dict:
    """Observability: per-domain free/largest-free-box plus, per demand
    shape, whether it can place anywhere right now — the /debug/defrag
    summary block.  ``placeable`` (a ``{demand: bool}`` map, e.g.
    :func:`plan_migration`'s ``placeable_out``) skips re-running the
    placer-exact scan the plan call already paid for."""
    domains = {}
    for sid in sorted(state.domains):
        dom = state.domains[sid]
        largest = dom.allocator.largest_free_box()
        domains[sid] = {
            "free_chips": dom.allocator.free_count,
            "largest_free_box": list(largest[1]) if largest else None,
        }
    out = {}
    for demand in demands:
        got = placeable.get(demand) if placeable is not None else None
        if got is None:
            got = any(placeable_free_box(state.domains[sid], demand)
                      for sid in sorted(state.domains))
        out[f"{demand[0]}x{demand[1]}"] = got
    return {"domains": domains, "demand_placeable": out}


def plan_migration(state: ClusterState, demands: list[tuple[int, int]], *,
                   max_moves: int = 2, max_chips_moved: int = 64,
                   pressured_out: list | None = None,
                   placeable_out: dict | None = None,
                   evictable=None,
                   require_free_capacity: bool = True,
                   cost_of=None) -> MigrationPlan | None:
    """The cheapest within-budget migration plan serving the largest
    pressured demand, or None (the do-nothing fallback).

    Per demand (largest first): skip it if it can place somewhere
    already; otherwise, in every domain with enough TOTAL free chips,
    scan the demand's usable-box vocabulary and cost each candidate box
    by the evictable units occupying it.  Boxes touching immovable
    occupancy (unhealthy chips, conflict leftovers) are infeasible, and
    a plan must be a NET contiguity gain: the chips it disturbs stay
    strictly below the box volume it restores (evicting one gang to seat
    another is churn, not defragmentation), whatever ``max_chips_moved``
    allows.  Ranking: fewest chips moved, fewest jobs, best restored-box
    bandwidth, most contact with already-free chips (the restored box
    should extend a free region, not open an isolated hole), then
    deterministic (box chips, domain id).

    ``pressured_out``, when given, collects the demand shapes found
    PRESSURED (not placeable anywhere, yet compaction-feasible in some
    domain) — whether or not a plan fit the budget, so the caller never
    re-runs this scan just to classify a None return.  ``placeable_out``
    likewise receives each demand's placeable-anywhere verdict (what
    :func:`pressure_report` consumes instead of rescanning).

    ``evictable`` (a predicate over the victim key, "namespace/gang-id"
    or "namespace/pod-name") restricts the victim universe: units
    failing it count as IMMOVABLE occupancy, so no box touching them is
    ever proposed — the priority planner (tputopo.priority) passes the
    strictly-lower-tier filter here and inherits every other rule
    (gang atomicity, net gain, budgets, ranking) unchanged.
    ``require_free_capacity=False`` drops the per-domain
    free-chips >= volume gate: defragmentation compacts (the chips must
    already exist free somewhere), preemption *frees* by evicting — the
    capacity comes from the victims themselves.

    ``cost_of`` (tputopo.elastic) reprices victims by what eviction
    *actually* destroys: a ``(key, chips_held) -> (charged_cost_s,
    destroyed_chips)`` callable (see
    :func:`tputopo.elastic.ckpt.victim_costs`).  When given, the ranking
    leads with the summed charged cost — cheap-restore victims win ties
    whatever volume they hold — and the net-gain rule debits the summed
    *work-bearing* chips instead of raw volume, so a gang that just
    checkpointed may be moved even when its raw chips match the restored
    box (``max_chips_moved`` still caps the raw disturbance).  None (the
    default) keeps the pre-elastic chips-moved key byte-for-byte."""
    victims = None  # built lazily — pressure usually absent
    for demand in demands:
        doms = [state.domains[sid] for sid in sorted(state.domains)]
        needs = {d.slice_id: _demand_box(d, demand) for d in doms}
        candidates = [d for d in doms if needs[d.slice_id] is not None]
        placeable = any(placeable_free_box(d, demand) for d in candidates)
        if placeable_out is not None:
            placeable_out[demand] = placeable
        if not candidates:
            continue
        if placeable:
            continue  # no pressure: the scheduler can place this now
        if pressured_out is not None and any(
                d.allocator.free_count >= needs[d.slice_id][0]
                for d in candidates):
            pressured_out.append(demand)
        best_key = None
        best_plan: MigrationPlan | None = None
        for dom in candidates:
            volume, mode = needs[dom.slice_id]
            alloc = dom.allocator
            if require_free_capacity and alloc.free_count < volume:
                continue  # compaction could not fit it either
            if victims is None:
                victims = _victim_index(state)
            by_chip: dict[int, _VictimRec] = {}
            movable = 0
            for rec in victims.values():
                if evictable is not None and not evictable(rec.key):
                    continue  # protected tier — counts as immovable below
                m = rec.masks.get(dom.slice_id, 0)
                movable |= m
                while m:
                    b = m & -m
                    m ^= b
                    by_chip[b.bit_length() - 1] = rec
            immovable = alloc.used_mask & ~movable
            free_mask = alloc.free_mask
            # Chips not covered by any PRESENT node (a failed/deleted
            # node's silicon): the allocator counts them free, but no pod
            # can ever land there — a box touching them would "restore"
            # capacity that cannot place (observed as zero-victim plans
            # on traces with node failures).
            present = 0
            for node in dom.host_by_node:
                present |= dom.node_masks.get(node, 0)
            # Net-gain budget: never disturb as many chips as the box
            # yields, whatever the configured ceiling allows.
            budget = min(max_chips_moved, volume - 1)
            for shape in enumerate_shapes(dom.topology, volume, alloc.cost):
                gbps = _shape_gbps(dom, shape.dims)
                for chips, mask, nbr in _usable_boxes(dom.topology,
                                                      shape.dims, mode):
                    if mask & ~present:
                        continue  # box touches absent-node silicon
                    occ = mask & alloc.used_mask
                    if not occ:
                        # A fully-free usable box contradicts the
                        # placeable gate — defensive: an empty eviction
                        # would still burn the cooldown for nothing.
                        continue
                    if occ & immovable:
                        continue
                    box_victims: dict[str, _VictimRec] = {}
                    m = occ
                    while m:
                        b = m & -m
                        m ^= b
                        rec = by_chip[b.bit_length() - 1]
                        box_victims[rec.key] = rec
                    if len(box_victims) > max_moves:
                        continue
                    moved = sum(r.chips for r in box_victims.values())
                    charged = None
                    if cost_of is None:
                        if moved > budget:
                            continue
                        head: tuple = (moved,)
                    else:
                        charged = destroyed = 0.0
                        for vk, rec in box_victims.items():
                            c_s, d_ch = cost_of(vk, rec.chips)
                            charged += c_s
                            destroyed += d_ch
                        # Net gain on ACTUAL destroyed work: checkpointed
                        # victims debit only their unsaved chips-worth;
                        # the raw-volume ceiling still bounds disturbance.
                        if destroyed > budget or moved > max_chips_moved:
                            continue
                        head = (round(charged, 6), moved)
                    free_contact = (nbr & free_mask).bit_count()
                    key = (*head, len(box_victims), -gbps, -free_contact,
                           chips, dom.slice_id)
                    if best_key is None or key < best_key:
                        best_key = key
                        best_plan = MigrationPlan(
                            slice_id=dom.slice_id,
                            demand=demand,
                            target_dims=shape.dims,
                            box_chips=chips,
                            box_mask=mask,
                            victims=tuple(
                                box_victims[k].to_victim()
                                for k in sorted(box_victims)),
                            chips_moved=moved,
                            chips_to_clear=occ.bit_count(),
                            predicted_gbps=gbps,
                            charged_cost_s=charged,
                        )
        if best_plan is not None:
            return best_plan
        # Largest demand pressured but unplannable within budget: fall
        # through to the next demand shape — a smaller box may be both
        # pressured and affordable.
    return None


def _shape_gbps(dom: SliceDomain, dims: tuple[int, ...]) -> float:
    from tputopo.topology.score import predict_allreduce_gbps

    return predict_allreduce_gbps(dom.topology, dims, dom.allocator.cost)
