"""Cluster state: the extender's in-memory world, rebuilt from the API
server on demand.

Keeps the reference's statelessness posture (SURVEY.md §5.4: "a restarted
extender rebuilds its world from the API server; no private state files"):
every sync reads node annotations (topology, component 2.5's output) and pod
annotations (assignments, component 2.9's output) and reconstructs
per-ICI-domain allocators.  An assumption older than the TTL that was never
confirmed does not count as occupancy — that is the GC semantics the
two-phase handshake needs (design.md:227-246; SURVEY.md §5.2).
"""

from __future__ import annotations

import json
import math
import time
from dataclasses import dataclass, field
from functools import lru_cache

from tputopo.k8s import objects as ko
from tputopo.k8s.fakeapi import FakeApiServer
from tputopo.topology.cost import LinkCostModel
from tputopo.topology.model import ChipTopology, Coord, parse_topology
from tputopo.topology.slices import Allocator


@dataclass
class PodAssignment:
    pod_name: str
    namespace: str
    node_name: str
    chips: list[Coord]
    assigned: bool
    assume_time: float
    gang_id: str | None


@lru_cache(maxsize=4096)
def _parse_chips_ann(s: str) -> tuple[Coord, ...]:
    """Node ANN_CHIPS JSON -> chip coords, memoized on the (stable)
    annotation string: every sync re-reads every node's chip list, which
    at fleet scale was ~10^5 json.loads per trace."""
    return tuple(tuple(int(x) for x in c["id"].split(","))
                 for c in json.loads(s))


def _assume_time_of(pod: dict) -> float:
    """Annotation timestamp, 0.0 when absent or malformed — a hand-written
    bad value must never crash sync (it just reads as long-expired).
    Non-finite values (nan/inf) count as malformed: nan would bypass the
    TTL comparison forever and inf would occupy chips eternally."""
    raw = pod["metadata"].get("annotations", {}).get(ko.ANN_ASSUME_TIME, "0")
    try:
        val = float(raw)
    except (TypeError, ValueError):
        return 0.0
    return val if math.isfinite(val) else 0.0


@dataclass
class SliceDomain:
    """One ICI domain: a set of nodes sharing a torus (same slice-id)."""

    slice_id: str
    topology: ChipTopology
    allocator: Allocator
    node_by_host: dict[Coord, str] = field(default_factory=dict)   # host coord -> node name
    host_by_node: dict[str, Coord] = field(default_factory=dict)
    chips_by_node: dict[str, list[Coord]] = field(default_factory=dict)
    assignments: list[PodAssignment] = field(default_factory=list)
    conflicts: list[PodAssignment] = field(default_factory=list)
    expired: list[PodAssignment] = field(default_factory=list)
    # Dead chips (node-reported health, ANN_UNHEALTHY) and the live
    # assignments whose groups overlap them — the scheduler half of the
    # health loop: never place onto these, surface who is stranded on them.
    unhealthy: set[Coord] = field(default_factory=set)
    on_unhealthy: list[PodAssignment] = field(default_factory=list)

    def node_of_chip(self, chip: Coord) -> str | None:
        host = self.topology.host_of(chip)
        return self.node_by_host.get(host)


class ClusterState:
    def __init__(self, api_server: FakeApiServer, *,
                 cost_for_generation=None, assume_ttl_s: float = 60.0,
                 clock=time.time) -> None:
        self.api = api_server
        self.assume_ttl_s = assume_ttl_s
        self.clock = clock
        self._cost_for_generation = cost_for_generation or (
            lambda gen: LinkCostModel.for_generation(gen))
        self.domains: dict[str, SliceDomain] = {}
        self.expired: list[PodAssignment] = []  # assumptions the TTL voided
        # Assignments whose chip groups overlap an earlier pod's (double-book
        # races, hand-written annotations) or name chips outside the slice.
        # Sync must tolerate them — a poisoned annotation would otherwise
        # wedge every verb AND the GC that could clean it up.
        self.conflicts: list[PodAssignment] = []
        self._dom_by_node: dict[str, SliceDomain] = {}

    # ---- sync (SURVEY.md §3.2: parse annotations -> in-memory model) -------

    def _list(self, kind: str) -> list[dict]:
        """List via the reader; sync only PARSES the objects (tuples/sets
        of its own are what it keeps), so copy-free readers (the informer
        mirror) are asked not to deepcopy."""
        try:
            return self.api.list(kind, copy=False)
        except TypeError:  # reader without a copy kwarg (fake/REST client)
            return self.api.list(kind)

    def sync(self) -> "ClusterState":
        self.domains = {}
        self.expired = []
        self.conflicts = []
        self._dom_by_node = {}
        for node in self._list("nodes"):
            anns = node["metadata"].get("annotations", {})
            if ko.ANN_TOPOLOGY not in anns or ko.ANN_SLICE_ID not in anns:
                continue  # not a TPU node
            slice_id = anns[ko.ANN_SLICE_ID]
            topo = parse_topology(anns[ko.ANN_TOPOLOGY])
            dom = self.domains.get(slice_id)
            if dom is None:
                cost = self._cost_for_generation(topo.generation.name)
                dom = SliceDomain(
                    slice_id=slice_id, topology=topo,
                    allocator=Allocator(topo, cost),
                )
                self.domains[slice_id] = dom
            elif dom.topology != topo:
                raise ValueError(
                    f"nodes of slice {slice_id!r} disagree on topology: "
                    f"{dom.topology.describe()} vs {topo.describe()}"
                )
            name = node["metadata"]["name"]
            host = tuple(int(x) for x in anns[ko.ANN_HOST_COORD].split(","))
            dom.node_by_host[host] = name
            dom.host_by_node[name] = host
            self._dom_by_node[name] = dom
            dom.chips_by_node[name] = list(
                _parse_chips_ann(anns.get(ko.ANN_CHIPS, "[]")))
            valid = dom.topology.chip_set
            dom.unhealthy.update(
                c for c in ko.ann_to_coords(anns.get(ko.ANN_UNHEALTHY, ""))
                if c in valid)  # a bogus coord must not wedge sync

        now = self.clock()
        valid_chips = {sid: set(dom.topology.chips)
                       for sid, dom in self.domains.items()}
        pods = sorted(
            self._list("pods"),
            key=lambda p: (
                _assume_time_of(p),
                p["metadata"].get("namespace", "default"),
                p["metadata"]["name"],
            ),
        )
        for pod in pods:
            anns = pod["metadata"].get("annotations", {})
            group = anns.get(ko.ANN_GROUP)
            node_name = pod["spec"].get("nodeName")
            if not group or not node_name:
                continue
            assigned = anns.get(ko.ANN_ASSIGNED) == "true"
            assume_time = _assume_time_of(pod)
            pa = PodAssignment(
                pod_name=pod["metadata"]["name"],
                namespace=pod["metadata"].get("namespace", "default"),
                node_name=node_name,
                chips=ko.ann_to_coords(group),
                assigned=assigned,
                assume_time=assume_time,
                gang_id=anns.get(ko.ANN_GANG_ID),
            )
            dom = self._domain_of_node(node_name)
            if dom is None:
                continue
            if not assigned and now - assume_time > self.assume_ttl_s:
                # Stale assumption: bind happened but Allocate never confirmed
                # within the TTL — the chips are NOT occupied (SURVEY.md §5.2).
                self.expired.append(pa)
                dom.expired.append(pa)
                continue
            dom.assignments.append(pa)
            valid = valid_chips[dom.slice_id]
            fresh = [c for c in dict.fromkeys(pa.chips)
                     if c in valid and c not in dom.allocator.used]
            if len(fresh) != len(pa.chips):
                # Overlap or out-of-slice chips: first pod keeps the chips,
                # later claimants are flagged (fragmentation_report surfaces
                # them; the operator or job controller resolves).
                self.conflicts.append(pa)
                dom.conflicts.append(pa)
            dom.allocator.mark_used(fresh)
            if any(c in dom.unhealthy for c in pa.chips):
                # Running (or promised) on silicon the node now reports
                # dead — surfaced for the job controller; chips stay
                # accounted to the pod until it is deleted/re-placed.
                dom.on_unhealthy.append(pa)
        # Dead chips are not placeable: mark the remainder used so no
        # selector, gang plan, or k=1 pick can touch them.
        for dom in self.domains.values():
            dom.allocator.mark_used(
                [c for c in dom.unhealthy if c not in dom.allocator.used])
        return self

    def _domain_of_node(self, node_name: str) -> SliceDomain | None:
        return self._dom_by_node.get(node_name)

    # ---- delta application (the bind fast path) ----------------------------

    def with_bind(self, pa: PodAssignment) -> "ClusterState":
        """A new state equal to this one plus one just-bound assignment —
        the extender's bind delta (VERDICT r3 #1: bind used to pay a full
        O(pods) cluster re-sync per call; applying its own delta to the
        informer-coherent derived state is O(chips)).

        Copy-on-write: the receiver and its domains are never mutated, so
        concurrently running sorts holding the old state keep a consistent
        snapshot; the caller atomically publishes the returned state.
        Raises ValueError when the assignment's chips are not free here
        (the caller falls back to a full re-sync)."""
        new = ClusterState.__new__(ClusterState)
        new.api = self.api
        new.assume_ttl_s = self.assume_ttl_s
        new.clock = self.clock
        new._cost_for_generation = self._cost_for_generation
        new.expired = list(self.expired)
        new.conflicts = list(self.conflicts)
        new.domains = {}
        new._dom_by_node = {}
        for sid, dom in self.domains.items():
            # Topology, node maps, chip lists, and the unhealthy set are
            # immutable after sync — shared; occupancy and assignment lists
            # are copied.  Per-state memos (gang plans, node scores) are
            # attribute-attached by the scheduler and deliberately NOT
            # carried over: the delta invalidates them.
            nd = SliceDomain(
                slice_id=sid, topology=dom.topology,
                allocator=dom.allocator.clone(),
                node_by_host=dom.node_by_host,
                host_by_node=dom.host_by_node,
                chips_by_node=dom.chips_by_node,
                assignments=list(dom.assignments),
                conflicts=list(dom.conflicts),
                expired=list(dom.expired),
                unhealthy=dom.unhealthy,
                on_unhealthy=list(dom.on_unhealthy),
            )
            new.domains[sid] = nd
            for node in nd.host_by_node:
                new._dom_by_node[node] = nd
        dom = new._dom_by_node.get(pa.node_name)
        if dom is None:
            raise ValueError(f"node {pa.node_name} not in any domain")
        dom.allocator.mark_used(pa.chips)  # raises if any chip is taken
        dom.assignments.append(pa)
        return new

    # ---- views -------------------------------------------------------------

    def domain_of_node(self, node_name: str) -> SliceDomain | None:
        return self._domain_of_node(node_name)

    def free_chips_on_node(self, node_name: str) -> list[Coord]:
        dom = self._domain_of_node(node_name)
        if dom is None:
            return []
        free = dom.allocator.free
        return [c for c in dom.chips_by_node.get(node_name, []) if c in free]

    def fragmentation_report(self) -> dict:
        """Observability: per-domain free/used and largest free box — the
        analog of Gaia's fragment-node bookkeeping (PDF §III.B)."""
        out = {}
        for sid, dom in self.domains.items():
            largest = dom.allocator.largest_free_box()
            out[sid] = {
                "topology": dom.topology.describe(),
                "free_chips": len(dom.allocator.free),
                "used_chips": len(dom.allocator.used),
                "largest_free_box": list(largest[1]) if largest else None,
                "expired_assumptions": len(dom.expired),
                "conflicting_assignments": [
                    f"{pa.namespace}/{pa.pod_name}" for pa in dom.conflicts
                ],
                "unhealthy_chips": sorted(map(list, dom.unhealthy)),
                "assignments_on_unhealthy": [
                    {"pod": f"{pa.namespace}/{pa.pod_name}",
                     "gang": pa.gang_id}
                    for pa in dom.on_unhealthy
                ],
            }
        return out
