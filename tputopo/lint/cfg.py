"""Per-function control-flow graphs over the repository's ASTs.

The PR-7/8 rules are lexical or flow-insensitive: ``locks.py`` matches
``with self._lock:`` blocks by nesting, ``nocopyflow.py`` walks
statements in AST order (so a rebind in one branch wrongly launders the
other), and nothing can ask "is this lock still held on the exception
path?".  This module gives every function a real CFG — branches, loops,
``with`` enter/exit, ``try``/``except``/``finally`` exception edges,
early returns, ``break``/``continue``, ``raise`` — that the
path-sensitive checkers (:mod:`lockset`, :mod:`releasepaths`,
:mod:`effects`) run dataflow over (:mod:`tputopo.lint.dataflow`).

Shape:

- A :class:`CFGNode` is one *simple* statement, a compound statement's
  header (an ``if``/``while`` test, a ``for`` iterator), a ``with``
  eval/enter/exit, a ``try`` handler entry, or a synthetic entry/exit.
  Compound bodies are linked by edges, not nested.
- **Exception edges**: any node whose statement can plausibly raise (it
  contains a call, a ``raise``, or an ``assert``) gets an edge to the
  innermost handlers — through every enclosing ``with``'s exit node
  (CPython runs ``__exit__`` on the way out, which is exactly what a
  lockset analysis must see: the lock is *released* on the exception
  path) and through ``finally`` bodies — ending at the shared
  :attr:`CFG.exit` when nothing catches.
- ``with`` is split into an **eval** node (the context expression — it
  can raise *before* acquisition) and an **enter** node (acquisition
  succeeded), plus one **exit** node every leaving edge funnels through.
- ``finally`` bodies are built once; their exits fan out to every
  continuation that entered them (normal fall-through, the unmatched-
  exception escape, return targets).  That merges facts conservatively —
  sound for the must-analyses built on top.

CFGs are built lazily per function and cached on the FunctionInfo via
:func:`cfg_for` (one build shared by every checker in a run).
"""

from __future__ import annotations

import ast
from typing import Iterable

__all__ = ["CFG", "CFGNode", "build_cfg", "cfg_for", "own_exprs",
           "walk_exprs"]


class CFGNode:
    """One CFG node.  ``kind`` is one of ``entry`` / ``exit`` / ``stmt``
    / ``test`` / ``handler`` / ``with_eval`` / ``with_enter`` /
    ``with_exit``; ``stmt`` carries the underlying AST node (None for
    entry/exit).  ``succs`` are normal-completion edges; ``esuccs`` are
    the this-node-raised edges — obligation checks must NOT count an
    acquire's own failure as a leaked path (the resource was never
    obtained), which is exactly the distinction the split preserves."""

    __slots__ = ("kind", "stmt", "succs", "esuccs", "idx")

    def __init__(self, kind: str, stmt: ast.AST | None, idx: int) -> None:
        self.kind = kind
        self.stmt = stmt
        self.succs: list[CFGNode] = []
        self.esuccs: list[CFGNode] = []
        self.idx = idx  # creation order — stable ids for tests/messages

    def link(self, other: "CFGNode") -> None:
        if other not in self.succs:
            self.succs.append(other)

    def elink(self, other: "CFGNode") -> None:
        if other not in self.esuccs:
            self.esuccs.append(other)

    def all_succs(self) -> list["CFGNode"]:
        return self.succs + self.esuccs

    @property
    def line(self) -> int:
        return getattr(self.stmt, "lineno", 0)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<CFGNode {self.idx} {self.kind} L{self.line}>"


def _can_raise(node: ast.AST) -> bool:
    """Conservative: a statement that contains a call, ``raise`` or
    ``assert`` may transfer to the innermost handler.  Pure
    name/constant shuffling is treated as non-raising — precise enough
    for release-on-all-paths, and it keeps the graphs small."""
    if isinstance(node, (ast.Raise, ast.Assert)):
        return True
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            return True
    return False


class _Frame:
    """Per-construct context the builder threads through recursion."""

    __slots__ = ("exc_targets", "break_to", "continue_to", "return_to")

    def __init__(self, exc_targets, break_to, continue_to, return_to):
        self.exc_targets = exc_targets    # list[CFGNode]: where raises go
        self.break_to = break_to          # list collecting break nodes
        self.continue_to = continue_to    # CFGNode or None
        self.return_to = return_to        # CFGNode: cfg.exit or a finally


class CFG:
    """The graph: ``entry`` -> ... -> ``exit`` (one shared exit for
    returns, fall-through, AND escaping exceptions — every obligation
    checker cares that all of them release)."""

    def __init__(self) -> None:
        self.nodes: list[CFGNode] = []
        self.entry = self._new("entry", None)
        self.exit = self._new("exit", None)

    def _new(self, kind: str, stmt: ast.AST | None) -> CFGNode:
        n = CFGNode(kind, stmt, len(self.nodes))
        self.nodes.append(n)
        return n

    # ---- queries -----------------------------------------------------------

    def preds_map(self) -> dict[CFGNode, list[CFGNode]]:
        out: dict[CFGNode, list[CFGNode]] = {n: [] for n in self.nodes}
        for n in self.nodes:
            for s in n.all_succs():
                out[s].append(n)
        return out

    def reachable_without(self, start: CFGNode, stop) -> bool:
        """True when :attr:`exit` is reachable from ``start`` along a
        path whose nodes (``start`` excluded) never satisfy ``stop`` —
        the release-on-all-paths query.  ``start``'s own exception
        edges are excluded: the obligation only exists once the
        acquiring statement COMPLETED."""
        seen = {id(start)}
        work = list(start.succs)
        while work:
            n = work.pop()
            if id(n) in seen:
                continue
            seen.add(id(n))
            if n is self.exit:
                return True
            if stop(n):
                continue
            work.extend(n.all_succs())
        return False


class _Builder:
    def __init__(self, cfg: CFG) -> None:
        self.cfg = cfg

    def build(self, body: list, frame: _Frame,
              frontier: list[CFGNode]) -> list[CFGNode]:
        """Wire ``body`` after ``frontier``; returns the fall-through
        frontier (nodes whose next edge is the statement after the
        construct)."""
        for stmt in body:
            frontier = self.stmt(stmt, frame, frontier)
            if not frontier:
                break  # everything returned/raised/broke
        return frontier

    def _join(self, frontier: Iterable[CFGNode], node: CFGNode) -> None:
        for f in frontier:
            f.link(node)

    def _raise_edges(self, node: CFGNode, frame: _Frame) -> None:
        for t in frame.exc_targets:
            node.elink(t)

    def stmt(self, stmt: ast.AST, frame: _Frame,
             frontier: list[CFGNode]) -> list[CFGNode]:
        cfg = self.cfg
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            n = cfg._new("stmt", stmt)  # the *definition* executes; its
            self._join(frontier, n)     # body is a separate function
            return [n]
        if isinstance(stmt, ast.Return):
            n = cfg._new("stmt", stmt)
            self._join(frontier, n)
            if stmt.value is not None and _can_raise(stmt.value):
                self._raise_edges(n, frame)
            n.link(frame.return_to)
            return []
        if isinstance(stmt, ast.Raise):
            n = cfg._new("stmt", stmt)
            self._join(frontier, n)
            self._raise_edges(n, frame)
            return []
        if isinstance(stmt, ast.Break):
            n = cfg._new("stmt", stmt)
            self._join(frontier, n)
            if frame.break_to is not None:
                frame.break_to.append(n)
            return []
        if isinstance(stmt, ast.Continue):
            n = cfg._new("stmt", stmt)
            self._join(frontier, n)
            if frame.continue_to is not None:
                n.link(frame.continue_to)
            return []
        if isinstance(stmt, ast.If):
            test = cfg._new("test", stmt)
            self._join(frontier, test)
            if _can_raise(stmt.test):
                self._raise_edges(test, frame)
            out = self.build(stmt.body, frame, [test])
            if stmt.orelse:
                out = out + self.build(stmt.orelse, frame, [test])
            else:
                out = out + [test]  # condition false, no else
            return out
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            head = cfg._new("test", stmt)
            self._join(frontier, head)
            head_expr = stmt.test if isinstance(stmt, ast.While) \
                else stmt.iter
            if _can_raise(head_expr):
                self._raise_edges(head, frame)
            breaks: list[CFGNode] = []
            inner = _Frame(frame.exc_targets, breaks, head, frame.return_to)
            body_out = self.build(stmt.body, inner, [head])
            self._join(body_out, head)  # loop back
            out = list(breaks)
            # Loop may run zero times / exhaust -> else -> fall through.
            if stmt.orelse:
                out = out + self.build(stmt.orelse, frame, [head])
            else:
                out = out + [head]
            return out
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._with(stmt, frame, frontier)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, frame, frontier)
        # Simple statement.
        n = cfg._new("stmt", stmt)
        self._join(frontier, n)
        if _can_raise(stmt):
            self._raise_edges(n, frame)
        return [n]

    def _with(self, stmt, frame: _Frame,
              frontier: list[CFGNode]) -> list[CFGNode]:
        cfg = self.cfg
        ev = cfg._new("with_eval", stmt)    # context exprs
        self._join(frontier, ev)            # BEFORE acquisition
        # Raise edge only when a context expr itself contains a call
        # (``with tr.phase("x"):``).  A bare ``with self._lock:`` is
        # treated as non-raising — flagging every manual acquire that
        # merely SPANS a lock block would drown the real leaks.
        if any(_can_raise(item.context_expr) for item in stmt.items):
            self._raise_edges(ev, frame)
        enter = cfg._new("with_enter", stmt)
        ev.link(enter)
        # ``__exit__`` runs on EVERY way out — but each way continues
        # somewhere DIFFERENT, so each leave kind gets its own exit
        # node (all kind "with_exit": a lockset transfer releases on
        # any of them).  One shared exit node fabricated paths (a
        # pass-through body appeared to reach the function exit
        # directly), which falsely tripped release-on-all-paths on
        # correctly paired acquires spanning a with.  Unused exits stay
        # unreachable orphans — harmless to every analysis.
        ex_norm = cfg._new("with_exit", stmt)   # fall-through
        ex_exc = cfg._new("with_exit", stmt)    # unwinding a raise
        for t in frame.exc_targets:
            ex_exc.link(t)
        ex_ret = cfg._new("with_exit", stmt)    # unwinding a return
        ex_ret.link(frame.return_to)
        ex_cont = cfg._new("with_exit", stmt)   # unwinding a continue
        if frame.continue_to is not None:
            ex_cont.link(frame.continue_to)
        breaks: list[CFGNode] = []
        inner = _Frame([ex_exc], breaks, ex_cont, ex_ret)
        body_out = self.build(stmt.body, inner, [enter])
        self._join(body_out, ex_norm)
        if breaks:                               # unwinding a break
            ex_brk = cfg._new("with_exit", stmt)
            self._join(breaks, ex_brk)
            if frame.break_to is not None:
                frame.break_to.append(ex_brk)
        return [ex_norm] if body_out else []

    def _try(self, stmt: ast.Try, frame: _Frame,
             frontier: list[CFGNode]) -> list[CFGNode]:
        cfg = self.cfg
        if stmt.finalbody:
            # One finally COPY per continuation kind, same reasoning as
            # the per-leave with exits: a single shared finally whose
            # exits fan out to every continuation fabricates paths (a
            # plain fall-through appeared to reach the function exit),
            # and routing break/continue around it entirely modeled
            # finally-released locks as leaked.  Unused copies are
            # unreachable orphans — harmless.
            fin_frame = _Frame(frame.exc_targets, frame.break_to,
                               frame.continue_to, frame.return_to)

            def fin(link_outs) -> CFGNode:
                entry = cfg._new("stmt", stmt)
                link_outs(self.build(stmt.finalbody, fin_frame, [entry]))
                return entry

            after: list[CFGNode] = []
            fin_norm = fin(after.extend)
            fin_exc = fin(lambda outs: [o.link(t) for o in outs
                                        for t in frame.exc_targets])
            fin_ret = fin(lambda outs: [o.link(frame.return_to)
                                        for o in outs])
            local_breaks: list[CFGNode] | None = None
            if frame.break_to is not None:
                fin_brk = fin(frame.break_to.extend)
                local_breaks = []
            fin_cont = None
            if frame.continue_to is not None:
                fin_cont = fin(lambda outs: [o.link(frame.continue_to)
                                             for o in outs])
            exc_escape: list[CFGNode] = [fin_exc]
            inner_return_to = fin_ret
            inner_break_to = local_breaks
            inner_continue_to = fin_cont
        else:
            fin_norm = None
            exc_escape = list(frame.exc_targets)
            inner_return_to = frame.return_to
            inner_break_to = frame.break_to
            inner_continue_to = frame.continue_to
            after = []
        handler_nodes = [cfg._new("handler", h) for h in stmt.handlers]
        # Raises in the try body dispatch to every handler (we cannot
        # statically match exception types) or escape unmatched.
        body_frame = _Frame(handler_nodes + exc_escape, inner_break_to,
                            inner_continue_to, inner_return_to)
        body_out = self.build(stmt.body, body_frame, frontier)
        # else runs only after a raise-free body — its own raises are
        # NOT caught by this try's handlers.
        escape_frame = _Frame(exc_escape, inner_break_to,
                              inner_continue_to, inner_return_to)
        if stmt.orelse:
            body_out = self.build(stmt.orelse, escape_frame, body_out)
        # Handler bodies: raises inside a handler escape the construct
        # (through finally when present).
        handler_outs: list[CFGNode] = []
        for hn, h in zip(handler_nodes, stmt.handlers):
            handler_outs += self.build(h.body, escape_frame, [hn])
        normal_out = body_out + handler_outs
        if fin_norm is not None:
            self._join(normal_out, fin_norm)
            if local_breaks:
                self._join(local_breaks, fin_brk)
            return after if normal_out else []
        return normal_out


def build_cfg(fn_node: ast.AST) -> CFG:
    """The CFG of one ``def``'s own body (nested defs are opaque
    single nodes — they are separate functions)."""
    cfg = CFG()
    frame = _Frame([cfg.exit], None, None, cfg.exit)
    out = _Builder(cfg).build(list(getattr(fn_node, "body", [])),
                              frame, [cfg.entry])
    for n in out:
        n.link(cfg.exit)
    return cfg


def cfg_for(fn) -> CFG:
    """Build-once CFG cache on a callgraph FunctionInfo: the three
    path-sensitive checkers in a run share one graph per function."""
    got = getattr(fn, "_cfg", None)
    if got is None:
        got = fn._cfg = build_cfg(fn.node)
    return got


def own_exprs(node: CFGNode) -> list:
    """The AST fragments a CFG node itself evaluates (compound bodies
    are separate nodes; nested function bodies never run here)."""
    s = node.stmt
    if s is None:
        return []
    if node.kind == "test":
        if isinstance(s, (ast.If, ast.While)):
            return [s.test]
        if isinstance(s, (ast.For, ast.AsyncFor)):
            return [s.iter, s.target]
        return []
    if node.kind == "with_eval":
        out = []
        for item in s.items:
            out.append(item.context_expr)
            if item.optional_vars is not None:
                out.append(item.optional_vars)
        return out
    if node.kind in ("with_enter", "with_exit"):
        return []
    if node.kind == "handler":
        return [s.type] if getattr(s, "type", None) is not None else []
    if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                      ast.Try)):
        return []  # opaque definition / structural anchor
    return [s]


def walk_exprs(node: CFGNode):
    """Every AST node the CFG node evaluates, nested scopes excluded."""
    stack = list(own_exprs(node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))
