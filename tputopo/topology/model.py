"""In-memory topology model: chip coordinates on an ICI mesh/torus.

TPU-native replacement for the reference's pairwise matrix
``gpuTopology map[uint]map[uint]gpuTopologyType`` (design.md:61-74).  The GPU
design must *discover* an irregular PCIe/NVLink hierarchy pairwise; a TPU
slice is a regular torus, so the model is a coordinate grid plus an axis
wrap mask, and every pairwise property (hop distance, link class) is derived
analytically rather than stored.

The reference's convention that a 1-GPU node reports no topology at all
(design.md:17-19) maps here to a 1-chip topology with no ICI links — it is
still representable (``num_chips == 1``) because the device plugin must be
able to advertise single-chip hosts (BASELINE config 1).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import cached_property, lru_cache

from tputopo.topology.generations import TpuGeneration, get_generation

Coord = tuple[int, ...]


@dataclass(frozen=True)
class ChipTopology:
    """A concrete slice/pod topology: a box of chips with optional wraparound.

    Attributes:
        generation: the TPU generation spec.
        dims: extent in chips along each axis (e.g. ``(2, 2, 4)``).
        wrap: per-axis torus wraparound.  By default an axis wraps iff the
            slice spans the generation's full pod extent on that axis
            (``TpuGeneration.wrap_when_full``).
    """

    generation: TpuGeneration
    dims: tuple[int, ...]
    wrap: tuple[bool, ...]

    @staticmethod
    def build(generation: str | TpuGeneration, dims: tuple[int, ...],
              wrap: tuple[bool, ...] | None = None) -> "ChipTopology":
        gen = get_generation(generation) if isinstance(generation, str) else generation
        if len(dims) != gen.ndims:
            raise ValueError(
                f"{gen.name} is {gen.ndims}-D; got dims {dims}"
            )
        for d, m in zip(dims, gen.max_dims):
            if d < 1 or d > m:
                raise ValueError(f"dims {dims} out of range for {gen.name} (max {gen.max_dims})")
        if wrap is None:
            wrap = tuple(
                gen.wrap_when_full and d == m and d > 2
                for d, m in zip(dims, gen.max_dims)
            )
        elif len(wrap) != gen.ndims:
            raise ValueError(f"wrap mask {wrap} must have {gen.ndims} axes")
        return ChipTopology(gen, tuple(dims), tuple(wrap))

    @property
    def num_chips(self) -> int:
        return math.prod(self.dims)

    @cached_property
    def chips(self) -> list[Coord]:
        """All chip coordinates in row-major order (also the device index order)."""
        coords: list[Coord] = [()]
        for d in self.dims:
            coords = [c + (i,) for c in coords for i in range(d)]
        return coords

    @cached_property
    def chip_set(self) -> frozenset[Coord]:
        """Membership view of :attr:`chips` — validity checks in the
        allocator hot path run per mark_used call, and rebuilding the set
        each time measured ~0.7 s across one fleet-scale trace."""
        return frozenset(self.chips)

    def index(self, coord: Coord) -> int:
        """Row-major flat index of a coordinate — the stable device id."""
        idx = 0
        for c, d in zip(coord, self.dims):
            if not (0 <= c < d):
                raise ValueError(f"coord {coord} outside dims {self.dims}")
            idx = idx * d + c
        return idx

    def coord(self, index: int) -> Coord:
        if not (0 <= index < self.num_chips):
            raise ValueError(f"index {index} outside 0..{self.num_chips - 1}")
        out = []
        for d in reversed(self.dims):
            out.append(index % d)
            index //= d
        return tuple(reversed(out))

    def neighbors(self, coord: Coord) -> list[Coord]:
        """ICI-adjacent chips (±1 along each axis, honoring wraparound)."""
        try:
            return self.neighbor_map[coord]
        except KeyError:
            return self._neighbors_uncached(coord)

    @cached_property
    def neighbor_map(self) -> dict[Coord, list[Coord]]:
        """Precomputed adjacency — the sort hot loop asks for neighbors of
        every free chip on every node per verb, which at 256-node fleet
        scale is tens of thousands of lookups per scheduling cycle."""
        return {c: self._neighbors_uncached(c) for c in self.chips}

    def _neighbors_uncached(self, coord: Coord) -> list[Coord]:
        out: list[Coord] = []
        for ax, (d, w) in enumerate(zip(self.dims, self.wrap)):
            if d == 1:
                continue
            for step in (-1, 1):
                c = coord[ax] + step
                if 0 <= c < d:
                    out.append(coord[:ax] + (c,) + coord[ax + 1:])
                elif w:
                    out.append(coord[:ax] + (c % d,) + coord[ax + 1:])
        # d == 2 with wrap would produce the same neighbor twice; dedupe.
        seen: set[Coord] = set()
        uniq = []
        for c in out:
            if c not in seen:
                seen.add(c)
                uniq.append(c)
        return uniq

    def hop_distance(self, a: Coord, b: Coord) -> int:
        """Minimal ICI hop count between two chips (Manhattan on the torus)."""
        hops = 0
        for ax, (d, w) in enumerate(zip(self.dims, self.wrap)):
            delta = abs(a[ax] - b[ax])
            hops += min(delta, d - delta) if w else delta
        return hops

    def host_of(self, coord: Coord) -> Coord:
        """Host coordinate for a chip — chips grouped by ``host_bounds``.

        Analog of the reference's CPU-affinity grouping used as the k=1
        tiebreak (design.md:145-146): same host == same NUMA/DCN attachment.
        """
        got = self.host_map.get(coord)
        if got is not None:
            return got
        hb = self.generation.host_bounds
        return tuple(c // b for c, b in zip(coord, hb))

    @cached_property
    def host_map(self) -> dict[Coord, Coord]:
        """Precomputed chip -> host lookup (the k=1 Singular tiebreak reads
        it per free chip per verb)."""
        hb = self.generation.host_bounds
        return {c: tuple(x // b for x, b in zip(c, hb)) for c in self.chips}

    @cached_property
    def hosts(self) -> dict[Coord, list[Coord]]:
        out: dict[Coord, list[Coord]] = {}
        for c in self.chips:
            out.setdefault(self.host_of(c), []).append(c)
        return out

    @property
    def num_hosts(self) -> int:
        return len(self.hosts)

    def links(self) -> list[tuple[Coord, Coord]]:
        """All ICI links, each undirected edge once, as sorted coordinate pairs."""
        out: list[tuple[Coord, Coord]] = []
        seen: set[frozenset] = set()
        for c in self.chips:
            for n in self.neighbors(c):
                e = frozenset((c, n))
                if e not in seen:
                    seen.add(e)
                    lo, hi = sorted((c, n))
                    out.append((lo, hi))
        return out

    def describe(self) -> str:
        w = "x".join(str(d) for d in self.dims)
        return f"{self.generation.name} {w} ({self.num_chips} chips, {self.num_hosts} hosts)"


@lru_cache(maxsize=512)
def parse_topology(spec: str) -> ChipTopology:
    """Parse ``"v5p:2x2x4"`` (with optional ``:wrap=101`` axis mask) into a topology.

    This string form is what the device plugin publishes in node annotations
    (the analog of the reference's per-edge ``GPU_<ABBR>_<i>_<j>`` annotation
    scheme, design.md:76-82 — a torus is described by its shape, not edges).

    Cached: every node of a slice publishes the same spec, so a cluster
    sync would otherwise rebuild the same frozen topology (and its derived
    chips/hosts/neighbor tables) once per node.  Safe because ChipTopology
    is frozen and all its cached derivations are value-determined.
    """
    parts = spec.split(":")
    if len(parts) < 2:
        raise ValueError(f"bad topology spec {spec!r}; want 'gen:AxBxC[:wrap=mask]'")
    gen = parts[0]
    dims = tuple(int(x) for x in parts[1].split("x"))
    wrap = None
    for extra in parts[2:]:
        if extra.startswith("wrap="):
            mask = extra[len("wrap="):]
            if not mask or set(mask) - {"0", "1"}:
                raise ValueError(f"bad wrap mask {mask!r}; want e.g. wrap=110")
            wrap = tuple(ch == "1" for ch in mask)
        else:
            raise ValueError(f"unknown topology spec field {extra!r}")
    return ChipTopology.build(gen, dims, wrap)


def format_topology(t: ChipTopology) -> str:
    dims = "x".join(str(d) for d in t.dims)
    wrap = "".join("1" if w else "0" for w in t.wrap)
    return f"{t.generation.name}:{dims}:wrap={wrap}"
