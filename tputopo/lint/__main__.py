"""CLI for the contract linter.

Usage::

    python -m tputopo.lint [paths...] [--root DIR] [--select r1,r2]
                           [--show-waived] [--list-rules]

Exit codes: 0 = clean, 1 = findings, 2 = usage error.  With no paths the
default file set is every ``.py`` under ``tputopo/`` and ``tests/``
(excluding generated ``*_pb2.py``), which is also what the CI lint job
runs.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

from tputopo.lint import default_checkers, find_repo_root, run_lint
from tputopo.lint.core import PARSE_RULE, WAIVER_RULE


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tputopo.lint",
        description="Project-contract static analysis "
                    "(determinism / clock / nocopy / lock / single-def).")
    parser.add_argument("paths", nargs="*",
                        help="files or directories (default: tputopo/ "
                             "and tests/ under the repo root)")
    parser.add_argument("--root", type=Path, default=None,
                        help="repository root (default: auto-detect)")
    parser.add_argument("--select", default=None,
                        help="comma-separated rule ids to run "
                             "(default: all)")
    parser.add_argument("--show-waived", action="store_true",
                        help="also print findings suppressed by waivers")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        # argparse exits 2 on usage errors and 0 on --help; keep both.
        return int(e.code or 0)

    checkers = default_checkers()
    if args.list_rules:
        meta = [(WAIVER_RULE, "waiver syntax: reason required, rules must "
                              "exist, unused waivers flagged"),
                (PARSE_RULE, "files must parse")]
        for rule, desc in [(c.rule, c.description) for c in checkers] + meta:
            print(f"{rule:12s} {desc}")
        return 0
    if args.select is not None:
        wanted = {r.strip() for r in args.select.split(",") if r.strip()}
        known = {c.rule for c in checkers}
        unknown = wanted - known
        if unknown:
            print(f"error: unknown rule(s) {sorted(unknown)}; "
                  f"known: {sorted(known)}", file=sys.stderr)
            return 2
        checkers = [c for c in checkers if c.rule in wanted]

    root = find_repo_root(args.root)
    for p in args.paths:
        ap = (root / p) if not Path(p).is_absolute() else Path(p)
        if not ap.exists():
            print(f"error: no such path: {p}", file=sys.stderr)
            return 2

    t0 = time.perf_counter()
    findings, run = run_lint(root=root, paths=args.paths, checkers=checkers)
    dt = time.perf_counter() - t0
    for f in findings:
        print(f.render())
    if args.show_waived:
        for f in run.waived:
            print(f"[waived] {f.render()}")
    n_files = len(run.modules)
    print(f"tputopo.lint: {len(findings)} finding(s), "
          f"{len(run.waived)} waived, {n_files} files, {dt:.2f}s",
          file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
