"""Token-stream data loading (tputopo.workloads.data): deterministic,
disjoint-by-construction rank shards, exact resume, static shapes."""

import numpy as np
import pytest

from tputopo.workloads.data import (TokenDataset, batch_iterator,
                                    steps_per_epoch, write_tokens)


@pytest.fixture
def corpus(tmp_path):
    path = str(tmp_path / "tokens.bin")
    rng = np.random.default_rng(0)
    write_tokens(path, rng.integers(0, 1000, 4096))
    return TokenDataset(path)


def test_roundtrip_and_shapes(corpus):
    assert len(corpus) == 4096
    b = corpus.batch(0, batch=4, seq=16)
    assert b.shape == (4, 16) and b.dtype == np.int32
    assert corpus.max_token() < 1000


def test_write_rejects_overflow(tmp_path):
    with pytest.raises(ValueError, match="do not fit"):
        write_tokens(str(tmp_path / "t.bin"), [0, 70000], "uint16")


def test_batches_are_deterministic_and_resumable(corpus):
    a = corpus.batch(7, batch=4, seq=16, seed=3)
    b = corpus.batch(7, batch=4, seq=16, seed=3)
    np.testing.assert_array_equal(a, b)
    # Iterator resume from a checkpointed step replays the schedule.
    it = batch_iterator(corpus, 4, 16, start_step=7, seed=3)
    np.testing.assert_array_equal(next(it), a)


def test_rank_shards_are_disjoint_within_a_step(corpus):
    """world ranks draw disjoint windows in every step — the property
    that lets a dp gang load with zero coordination."""
    seq, batch, world = 16, 4, 4
    for step in range(3):
        seen: set[tuple] = set()
        for rank in range(world):
            b = corpus.batch(step, batch, seq, rank=rank, world=world,
                             seed=1)
            for row in b:
                key = tuple(row.tolist())
                assert key not in seen, f"window repeated in step {step}"
                seen.add(key)


def test_epoch_covers_all_windows_once(corpus):
    """Within one epoch every non-overlapping window appears at most once
    across all steps and ranks (a permutation, not sampling)."""
    seq, batch, world = 16, 8, 2
    spe = steps_per_epoch(corpus, batch, seq, world)
    starts: set[int] = set()
    toks = np.asarray(corpus.tokens)
    window_of = {toks[i * seq:(i + 1) * seq].tobytes(): i
                 for i in range(corpus.n_windows(seq))}
    for step in range(spe):
        for rank in range(world):
            for row in corpus.batch(step, batch, seq, rank=rank,
                                    world=world, seed=2):
                w = window_of[row.astype(corpus.tokens.dtype).tobytes()]
                assert w not in starts
                starts.add(w)
    assert len(starts) == spe * world * batch


def test_seed_epoch_pairs_do_not_collide(corpus):
    """(seed=1, epoch=0) must not replay (seed=0, epoch=1)'s permutation:
    the old key=seed+epoch folding made nominally independent runs replay
    each other's epoch schedules shifted by one (ADVICE r5)."""
    import numpy as np

    a = corpus._perm(128, seed=1, epoch=0)
    b = corpus._perm(128, seed=0, epoch=1)
    assert not np.array_equal(a, b)


def test_epoch_rollover_reshuffles(corpus):
    seq, batch = 16, 4
    spe = steps_per_epoch(corpus, batch, seq)
    first = corpus.batch(0, batch, seq, seed=5)
    again = corpus.batch(spe, batch, seq, seed=5)  # epoch 1, slot 0
    assert not np.array_equal(first, again)


def test_too_small_corpus_is_loud(corpus):
    with pytest.raises(ValueError, match="windows"):
        corpus.batch(0, batch=300, seq=16)
    with pytest.raises(ValueError, match="rank"):
        corpus.batch(0, batch=2, seq=16, rank=2, world=2)


def test_train_cli_on_real_corpus(tmp_path):
    """End-to-end: the train CLI consumes a corpus file and exits 0 with
    finite losses (fresh batches need not fall monotonically)."""
    import json
    import subprocess
    import sys

    path = str(tmp_path / "corpus.bin")
    write_tokens(path, np.random.default_rng(1).integers(0, 2048, 8192))
    code = (
        "import jax, sys; jax.config.update('jax_platforms', 'cpu'); "
        f"sys.argv = ['x', 'train', '--steps', '3', '--seq', '32', "
        f"'--batch', '2', '--data', {path!r}]; "
        "from tputopo.workloads.__main__ import main; "
        "raise SystemExit(main())")
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=240)
    assert proc.returncode == 0, proc.stderr[-2000:]
    report = json.loads(
        [ln for ln in proc.stdout.splitlines() if ln.strip()][-1])
    assert report["final_step"] == 3
