"""Full training step for the flagship LM, sharded over a MeshPlan.

The complete DP x SP x TP step — forward, next-token cross-entropy, grads,
AdamW update — compiled as ONE jitted function over the scheduler-provided
mesh.  XLA inserts the collectives implied by the shardings (psum of row-
parallel block outputs inside the layer, reduce-scatter/all-reduce of grads
across ``dp``), and because the extender placed the slice contiguously they
all ride ICI rings — that is the framework's whole value proposition
measured end to end (BASELINE.md north star).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax

from tputopo.workloads import sharding as shardlib
from tputopo.workloads.model import ModelConfig, forward_with_aux, init_params


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array


def make_optimizer(lr: float = 3e-4, weight_decay: float = 0.1) -> optax.GradientTransformation:
    return optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=weight_decay)


def make_train_state(config: ModelConfig, key: jax.Array,
                     lr: float = 3e-4) -> TrainState:
    params = init_params(config, key)
    opt = make_optimizer(lr)
    return TrainState(params=params, opt_state=opt.init(params),
                      step=jnp.zeros((), jnp.int32))


def loss_fn(params: Any, tokens: jax.Array, config: ModelConfig,
            forward_fn=forward_with_aux) -> jax.Array:
    """Next-token cross-entropy over [B, S] token ids (last position
    dropped), plus the router load-balancing auxiliary for MoE configs.
    ``forward_fn`` swaps in the pipelined forward (pipeline.py)."""
    logits, aux = forward_fn(params, tokens, config)  # [B, S, V] f32
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll) + aux


def train_step(state: TrainState, tokens: jax.Array, config: ModelConfig,
               lr: float = 3e-4, forward_fn=forward_with_aux,
               accum_steps: int = 1) -> tuple[TrainState, jax.Array]:
    """One optimizer step; jit-able as-is (config/lr static via closure).

    ``accum_steps > 1`` splits the batch into that many microbatches and
    runs forward+backward per microbatch under a ``lax.scan``, summing
    grads and applying ONE optimizer update — activation memory drops to
    one microbatch's worth (the scan serializes the backward) while the
    update sees the full-batch gradient.  For the dense model the result
    is the full-batch gradient exactly (cross-entropy means over equal
    chunks average to the full mean); an MoE router's load-balancing aux
    is averaged per-microbatch, a standard and benign difference.
    """
    if accum_steps <= 1:
        loss, grads = jax.value_and_grad(loss_fn)(state.params, tokens,
                                                  config, forward_fn)
    else:
        B = tokens.shape[0]
        if B % accum_steps:
            raise ValueError(
                f"batch {B} not divisible by accum_steps {accum_steps}")
        micro = tokens.reshape(accum_steps, B // accum_steps,
                               tokens.shape[1])
        micro = shardlib.constrain(micro, None, "dp", "sp")

        def acc(carry, mb):
            loss_sum, grad_sum = carry
            l, g = jax.value_and_grad(loss_fn)(state.params, mb, config,
                                               forward_fn)
            return (loss_sum + l, jax.tree.map(jnp.add, grad_sum, g)), None

        zeros = jax.tree.map(jnp.zeros_like, state.params)
        (loss_sum, grad_sum), _ = jax.lax.scan(
            acc, (jnp.zeros((), jnp.float32), zeros), micro)
        loss = loss_sum / accum_steps
        grads = jax.tree.map(lambda g: g / accum_steps, grad_sum)
    opt = make_optimizer(lr)
    updates, opt_state = opt.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return TrainState(params=params, opt_state=opt_state,
                      step=state.step + 1), loss


def opt_shardings(opt: optax.GradientTransformation, template,
                  tree_shard, plan: shardlib.MeshPlan):
    """Optimizer-state shardings for any trainable tree: AdamW moments
    mirror the tree's own shardings, counts/schedule scalars replicated.
    Shared by the full-model state and the LoRA adapter state so the two
    never diverge when the optimizer recipe changes."""

    def fix(node):
        if isinstance(node, optax.ScaleByAdamState):
            return optax.ScaleByAdamState(
                count=plan.replicated(), mu=tree_shard, nu=tree_shard)
        return jax.tree.map(lambda _: plan.replicated(), node)

    dummy = jax.eval_shape(opt.init, template)
    return jax.tree.map(
        fix, dummy, is_leaf=lambda n: isinstance(n, optax.ScaleByAdamState))


def state_shardings(plan: shardlib.MeshPlan, config: ModelConfig,
                    lr: float = 3e-4) -> TrainState:
    """NamedSharding pytree for the full TrainState: params per the
    Megatron-style layout, AdamW moments mirroring the params they track,
    scalars replicated."""
    pshard = shardlib.param_shardings(plan, config)
    template = jax.eval_shape(partial(init_params, config),
                              jax.random.key(0))
    return TrainState(
        params=pshard,
        opt_state=opt_shardings(make_optimizer(lr), template, pshard, plan),
        step=plan.replicated())


def make_sharded_train_step(plan: shardlib.MeshPlan, config: ModelConfig,
                            lr: float = 3e-4, n_micro: int | None = None,
                            accum_steps: int = 1):
    """Compile train_step with explicit in/out shardings over ``plan``.

    Params (and therefore AdamW moments, which mirror the param pytree)
    shard per :func:`tputopo.workloads.sharding.param_specs`; batches shard
    batch-over-dp, sequence-over-sp.  Donates the state buffers.  When the
    plan has pp > 1 the forward pass runs the SPMD pipeline
    (:mod:`tputopo.workloads.pipeline`) with ``n_micro`` microbatches.
    ``accum_steps`` layers gradient accumulation on top (each accumulation
    microbatch still splits over dp, and pipelines over pp when active).
    """
    shardings = state_shardings(plan, config, lr)
    if plan.axes.get("pp", 1) > 1:
        from tputopo.workloads.pipeline import pipelined_forward_with_aux

        fwd = partial(pipelined_forward_with_aux, plan=plan, n_micro=n_micro)
    else:
        fwd = forward_with_aux

    def step_fn(state: TrainState, tokens: jax.Array):
        with shardlib.activate(plan):
            return train_step(state, tokens, config, lr, forward_fn=fwd,
                              accum_steps=accum_steps)

    return jax.jit(
        step_fn,
        in_shardings=(shardings, shardlib.batch_sharding(plan)),
        out_shardings=(shardings, plan.replicated()),
        donate_argnums=(0,),
    )


def make_sharded_state(plan: shardlib.MeshPlan, config: ModelConfig,
                       key: jax.Array, lr: float = 3e-4) -> TrainState:
    """Initialize TrainState directly into its sharded layout (jitted init
    with explicit out_shardings, so no host-side full materialization and
    no accidental replication of the optimizer moments)."""
    shardings = state_shardings(plan, config, lr)

    @partial(jax.jit, out_shardings=shardings)
    def init():
        params = init_params(config, key)
        opt_state = make_optimizer(lr).init(params)
        return TrainState(params=params, opt_state=opt_state,
                          step=jnp.zeros((), jnp.int32))

    with plan.mesh:
        return init()
