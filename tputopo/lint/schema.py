"""The ``schema-additivity`` checker: report schemas only ever grow.

The sim report's versioning contract (v2 -> v6) is strict additivity:
every schema version emits a superset of the prior one, new keys are
feature-gated (present only when their feature ran, so each feature-off
path stays byte-identical to the prior schema), and the version strings
themselves are single-definition contract literals.  Until now that was
enforced only dynamically — full-trace byte-identity replays in CI.
This rule proves the structural half statically:

- The **manifest** (``tputopo/sim/report.py`` ``SCHEMA_KEY_MANIFEST``)
  pins, per schema version, the top-level report keys and per-policy
  record keys, split into unconditional and feature-gated sets.
- The **extraction** reads the key-sets the builders actually emit from
  their ASTs: the dict literal a builder returns (or assigns and
  returns) gives the unconditional keys; ``out["key"] = ...`` subscript
  stores give gated keys when under a conditional, unconditional ones
  otherwise.  Builders: ``build_report`` (top), ``MetricsCollector.
  report`` and ``sim/engine.py::finalize_run_state`` (policy).
- **Findings**: a manifest key no builder emits any more (a removed key
  breaks every consumer pinned to its version); a feature-gated key
  emitted unconditionally (the feature-off report gains the key — the
  byte-identity contract breaks silently); a formerly-unconditional key
  now emitted only behind a condition (removed from feature-off
  reports); an emitted key absent from the manifest (additive changes
  extend the manifest in the same PR, in front of review); and any
  version-SHAPED literal (``tputopo.sim/vN``) whose value is not one of
  the canonical constants — the single-def rule already flags duplicates
  of the defined versions, so this closes the gap it cannot see: a NEW
  version string typed inline instead of being routed through
  ``report.py``.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from tputopo.lint.core import Checker, Finding, Module

_VERSION_RE = re.compile(r"tputopo\.sim/v\d+\Z")

#: The canonical report module: schema constants + the key manifest.
REPORT_MODULE = "tputopo/sim/report.py"

#: (relpath, function qualname, category) — where report keys are born.
DEFAULT_BUILDERS: tuple[tuple[str, str, str], ...] = (
    ("tputopo/sim/report.py", "build_report", "top"),
    ("tputopo/sim/report.py", "MetricsCollector.report", "policy"),
    ("tputopo/sim/engine.py", "finalize_run_state", "policy"),
)

MANIFEST_NAME = "SCHEMA_KEY_MANIFEST"


class _Emit:
    __slots__ = ("key", "category", "relpath", "line", "gated", "gate")

    def __init__(self, key, category, relpath, line, gated, gate=None):
        self.key = key
        self.category = category
        self.relpath = relpath
        self.line = line
        self.gated = gated
        #: (id of the innermost gating If, arm) — lets the extractor
        #: recognize a key emitted on BOTH arms of one if/else as
        #: unconditional (every path emits it), not feature-gated.
        self.gate = gate


def _function_node(mod: Module, qualname: str) -> ast.AST | None:
    parts = qualname.split(".")
    body = getattr(mod.tree, "body", [])
    node = None
    for part in parts:
        node = next((n for n in body
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef, ast.ClassDef))
                     and n.name == part), None)
        if node is None:
            return None
        body = node.body
    return node if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) else None


def _returned_name(fn: ast.AST) -> str | None:
    """The Name a builder ultimately returns (``return out``), so only
    ITS dict literal / subscript stores count as emissions."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and isinstance(node.value,
                                                       ast.Name):
            return node.value.id
    return None


class SchemaAdditivityChecker(Checker):
    rule = "schema-additivity"
    description = ("report schemas are strictly additive: the key-sets "
                   "the sim report builders emit must match report.py's "
                   "pinned SCHEMA_KEY_MANIFEST (no removed keys, "
                   "feature-gated keys never emitted unconditionally) "
                   "and every tputopo.sim/vN literal must be one of the "
                   "canonical schema constants")

    version = 1

    def __init__(self, builders=DEFAULT_BUILDERS,
                 report_module: str = REPORT_MODULE) -> None:
        self.builders = tuple(builders)
        self.report_module = report_module
        self._mods: list[Module] = []

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("tputopo/")

    def check_module(self, mod: Module) -> Iterable[Finding]:
        self._mods.append(mod)
        return ()

    # ---- manifest + constants ----------------------------------------------

    def _canon(self, mod: Module):
        """(version values, manifest literal, manifest key lines)."""
        versions: set[str] = set()
        manifest = None
        key_lines: dict[tuple[str, str, str], int] = {}
        for node in getattr(mod.tree, "body", []):
            if not isinstance(node, ast.Assign):
                continue
            names = [t.id for t in node.targets
                     if isinstance(t, ast.Name)]
            if not names:
                continue
            if isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str) \
                    and _VERSION_RE.match(node.value.value):
                versions.add(node.value.value)
            if MANIFEST_NAME in names and isinstance(node.value, ast.Dict):
                try:
                    manifest = ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    manifest = None
                else:
                    self._manifest_key_lines(node.value, key_lines)
        return versions, manifest, key_lines

    @staticmethod
    def _manifest_key_lines(dict_node: ast.Dict, out: dict) -> None:
        """(version, bucket, key) -> line inside the manifest literal,
        so removed-key findings point at the stale pin itself."""
        for vk, vv in zip(dict_node.keys, dict_node.values):
            if not (isinstance(vk, ast.Constant)
                    and isinstance(vv, ast.Dict)):
                continue
            for bk, bv in zip(vv.keys, vv.values):
                if not (isinstance(bk, ast.Constant)
                        and isinstance(bv, (ast.Tuple, ast.List))):
                    continue
                for el in bv.elts:
                    if isinstance(el, ast.Constant):
                        out[(vk.value, bk.value, el.value)] = el.lineno

    # ---- builder extraction ------------------------------------------------

    def _extract(self, mod: Module, qualname: str,
                 category: str) -> list[_Emit]:
        fn = _function_node(mod, qualname)
        if fn is None:
            return []
        out_name = _returned_name(fn)
        emits: list[_Emit] = []

        def visit(body: list, gated: bool, gate) -> None:
            for node in body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                if isinstance(node, ast.Return) \
                        and isinstance(node.value, ast.Dict):
                    for k in node.value.keys:
                        if isinstance(k, ast.Constant) \
                                and isinstance(k.value, str):
                            emits.append(_Emit(k.value, category,
                                               mod.relpath, k.lineno,
                                               gated, gate))
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id == out_name \
                                and isinstance(node.value, ast.Dict):
                            for k in node.value.keys:
                                if isinstance(k, ast.Constant) \
                                        and isinstance(k.value, str):
                                    emits.append(_Emit(
                                        k.value, category, mod.relpath,
                                        k.lineno, gated, gate))
                        if isinstance(t, ast.Subscript) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id == out_name \
                                and isinstance(t.slice, ast.Constant) \
                                and isinstance(t.slice.value, str):
                            emits.append(_Emit(t.slice.value, category,
                                               mod.relpath, t.lineno,
                                               gated, gate))
                if isinstance(node, ast.If):
                    visit(node.body, True, (id(node), "body"))
                    visit(node.orelse, True, (id(node), "orelse"))
                elif isinstance(node, (ast.For, ast.While, ast.With,
                                       ast.Try)):
                    visit(getattr(node, "body", []), gated, gate)
                    visit(getattr(node, "orelse", []), gated, gate)
                    visit(getattr(node, "finalbody", []), gated, gate)
                    for h in getattr(node, "handlers", ()) or ():
                        visit(h.body, gated, gate)

        visit(fn.body, False, None)
        # A key emitted on BOTH arms of the SAME if/else reaches every
        # path through that statement — it is unconditional, not
        # feature-gated (an `if compact: out[k] = a else: out[k] = b`
        # refactor must not read as gating the key).
        by_key: dict[str, list[_Emit]] = {}
        for e in emits:
            by_key.setdefault(e.key, []).append(e)
        for es in by_key.values():
            arms_by_if: dict[int, set[str]] = {}
            for e in es:
                if e.gate is not None:
                    arms_by_if.setdefault(e.gate[0], set()).add(e.gate[1])
            both = {if_id for if_id, arms in arms_by_if.items()
                    if arms == {"body", "orelse"}}
            for e in es:
                if e.gate is not None and e.gate[0] in both:
                    e.gated = False
        return emits

    # ---- the analysis ------------------------------------------------------

    def finalize(self) -> Iterable[Finding]:
        mods, self._mods = self._mods, []
        by_path = {m.relpath: m for m in mods}
        report_mod = by_path.get(self.report_module)
        if report_mod is None:
            return  # canonical module not in this run's file set
        versions, manifest, key_lines = self._canon(report_mod)
        emits: list[_Emit] = []
        complete: dict[str, bool] = {}
        for rel, qual, category in self.builders:
            mod = by_path.get(rel)
            complete[category] = complete.get(category, True) \
                and mod is not None
            if mod is not None:
                emits.extend(self._extract(mod, qual, category))
        if manifest is not None:
            yield from self._diff(manifest, key_lines, emits, complete)
        # Version-literal routing: a version-shaped string whose value is
        # NOT a canonical constant (single-def owns exact duplicates of
        # the defined ones; this catches a NEW version typed inline).
        for mod in mods:
            for node in mod.nodes():
                if isinstance(node, ast.Constant) \
                        and isinstance(node.value, str) \
                        and _VERSION_RE.match(node.value) \
                        and node.value not in versions:
                    yield Finding(
                        mod.relpath, node.lineno, node.col_offset,
                        self.rule,
                        f"schema version literal {node.value!r} is not "
                        "routed through the contract constants in "
                        f"{self.report_module} — define SCHEMA_<NAME> "
                        "there (and extend SCHEMA_KEY_MANIFEST) first")

    def _diff(self, manifest: dict, key_lines: dict, emits: list[_Emit],
              complete: dict[str, bool]) -> Iterable[Finding]:
        emitted: dict[tuple[str, str], list[_Emit]] = {}
        for e in emits:
            emitted.setdefault((e.category, e.key), []).append(e)
        manifest_keys: dict[tuple[str, str], tuple[str, bool]] = {}
        for version in sorted(manifest):
            buckets = manifest[version]
            for bucket, gated in (("top", False), ("top_gated", True),
                                  ("policy", False),
                                  ("policy_gated", True)):
                category = "top" if bucket.startswith("top") else "policy"
                for key in buckets.get(bucket, ()):
                    manifest_keys.setdefault((category, key),
                                             (version, gated))
        for (category, key), (version, gated) in sorted(
                manifest_keys.items()):
            got = emitted.get((category, key))
            first_bucket = (f"{category}_gated" if gated else category)
            line = key_lines.get((version, first_bucket, key), 1)
            if not got:
                if not complete.get(category, False):
                    # A builder of this category is outside this run's
                    # file set (a scoped CLI run) — "not emitted" would
                    # be an artifact of the scope, not a removal.
                    continue
                yield Finding(
                    self.report_module, line, 0, self.rule,
                    f"schema key '{key}' ({category}, {version}) is "
                    "pinned in SCHEMA_KEY_MANIFEST but no builder emits "
                    "it — schema versions are strictly additive; a "
                    "removed key breaks every consumer pinned to "
                    f"{version}")
                continue
            if gated:
                for e in got:
                    if not e.gated:
                        yield Finding(
                            e.relpath, e.line, 0, self.rule,
                            f"feature-gated schema key '{key}' "
                            f"({version}) is emitted unconditionally — "
                            "the feature-off report gains the key and "
                            "its byte-identity to the prior schema "
                            "breaks; emit it only when the feature ran")
            elif all(e.gated for e in got):
                e = got[0]
                yield Finding(
                    e.relpath, e.line, 0, self.rule,
                    f"schema key '{key}' is unconditional in "
                    f"{version} but now emitted only behind a "
                    "condition — feature-off reports lose it, which is "
                    "a removal in disguise")
        for (category, key), es in sorted(emitted.items()):
            if (category, key) not in manifest_keys:
                e = es[0]
                yield Finding(
                    e.relpath, e.line, 0, self.rule,
                    f"schema key '{key}' ({category}) is emitted but "
                    "absent from SCHEMA_KEY_MANIFEST — additive schema "
                    "changes extend the manifest (and bump/gate the "
                    "version) in the same PR")
