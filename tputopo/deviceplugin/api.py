"""Device-plugin API types + in-process kubelet transport.

Python-side types for the contract in ``deviceplugin.proto`` (the kubelet
device-plugin gRPC shape the reference design uses, design.md:57-59).  The
transport is pluggable: :class:`FakeKubelet` drives the same Register /
ListAndWatch / Allocate state machine in-process (how most tests stage
clusters), and :mod:`tputopo.deviceplugin.grpc_transport` drives it over
the real kubelet unix-socket gRPC wire.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field


HEALTHY = "Healthy"
UNHEALTHY = "Unhealthy"
API_VERSION = "v1beta1"


@dataclass(frozen=True)
class Device:
    id: str           # global chip coordinate string, e.g. "0,0,1"
    health: str = HEALTHY


@dataclass(frozen=True)
class DeviceSpec:
    container_path: str
    host_path: str
    permissions: str = "rw"


@dataclass
class ContainerAllocateResponse:
    envs: dict[str, str] = field(default_factory=dict)
    devices: list[DeviceSpec] = field(default_factory=list)


@dataclass
class AllocateRequest:
    container_device_ids: list[list[str]]


@dataclass
class AllocateResponse:
    container_responses: list[ContainerAllocateResponse]


@dataclass(frozen=True)
class RegisterRequest:
    version: str
    endpoint: str
    resource_name: str


class FakeKubelet:
    """In-process stand-in for the kubelet side of the device-plugin API.

    Mirrors kubelet behavior the plugin depends on: accepts Register, pulls
    the ListAndWatch stream into a device inventory, and forwards Allocate
    calls.  Exposes that inventory to tests/extender fixtures.
    """

    def __init__(self) -> None:
        self.registrations: list[RegisterRequest] = []
        self.devices: dict[str, Device] = {}
        self._plugins: dict[str, "object"] = {}  # resource -> plugin
        self._updates: queue.Queue = queue.Queue()
        self._lock = threading.Lock()

    # -- Registration service ----------------------------------------------

    def register(self, req: RegisterRequest, plugin) -> None:
        if req.version != API_VERSION:
            raise ValueError(
                f"unsupported device-plugin API version {req.version!r}"
            )
        with self._lock:
            self.registrations.append(req)
            self._plugins[req.resource_name] = plugin
        # kubelet immediately opens the ListAndWatch stream:
        for resp in plugin.list_and_watch_once():
            self._consume(resp)

    def _consume(self, devices: list[Device]) -> None:
        with self._lock:
            self.devices = {d.id: d for d in devices}
        self._updates.put(devices)

    def notify_devices(self, devices: list[Device]) -> None:
        """Plugin pushes an updated device list (health change etc.)."""
        self._consume(devices)

    # -- scheduling-side views ----------------------------------------------

    def allocatable(self, resource: str) -> int:
        with self._lock:
            if resource not in self._plugins:
                return 0
            return sum(1 for d in self.devices.values() if d.health == HEALTHY)

    def allocate(self, resource: str, device_ids: list[str]) -> AllocateResponse:
        with self._lock:
            plugin = self._plugins.get(resource)
            if plugin is None:
                raise KeyError(f"no device plugin registered for {resource}")
            unknown = [d for d in device_ids if d not in self.devices]
        if unknown:
            raise ValueError(f"unknown device ids {unknown}")
        return plugin.allocate(AllocateRequest(container_device_ids=[device_ids]))
