"""Weight-only int8 quantization for serving (tputopo.workloads.quant).

The reference has no serving or quantization story at all (SURVEY §0 —
it ships a design doc for a *placement* system); this is part of the
workload layer the placement serves (SURVEY §1 L5).  Contract under
test: quantized decode/serving is a drop-in parameter swap — same code
path, same shapes, near-identical tokens — at roughly half the streamed
bytes (the HBM-bound decode loop's only remaining throughput lever;
bench_decode measures the realized speedup on hardware).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tputopo.workloads.decode import generate
from tputopo.workloads.model import ModelConfig, forward, init_params
from tputopo.workloads.moe import MoEConfig, moe_mlp
from tputopo.workloads.quant import (deq, deq_rows, is_quantized, qdot,
                                     quantize_params, streamed_bytes)
from tputopo.workloads.serving import ServingEngine

CFG = ModelConfig(vocab_size=128, d_model=64, n_layers=2, n_heads=4,
                  n_kv_heads=2, d_ff=128, max_seq=64)


def _params(cfg=CFG, seed=0):
    return init_params(cfg, jax.random.key(seed))


@pytest.mark.slow
def test_roundtrip_error_bounded_by_half_scale():
    """Symmetric absmax int8: |deq(q) - w| <= scale/2 elementwise (the
    rounding bound), and exactly 0 for all-zero channels."""
    w = _params()["layers"]["wq"]
    qw = quantize_params(_params())["layers"]["wq"]
    err = jnp.abs(deq(qw, jnp.float32) - w)
    assert float(jnp.max(err / qw["scale"])) <= 0.5 + 1e-3
    z = jnp.zeros((4, 8))
    qz = quantize_params({"embed": z, "lm_head": z, "final_norm": z[0],
                          "layers": {"wq": z[None]}})
    assert float(jnp.abs(deq(qz["layers"]["wq"], jnp.float32)).max()) == 0.0


def test_qdot_matches_dequantize_then_dot():
    """(x @ q) * s == x @ (q * s): the scale commutes with the
    contraction, so the fused form qdot uses is exact, not approximate."""
    key = jax.random.key(1)
    w = jax.random.normal(key, (3, 16, 8), jnp.float32)
    qw = quantize_params({"embed": w[0], "lm_head": w[0].T,
                          "final_norm": w[0, 0], "layers": {"wq": w}})
    x = jax.random.normal(jax.random.key(2), (5, 16), jnp.float32)
    slice1 = jax.tree.map(lambda a: a[1], qw["layers"]["wq"])  # a scan step's view
    np.testing.assert_allclose(np.asarray(qdot(x, slice1)),
                               np.asarray(x @ deq(qw["layers"]["wq"], jnp.float32)[1]),
                               rtol=1e-5, atol=1e-5)


def test_forward_parity():
    """Quantized forward logits track the f32 forward closely (weight-only
    per-channel int8 is near-lossless)."""
    params = _params()
    qp = quantize_params(params)
    toks = jax.random.randint(jax.random.key(3), (2, 16), 0, CFG.vocab_size)
    lg = forward(params, toks, CFG)
    lq = forward(qp, toks, CFG)
    rel = float(jnp.max(jnp.abs(lg - lq)) / jnp.max(jnp.abs(lg)))
    assert rel < 0.1, rel


def test_greedy_decode_token_parity():
    """Greedy decode with quantized weights tracks the unquantized token
    stream.  A random-init tiny model has near-uniform logits, so one
    flipped argmax diverges the rest of that sequence chaotically —
    demand strong agreement, not bitwise identity (which even bf16 vs
    f32 compute would fail here)."""
    params = _params()
    qp = quantize_params(params)
    prompt = jax.random.randint(jax.random.key(4), (2, 8), 0, CFG.vocab_size)
    g = np.asarray(generate(params, prompt, CFG, max_new=8))
    gq = np.asarray(generate(qp, prompt, CFG, max_new=8))
    np.testing.assert_array_equal(g[:, :8], gq[:, :8])  # prompts echoed
    # The first generated token of each sequence sees identical context:
    # measured top-1/top-2 logit gap here is ~0.4 vs ~0.06 quantization
    # perturbation, so it must agree.  Later steps legitimately diverge
    # once any near-tie flips (verified: agreement decays chaotically,
    # not systematically — logits stay within 10% in test_forward_parity).
    np.testing.assert_array_equal(g[:, 8], gq[:, 8])


def test_streamed_bytes_roughly_halved():
    """int8 + f32-scales stream less than 55% of the bf16 accounting
    (matmul weights incl. the lm_head drop 2 bytes -> 1, plus scales)."""
    params = _params()
    qp = quantize_params(params)
    ratio = streamed_bytes(qp) / streamed_bytes(params)
    assert ratio < 0.55, ratio
    # embed excluded from streaming both sides; scales are counted.
    assert is_quantized(qp["lm_head"]) and is_quantized(qp["layers"]["wq"])


@pytest.mark.slow
def test_moe_quantized_decode_and_training_path():
    """MoE expert tables quantize too: the drop-free decode mixture scans
    quantized {int8, scale} leaves, and the capacity-dispatch training
    path dequantizes wholesale (deq) — both run and track f32."""
    mcfg = ModelConfig(vocab_size=128, d_model=64, n_layers=2, n_heads=4,
                       n_kv_heads=2, d_ff=128, max_seq=64,
                       moe=MoEConfig(n_experts=4, top_k=2))
    params = init_params(mcfg, jax.random.key(0))
    qp = quantize_params(params)
    prompt = jax.random.randint(jax.random.key(5), (2, 8), 0, 128)
    g = generate(params, prompt, mcfg, max_new=4)
    gq = generate(qp, prompt, mcfg, max_new=4)
    assert float((np.asarray(g) == np.asarray(gq)).mean()) > 0.9
    # Training-path einsums (one layer's slice) accept quantized leaves.
    x = jax.random.normal(jax.random.key(6), (2, 8, 64), jnp.float32)
    layer0 = jax.tree.map(lambda a: a[0], qp["layers"]["moe"])
    out, aux = moe_mlp(x, layer0, mcfg)
    assert out.shape == x.shape and np.isfinite(float(aux))


@pytest.mark.slow
def test_serving_engine_quantized_matches_one_shot():
    """The continuous-batching engine is parameter-format agnostic: with
    quantized weights it still matches its own one-shot generate
    reference per request."""
    params = _params()
    qp = quantize_params(params)
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, 128, n).tolist() for n in (5, 3)]
    eng = ServingEngine(qp, CFG, slots=2, max_len=24, prompt_pad=5)
    ids = [eng.submit(p, max_new=6) for p in prompts]
    results = eng.run()
    for rid, p in zip(ids, prompts):
        one = generate(qp, jnp.asarray([p + [0] * (5 - len(p))])[:, :len(p)],
                       CFG, max_new=6)
        assert results[rid] == np.asarray(one)[0].tolist(), rid


def test_embed_rows_gather_parity():
    params = _params()
    qp = quantize_params(params)
    idx = jnp.asarray([[0, 5, 7]])
    raw = deq_rows(params["embed"], idx, jnp.float32)
    q = deq_rows(qp["embed"], idx, jnp.float32)
    assert float(jnp.max(jnp.abs(raw - q))) < 0.05 * float(jnp.max(jnp.abs(raw)))


@pytest.mark.slow
def test_sharded_int8_decode_matches_single_device():
    """Multi-chip int8 serving: quantize ON device under the mesh (GSPMD
    propagates the weight shardings onto the int8/scale pair) and decode
    over dp x tp — tokens must match the unsharded quantized run."""
    from tputopo.workloads import sharding as shardlib
    from tputopo.workloads.sharding import mesh_for_slice

    params = _params()
    qp_host = quantize_params(params)
    prompt = jax.random.randint(jax.random.key(8), (4, 8), 0, CFG.vocab_size)
    want = np.asarray(generate(qp_host, prompt, CFG, max_new=6))

    plan = mesh_for_slice((8,), heads=CFG.n_kv_heads)
    sharded = jax.device_put(params, shardlib.param_shardings(plan, CFG))
    with plan.mesh:
        qp = jax.jit(quantize_params)(sharded)
    sp = jax.device_put(prompt, plan.sharding("dp", None))
    with shardlib.activate(plan):
        got = np.asarray(generate(qp, sp, CFG, max_new=6))
    np.testing.assert_array_equal(want, got)


def test_quantize_kv_roundtrip_and_fold_layout():
    """KV rows roundtrip within half a scale step, and fold_kv_scale
    produces exactly the broadcast layout of the bkgts logits einsum."""
    from tputopo.workloads.quant import fold_kv_scale, quantize_kv

    x = jax.random.normal(jax.random.key(9), (2, 6, 3, 8))  # [B,S,KV,H]
    q, s = quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == (2, 6, 3, 1)
    assert float(jnp.max(jnp.abs(q * s - x) / s)) <= 0.5 + 1e-3
    folded = fold_kv_scale(s)
    assert folded.shape == (2, 3, 1, 1, 6)  # [B,KV,1,1,S]
    np.testing.assert_allclose(np.asarray(folded[1, 2, 0, 0]),
                               np.asarray(s[1, :, 2, 0]))


def test_int8_kv_decode_token_parity():
    """kv_dtype="int8" is a config-only swap: same generate code, tokens
    track the bf16 cache on the tiny model.  The scale FOLD is exact, but
    the int8 rounding perturbs logits, so exact-token equality is not the
    guarantee — assert the deterministic part (prefill logits close) plus
    strong first-token agreement."""
    import dataclasses

    from tputopo.workloads.decode import KVCache, _block_step, _rope_tables

    params = _params()
    prompt = jax.random.randint(jax.random.key(10), (2, 8), 0, CFG.vocab_size)
    cfg8 = dataclasses.replace(CFG, kv_dtype="int8")
    cos, sin = _rope_tables(CFG, 16)
    lg, _ = _block_step(params, CFG, prompt, 0,
                        KVCache.create(CFG, 2, 16), cos, sin)
    lq, _ = _block_step(params, cfg8, prompt, 0,
                        KVCache.create(cfg8, 2, 16), cos, sin)
    rel = float(jnp.max(jnp.abs(lg - lq)) / jnp.max(jnp.abs(lg)))
    assert rel < 0.1, rel
    g = np.asarray(generate(params, prompt, CFG, max_new=8))
    g8 = np.asarray(generate(params, prompt, cfg8, max_new=8))
    np.testing.assert_array_equal(g[:, :8], g8[:, :8])  # prompts echoed
    assert (g[:, 8] == g8[:, 8]).mean() >= 0.5  # later steps may diverge


@pytest.mark.slow
def test_serving_engine_int8_kv_matches_one_shot():
    """Continuous batching over an int8 cache (quantize-at-write in the
    ragged step, scale folds in _attend_ragged) matches its own one-shot
    generate reference — including across slot reuse."""
    import dataclasses

    cfg8 = dataclasses.replace(CFG, kv_dtype="int8")
    params = _params()
    rng = np.random.default_rng(11)
    prompts = [rng.integers(0, 128, n).tolist() for n in (5, 3, 4)]
    eng = ServingEngine(params, cfg8, slots=2, max_len=24, prompt_pad=5)
    ids = [eng.submit(p, max_new=6) for p in prompts]
    results = eng.run()
    for rid, p in zip(ids, prompts):
        one = generate(params, jnp.asarray([p]), cfg8, max_new=6)
        assert results[rid] == np.asarray(one)[0].tolist(), rid


def test_int8_kv_cache_structure():
    """create() materializes int8 buffers + f32 scales; bf16 stays
    two-leaf (None scales) so jit structures differ only via the static
    config; unknown kv_dtype is rejected."""
    import dataclasses

    from tputopo.workloads.decode import KVCache

    c8 = KVCache.create(dataclasses.replace(CFG, kv_dtype="int8"), 2, 16)
    assert c8.k.dtype == jnp.int8 and c8.k_scale.dtype == jnp.float32
    assert c8.k_scale.shape == c8.k.shape[:-1] + (1,)
    c16 = KVCache.create(CFG, 2, 16)
    assert c16.k_scale is None and c16.v_scale is None
    with pytest.raises(ValueError):
        KVCache.create(dataclasses.replace(CFG, kv_dtype="fp8"), 2, 16)


def test_training_keeps_f32_masters():
    """quantize_params never mutates its input; norms/router stay f32."""
    params = _params()
    before = jax.tree.map(lambda a: np.asarray(a).copy(), params)
    qp = quantize_params(params)
    for (p1, a), (p2, b) in zip(
            jax.tree_util.tree_leaves_with_path(before),
            jax.tree_util.tree_leaves_with_path(params)):
        np.testing.assert_array_equal(a, np.asarray(b))
    assert qp["final_norm"].dtype == jnp.float32
    assert qp["layers"]["attn_norm"].dtype == jnp.float32
    with pytest.raises(KeyError):
        _ = qp["layers"]["wq"]["missing"]


@pytest.mark.slow
def test_quantized_params_checkpoint_roundtrip(tmp_path):
    """Deployment flow: quantize once, save, restore onto a fresh
    template, serve — restored int8/scale leaves are bit-identical and
    the engine produces the same tokens."""
    from tputopo.workloads.checkpoint import restore_params, save_params

    params = _params()
    qp = quantize_params(params)
    save_params(tmp_path, qp)
    template = quantize_params(_params(seed=1))  # different values, same tree
    restored = restore_params(tmp_path, template)
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(qp),
            jax.tree_util.tree_leaves_with_path(restored)):
        assert pa == pb
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    prompt = jax.random.randint(jax.random.key(30), (2, 8), 0, CFG.vocab_size)
    np.testing.assert_array_equal(
        np.asarray(generate(qp, prompt, CFG, max_new=4)),
        np.asarray(generate(restored, prompt, CFG, max_new=4)))
    assert restore_params(tmp_path / "empty", template) is None


# ---- grouped int4 -----------------------------------------------------------

F32CFG = ModelConfig(vocab_size=128, d_model=64, n_layers=2, n_heads=4,
                     n_kv_heads=2, d_ff=128, max_seq=64,
                     compute_dtype=jnp.float32)


def _dequantize_tree(t):
    if is_quantized(t):
        return deq(t, jnp.float32)
    if isinstance(t, dict):
        return {k: _dequantize_tree(v) for k, v in t.items()}
    return t


def test_int4_roundtrip_error_bounded_by_half_group_scale():
    """Grouped symmetric int4: |deq(q) - w| <= group_scale/2 elementwise."""
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(64, 32)), jnp.float32)
    q = quantize_params({"embed": w, "lm_head": w, "final_norm": w[0],
                         "layers": {"wq": w[None]}}, bits=4, group_size=16)
    leaf = q["layers"]["wq"]
    assert leaf["int4"].dtype == jnp.int4
    assert leaf["int4"].shape == (1, 4, 16, 32)  # [L, G, g, out]
    back = deq(leaf, jnp.float32)[0]
    bound = jnp.repeat(jnp.squeeze(leaf["scale"], -2)[0], 16, axis=0) / 2
    assert float(jnp.max(jnp.abs(back - w) - bound)) <= 1e-6


def test_int4_qdot_matches_deq_reference():
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.normal(size=(64, 32)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(2, 8, 64)), jnp.float32)
    q = quantize_params({"embed": w, "lm_head": w, "final_norm": w[0],
                         "layers": {"wq": w[None]}}, bits=4,
                        group_size=16)["layers"]["wq"]
    leaf = jax.tree.map(lambda a: a[0], q)
    ref = x @ deq(leaf, jnp.float32)
    got = qdot(x, leaf)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_int4_qdot_rejects_unsliced_stacked_leaf():
    """The int4 group einsum cannot broadcast x's batch ellipsis against
    a weight's leading layer/expert axis; an un-sliced stacked leaf must
    error loudly (scan-slice contract in qdot's docstring), not broadcast
    silently wrong when the dims happen to coincide."""
    rng = np.random.default_rng(4)
    w = jnp.asarray(rng.normal(size=(64, 32)), jnp.float32)
    stacked = quantize_params({"embed": w, "lm_head": w, "final_norm": w[0],
                               "layers": {"wq": w[None]}}, bits=4,
                              group_size=16)["layers"]["wq"]
    assert stacked["int4"].ndim == 4  # [L, G, g, out] — NOT sliced
    x = jnp.asarray(rng.normal(size=(2, 8, 64)), jnp.float32)
    with pytest.raises(ValueError, match="scan-slice"):
        qdot(x, stacked)


def test_int4_decode_token_parity_with_dequantized_twin():
    """Greedy decode through the live int4 path must equal decoding the
    dequantized-f32 copy of the same tree — the quantization is in the
    weights, not the code path."""
    params = quantize_params(init_params(F32CFG, jax.random.key(0)),
                             bits=4, group_size=16)
    twin = _dequantize_tree(params)
    prompt = jnp.asarray(np.random.default_rng(2).integers(0, 128, (2, 16)))
    t4 = np.asarray(generate(params, prompt, F32CFG, max_new=8))
    td = np.asarray(generate(twin, prompt, F32CFG, max_new=8))
    assert (t4 == td).all()


def test_int4_streams_fewer_bytes_than_int8():
    cfg = ModelConfig(vocab_size=512, d_model=256, n_layers=2, n_heads=8,
                      n_kv_heads=4, d_ff=512, max_seq=64)
    params = init_params(cfg, jax.random.key(0))
    raw = streamed_bytes(params)
    i8 = streamed_bytes(quantize_params(params))
    i4 = streamed_bytes(quantize_params(params, bits=4))
    assert i4 < i8 < raw
    # At this size the matmul tables dominate: int4 should land well
    # under 3/4 of int8's stream (scales + f32 norms are the overhead).
    assert i4 / i8 < 0.75


@pytest.mark.slow
def test_int4_moe_forward_runs_and_matches_twin():
    cfg = ModelConfig(vocab_size=128, d_model=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, d_ff=128, max_seq=64,
                      compute_dtype=jnp.float32,
                      moe=MoEConfig(n_experts=4, top_k=2))
    params = quantize_params(init_params(cfg, jax.random.key(0)),
                             bits=4, group_size=16)
    twin = _dequantize_tree(params)
    toks = jnp.asarray(np.random.default_rng(3).integers(0, 128, (2, 16)))
    out4 = forward(params, toks, cfg)
    outd = forward(twin, toks, cfg)
    np.testing.assert_allclose(np.asarray(out4), np.asarray(outd),
                               atol=3e-5, rtol=3e-5)


def test_int4_bf16_compute_path_runs_on_cpu():
    """The int4 dot casts operands to f32 rather than relying on
    bf16 x bf16 = f32 dot support (the CPU backend rejects that mode);
    a bf16-compute int4 forward must run everywhere the suite does."""
    cfg = ModelConfig(vocab_size=64, d_model=32, n_layers=2, n_heads=4,
                      n_kv_heads=2, d_ff=64, max_seq=64,
                      compute_dtype=jnp.bfloat16)
    params = quantize_params(init_params(cfg, jax.random.key(0)),
                             bits=4, group_size=8)
    toks = jnp.asarray(np.random.default_rng(5).integers(0, 64, (2, 16)))
    out = forward(params, toks, cfg)
    assert bool(jnp.isfinite(out).all())


def test_int4_degraded_group_warns():
    """A prime inner dim collapses the divisor walk toward per-element
    scales; that regression must warn, not silently ship as 'int4'."""
    import warnings

    w = jnp.ones((13, 8))
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        quantize_params({"embed": w, "lm_head": w, "final_norm": w[0],
                         "layers": {"wq": w[None]}}, bits=4, group_size=4)
    assert any("group size degraded" in str(r.message) for r in rec)
