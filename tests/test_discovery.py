"""Discovery-shim tests: native C++ probe vs pure-Python twin must agree
(SURVEY.md §4.2 — the fake backend is the rebuild's only topology fixture
source, the analog of the reference's `nvidia-smi topo -m` PNG)."""

import os

import pytest

from tputopo.discovery import ensure_native_built, probe_host
from tputopo.discovery.shim import _probe_native, _probe_python, _load_native
from tputopo.topology.generations import GENERATIONS


@pytest.fixture(scope="session")
def native_lib():
    path = ensure_native_built()
    if path is None:
        pytest.skip("no C++ toolchain available")
    lib = _load_native()
    assert lib is not None
    return lib


def _with_env(env, fn):
    saved = {k: os.environ.get(k) for k in
             ("TPUTOPO_FAKE", "TPU_ACCELERATOR_TYPE", "TPU_CHIPS_PER_HOST_BOUNDS",
              "TPU_HOST_BOUNDS", "TPU_WORKER_ID", "CLOUD_TPU_TASK_ID")}
    try:
        for k in saved:
            os.environ.pop(k, None)
        os.environ.update(env)
        return fn()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


FAKE_CASES = [
    {"TPUTOPO_FAKE": "v5p:2x2x4"},
    {"TPUTOPO_FAKE": "v5p:2x2x4@3"},
    {"TPUTOPO_FAKE": "v5e:4x4"},
    {"TPUTOPO_FAKE": "v4:2x2x2@1"},
    {"TPUTOPO_FAKE": "nonsense"},
    {"TPUTOPO_FAKE": "v99:2x2"},
    {"TPUTOPO_FAKE": "v5e:2x2x2"},
    {"TPUTOPO_FAKE": "v5p:2x2x4x"},   # trailing separator -> error in BOTH
    {"TPUTOPO_FAKE": "v5p:2x2x4@3abc"},  # junk worker id -> 0 in BOTH
    {"TPUTOPO_FAKE": "v5e:4x4@-1"},      # negative worker id -> 0 in BOTH
    {"TPU_ACCELERATOR_TYPE": "v5p-32", "TPU_WORKER_ID": "-1",
     "TPU_HOST_BOUNDS": "1,1,4"},
    {},  # no TPU at all -> clean error
    {"TPU_ACCELERATOR_TYPE": "v5p-32", "TPU_WORKER_ID": "2",
     "TPU_HOST_BOUNDS": "1,1,4", "TPU_CHIPS_PER_HOST_BOUNDS": "2,2,1"},
    {"TPU_ACCELERATOR_TYPE": "v5litepod-8"},
    {"TPU_ACCELERATOR_TYPE": "weird-128"},
]


@pytest.mark.parametrize("env", FAKE_CASES, ids=lambda e: str(sorted(e.values())) or "empty")
def test_native_and_python_probes_agree(native_lib, env):
    native = _with_env(env, lambda: _probe_native(native_lib))
    python = _with_env(env, lambda: _probe_python())
    if "error" in native or "error" in python:
        assert "error" in native and "error" in python
        assert native["error"] == python["error"]
        return
    # device_path entries may differ on the real backend (native scans /dev
    # directly); compare everything else exactly.
    def strip(d):
        d = dict(d)
        d["chips"] = [{k: v for k, v in c.items() if k != "device_path"}
                      for c in d["chips"]]
        return d
    assert strip(native) == strip(python)


def test_fake_probe_v5p_worker3(native_lib):
    p = _with_env({"TPUTOPO_FAKE": "v5p:2x2x4@3"}, lambda: probe_host())
    assert p.ok and p.backend == "fake"
    assert p.generation == "v5p"
    assert p.slice_dims == (2, 2, 4)
    assert p.host_bounds == (2, 2, 1)
    assert p.worker_id == 3
    assert p.host_coord == (0, 0, 3)  # 4 hosts along z
    assert p.local_chip_coords() == [(0, 0, 3), (0, 1, 3), (1, 0, 3), (1, 1, 3)]
    assert p.chips[0]["device_path"] == "/dev/accel0"


def test_probe_topology_integration():
    p = _with_env({"TPUTOPO_FAKE": "v5p:2x2x4"}, lambda: probe_host(prefer_native=False))
    topo = p.topology()
    assert topo.num_chips == 16
    assert topo.generation.name == "v5p"
    for c in p.local_chip_coords():
        assert c in topo.chips


def test_error_probe_is_clean():
    p = _with_env({}, lambda: probe_host(prefer_native=False))
    assert not p.ok
    assert "TPU_ACCELERATOR_TYPE" in p.error


def test_shim_matches_python_generations(native_lib):
    """The C++ table must stay in sync with generations.py."""
    for name, env_spec in [("v4", "v4:2x2x2"), ("v5p", "v5p:2x2x4"),
                           ("v5e", "v5e:4x4"), ("v6e", "v6e:4x4")]:
        native = _with_env({"TPUTOPO_FAKE": env_spec}, lambda: _probe_native(native_lib))
        g = GENERATIONS[name]
        assert native["generation"] == name
        assert native["ndims"] == g.ndims
        assert native["cores_per_chip"] == g.cores_per_chip
        assert tuple(native["host_bounds"]) == tuple(
            min(b, d) for b, d in zip(g.host_bounds, native["slice_dims"])
        )


def test_real_backend_with_multi_host_env(native_lib):
    env = {"TPU_ACCELERATOR_TYPE": "v5p-32", "TPU_WORKER_ID": "2",
           "TPU_HOST_BOUNDS": "1,1,4", "TPU_CHIPS_PER_HOST_BOUNDS": "2,2,1"}
    p = _with_env(env, lambda: probe_host(prefer_native=False))
    assert p.ok
    assert p.slice_dims == (2, 2, 4)
    assert p.host_coord == (0, 0, 2)
    assert p.local_chip_coords() == [(0, 0, 2), (0, 1, 2), (1, 0, 2), (1, 1, 2)]
