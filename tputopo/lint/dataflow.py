"""Worklist fixpoint framework over :mod:`tputopo.lint.cfg` graphs.

One engine, two lattices in practice:

- **must** analyses (lockset): facts are sets that shrink at joins —
  ``join`` is intersection, and an unvisited predecessor contributes
  nothing (the engine seeds only the entry node and propagates, so a
  node's input is the join over *visited* predecessors; by the fixpoint
  every reachable predecessor has been visited, which is exactly the
  must-over-all-paths semantics).
- **may** analyses (effect taint): ``join`` is union.

Interprocedural composition stays the checkers' job: they compute
per-function summaries with one intraprocedural pass each, then iterate
caller rescans over the existing call graph (:mod:`callgraph`) — the
infer-style summary worklist the whole-program rules already use.

Facts must be immutable values with ``==`` (frozensets, tuples);
``transfer`` returns a NEW fact.  The engine iterates in node creation
order (a reverse-postorder-ish order for the structured graphs the
builder emits), with a hard iteration backstop so a buggy transfer can
fail loudly instead of hanging a lint run.
"""

from __future__ import annotations

from typing import Callable, Generic, Hashable, TypeVar

from tputopo.lint.cfg import CFG, CFGNode

__all__ = ["ForwardAnalysis", "run_forward"]

F = TypeVar("F", bound=Hashable)


class ForwardAnalysis(Generic[F]):
    """Subclass (or duck-type) with ``entry_fact``, ``join`` and
    ``transfer``."""

    def entry_fact(self) -> F:
        raise NotImplementedError

    def join(self, a: F, b: F) -> F:
        raise NotImplementedError

    def transfer(self, node: CFGNode, fact: F) -> F:
        raise NotImplementedError


def run_forward(cfg: CFG, analysis: ForwardAnalysis,
                visit: Callable[[CFGNode, object], None] | None = None,
                ) -> dict[int, object]:
    """Run ``analysis`` to fixpoint; returns ``{node.idx: input fact}``
    for every reachable node.  ``visit(node, in_fact)`` — when given —
    is called exactly once per reachable node AFTER convergence, in node
    order, with the converged input fact: the reporting pass, separated
    so findings are emitted once however many times the worklist
    revisited a node."""
    in_facts: dict[int, object] = {cfg.entry.idx: analysis.entry_fact()}
    out_facts: dict[int, object] = {}
    work = [cfg.entry]
    # Loops converge in a handful of rounds on these lattices; the
    # backstop turns a non-monotone transfer into a loud failure.
    budget = 64 * max(1, len(cfg.nodes))
    while work:
        budget -= 1
        if budget < 0:
            raise RuntimeError("dataflow fixpoint did not converge "
                               f"({len(cfg.nodes)} nodes)")
        node = work.pop()
        fact = analysis.transfer(node, in_facts[node.idx])
        if node.idx in out_facts and out_facts[node.idx] == fact:
            continue
        out_facts[node.idx] = fact
        for succ in node.all_succs():
            if succ.idx in in_facts:
                merged = analysis.join(in_facts[succ.idx], fact)
            else:
                merged = fact
            if succ.idx not in in_facts or merged != in_facts[succ.idx]:
                in_facts[succ.idx] = merged
                work.append(succ)
    if visit is not None:
        for node in cfg.nodes:
            if node.idx in in_facts:
                visit(node, in_facts[node.idx])
    return in_facts
