"""tputopo.lint — checker fixtures, waiver grammar, CLI exit codes, and
the whole-repo-clean meta-test that pins the contract for future PRs.

Each checker gets true-positive fixtures (a seeded violation must be
found) and false-positive fixtures (the corrected form must pass) — the
acceptance shape from ISSUE 7.  Fixtures are in-memory sources fed
through the same LintRun the CLI uses, with repo-shaped relpaths so the
per-rule scoping applies exactly as in a real run.
"""

from __future__ import annotations

import subprocess
import sys
import textwrap
from pathlib import Path

from tputopo.lint import (ClockDisciplineChecker, DeterminismChecker,
                          LockGuardChecker, NocopyChecker, SingleDefChecker,
                          default_checkers, run_lint)
from tputopo.lint.core import WAIVER_RULE, LintRun

REPO_ROOT = Path(__file__).resolve().parents[1]


def lint_sources(checkers, *sources: tuple[str, str]):
    """Run ``checkers`` over (relpath, source) fixtures; return
    (active findings, run).  The waiver universe is every default rule —
    a fixture (or a real repo file) may carry waivers for rules outside
    the subset under test, exactly like a ``--select`` run."""
    run = LintRun(checkers,
                  known_rules={c.rule for c in default_checkers()})
    for relpath, src in sources:
        run.add_source(relpath, textwrap.dedent(src))
    return run.finish(), run


# ---- determinism -------------------------------------------------------------

class TestDeterminismChecker:
    def test_wall_clock_call_in_sim_is_flagged(self):
        findings, _ = lint_sources(
            [DeterminismChecker()],
            ("tputopo/sim/fixture.py", """\
                import time
                def now():
                    return time.time()
            """))
        assert [f.rule for f in findings] == ["determinism"]
        assert "time.time" in findings[0].message
        assert findings[0].line == 3

    def test_injected_clock_default_is_the_escape_hatch(self):
        findings, _ = lint_sources(
            [DeterminismChecker()],
            ("tputopo/sim/fixture.py", """\
                import time
                def now(clock=time.time):
                    return clock()
            """))
        assert findings == []

    def test_unseeded_rng_flagged_seeded_allowed(self):
        findings, _ = lint_sources(
            [DeterminismChecker()],
            ("tputopo/chaos/fixture.py", """\
                import random
                import numpy as np
                bad = random.Random()
                worse = random.random()
                ambient = np.random.default_rng()
                ok = random.Random(0x7E7)
                also_ok = np.random.Generator(np.random.Philox(
                    seed=np.random.SeedSequence(entropy=(1, 2))))
                seeded = np.random.default_rng(0)
            """))
        assert [f.line for f in findings] == [3, 4, 5]
        assert all(f.rule == "determinism" for f in findings)

    def test_out_of_scope_module_not_checked(self):
        findings, _ = lint_sources(
            [DeterminismChecker()],
            ("tputopo/extender/fixture.py",
             "import time\nt = time.time()\n"))
        assert findings == []

    def test_defrag_planner_in_scope_controller_not(self):
        src = "import time\nt = time.sleep(1)\n"
        flagged, _ = lint_sources([DeterminismChecker()],
                                  ("tputopo/defrag/planner.py", src))
        clean, _ = lint_sources([DeterminismChecker()],
                                ("tputopo/defrag/controller.py", src))
        assert len(flagged) == 1 and clean == []


# ---- clock discipline --------------------------------------------------------

class TestClockDisciplineChecker:
    def test_clock_taking_fn_calling_wall_clock_is_flagged(self):
        findings, _ = lint_sources(
            [ClockDisciplineChecker()],
            ("tputopo/extender/fixture.py", """\
                import time
                def retry(fn, clock):
                    deadline = time.monotonic() + 5
                    return fn()
            """))
        assert [f.rule for f in findings] == ["clock"]
        assert "time.monotonic" in findings[0].message

    def test_clock_used_properly_is_clean(self):
        findings, _ = lint_sources(
            [ClockDisciplineChecker()],
            ("tputopo/extender/fixture.py", """\
                import time
                def retry(fn, clock=time.time, sleep=time.sleep):
                    deadline = clock() + 5
                    sleep(0.1)
                    return fn()
            """))
        assert findings == []

    def test_nested_fn_with_own_clock_param_owns_its_body(self):
        findings, _ = lint_sources(
            [ClockDisciplineChecker()],
            ("tputopo/extender/fixture.py", """\
                import time
                def outer(clock):
                    def inner(clock):
                        return clock()
                    return inner(clock) + time.time()
            """))
        # exactly one finding, attributed to outer's body
        assert len(findings) == 1 and findings[0].line == 5


# ---- nocopy ------------------------------------------------------------------

class TestNocopyChecker:
    def check(self, body, relpath="tputopo/extender/fixture.py"):
        findings, _ = lint_sources([NocopyChecker()], (relpath, body))
        return findings

    def test_mutating_a_named_nocopy_result(self):
        findings = self.check("""\
            def f(api):
                pod = api.get_nocopy("pods", "p0")
                pod["spec"]["nodeName"] = "n1"
        """)
        assert [f.rule for f in findings] == ["nocopy"]

    def test_mutating_elements_of_a_nocopy_list(self):
        findings = self.check("""\
            def f(api):
                for o in api.list_nocopy("pods"):
                    o["metadata"]["labels"] = {}
        """)
        assert len(findings) == 1

    def test_mutating_method_call_and_direct_call_result(self):
        findings = self.check("""\
            def f(api, h):
                pod = h.fetch()
                pod["metadata"]["annotations"].update(x="1")
                api.get_nocopy("pods", "p")["status"] = {}
        """)
        assert len(findings) == 2

    def test_storing_onto_self_and_returning_escape(self):
        findings = self.check("""\
            class S:
                def grab(self, api):
                    self.pod = api.get_nocopy("pods", "p0")
                def hand_out(self, api):
                    return api.list_nocopy("pods")
        """)
        assert len(findings) == 2

    def test_owner_module_may_return_nocopy_views(self):
        findings = self.check("""\
            def get(api):
                return api.get_nocopy("pods", "p0")
        """, relpath="tputopo/sim/engine.py")
        assert findings == []

    def test_read_only_use_and_copying_api_are_clean(self):
        findings = self.check("""\
            import copy
            def f(api):
                pod = api.get_nocopy("pods", "p0")
                name = pod["metadata"]["name"]
                mine = copy.deepcopy(pod)
                mine["spec"]["nodeName"] = "n1"
                pods = api.list("pods")
                pods[0]["x"] = 1
                pod = {}
                pod["now"] = "rebound, fine"
        """)
        assert findings == []


# ---- lock guard --------------------------------------------------------------

_LOCK_FIXTURE = """\
    import threading

    class Store:
        def __init__(self):
            self._lock = threading.Lock()
            self._cond = threading.Condition(self._lock)
            self._store = {{}}  # guarded-by: _lock|_cond
            self._state = None  # guarded-by: _lock (writes)

        def accessor(self):
            {access}
"""


class TestLockGuardChecker:
    def check(self, access):
        findings, _ = lint_sources(
            [LockGuardChecker()],
            ("tputopo/k8s/fixture.py",
             textwrap.dedent(_LOCK_FIXTURE).format(access=access)))
        return findings

    def test_unlocked_access_is_flagged(self):
        findings = self.check('self._store["a"] = 1')
        assert [f.rule for f in findings] == ["lock"]
        assert "_store" in findings[0].message

    def test_with_lock_and_condition_alias_are_clean(self):
        assert self.check(
            'with self._lock:\n'
            '                self._store["a"] = 1') == []
        assert self.check(
            'with self._cond:\n'
            '                self._store["a"] = 1') == []

    def test_writes_only_mode(self):
        assert self.check('return self._state') == []      # lock-free read
        flagged = self.check('self._state = 2')            # serialized write
        assert len(flagged) == 1 and "(write)" in flagged[0].message

    def test_holds_lock_annotation_on_helper(self):
        findings, _ = lint_sources(
            [LockGuardChecker()],
            ("tputopo/k8s/fixture.py", """\
                import threading

                class Store:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._store = {}  # guarded-by: _lock

                    def _helper(self):  # holds-lock: _lock
                        return self._store

                    def caller(self):
                        with self._lock:
                            return self._helper()
            """))
        assert findings == []

    def test_nested_function_drops_held_locks(self):
        findings, _ = lint_sources(
            [LockGuardChecker()],
            ("tputopo/k8s/fixture.py", """\
                import threading

                class Store:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._store = {}  # guarded-by: _lock

                    def spawn(self):
                        with self._lock:
                            def later():
                                return self._store
                            return later
            """))
        assert len(findings) == 1  # the closure runs after release


# ---- single-def --------------------------------------------------------------

_CANON = (("tputopo/canon.py", ("SCHEMA", "KEEP")),)


class TestSingleDefChecker:
    def test_duplicated_literal_and_shadow_name(self):
        findings, _ = lint_sources(
            [SingleDefChecker(canon=_CANON)],
            ("tputopo/canon.py",
             'SCHEMA = "x.sim/v9"\nKEEP = ("a", "b")\n'),
            ("tputopo/emitter.py",
             'def emit():\n    return {"schema": "x.sim/v9"}\n'),
            ("tputopo/shadow.py", 'KEEP = ("a",)\n'))
        rules = sorted((f.path, f.rule) for f in findings)
        assert rules == [("tputopo/emitter.py", "single-def"),
                         ("tputopo/shadow.py", "single-def")]

    def test_importing_the_constant_is_clean(self):
        findings, _ = lint_sources(
            [SingleDefChecker(canon=_CANON)],
            ("tputopo/canon.py", 'SCHEMA = "x.sim/v9"\n'),
            ("tputopo/emitter.py",
             "from tputopo.canon import SCHEMA\n"
             "def emit():\n    return {'schema': SCHEMA}\n"))
        assert findings == []

    def test_real_repo_canon_resolves(self):
        """The default canon must keep matching the real modules — if the
        schema constants move, the checker config moves with them."""
        checker = SingleDefChecker()
        run = LintRun([checker],
                      known_rules={c.rule for c in default_checkers()})
        report = REPO_ROOT / "tputopo/sim/report.py"
        server = REPO_ROOT / "tputopo/extender/server.py"
        run.add_path(report, "tputopo/sim/report.py")
        run.add_path(server, "tputopo/extender/server.py")
        # Seed one duplicate to prove values were extracted from the canon.
        run.add_source("tputopo/dup.py", 's = "tputopo.sim/v4"\n')
        findings = run.finish()
        assert [f.path for f in findings] == ["tputopo/dup.py"]
        assert "SCHEMA_CHAOS" in findings[0].message

    def test_class_attribute_canon_value_is_extracted(self):
        """``_PREFIX`` is a class attribute of the HTTP handler, not a
        module-level constant — duplicating its value must still be a
        finding (it was silently unchecked before)."""
        checker = SingleDefChecker()
        run = LintRun([checker],
                      known_rules={c.rule for c in default_checkers()})
        run.add_path(REPO_ROOT / "tputopo/sim/report.py",
                     "tputopo/sim/report.py")
        run.add_path(REPO_ROOT / "tputopo/extender/server.py",
                     "tputopo/extender/server.py")
        run.add_source("tputopo/dup.py", 'p = "tputopo_extender"\n')
        findings = run.finish()
        assert [f.path for f in findings] == ["tputopo/dup.py"]
        assert "_PREFIX" in findings[0].message


# ---- waivers -----------------------------------------------------------------

class TestWaivers:
    def test_waiver_suppresses_its_rule_on_its_line(self):
        findings, run = lint_sources(
            default_checkers(),
            ("tputopo/sim/fixture.py", """\
                import time
                t = time.time()  # tpulint: disable=determinism -- fixture telemetry
            """))
        assert findings == []
        assert len(run.waived) == 1

    def test_standalone_waiver_covers_next_line(self):
        findings, _ = lint_sources(
            default_checkers(),
            ("tputopo/sim/fixture.py", """\
                import time
                # tpulint: disable=determinism -- fixture telemetry
                t = time.time()
            """))
        assert findings == []

    def test_missing_reason_is_rejected(self):
        findings, _ = lint_sources(
            default_checkers(),
            ("tputopo/sim/fixture.py", """\
                import time
                t = time.time()  # tpulint: disable=determinism
            """))
        # the violation stays active AND the waiver itself is flagged
        rules = sorted(f.rule for f in findings)
        assert rules == ["determinism", WAIVER_RULE]
        assert any("reason" in f.message for f in findings)

    def test_unknown_rule_and_unused_waiver_are_flagged(self):
        findings, _ = lint_sources(
            default_checkers(),
            ("tputopo/sim/fixture.py", """\
                x = 1  # tpulint: disable=bogus-rule -- because
                y = 2  # tpulint: disable=determinism -- suppresses nothing
            """))
        msgs = sorted(f.message for f in findings)
        assert len(findings) == 2
        assert any("unknown rule" in m for m in msgs)
        assert any("unused waiver" in m for m in msgs)

    def test_wrong_rule_waiver_does_not_suppress(self):
        findings, _ = lint_sources(
            default_checkers(),
            ("tputopo/sim/fixture.py", """\
                import time
                t = time.time()  # tpulint: disable=nocopy -- wrong rule
            """))
        assert sorted(f.rule for f in findings) == ["determinism",
                                                    WAIVER_RULE]

    def test_selected_subset_keeps_other_rules_waivers_legal(self):
        """Under --select, a waiver for a deselected rule is neither
        unknown (the rule exists) nor unused (its checker never ran)."""
        src = ("tputopo/sim/fixture.py", """\
            import time
            t = time.time()  # tpulint: disable=determinism -- telemetry
        """)
        all_rules = {c.rule for c in default_checkers()}
        run = LintRun([NocopyChecker()], known_rules=all_rules)
        run.add_source(src[0], textwrap.dedent(src[1]))
        assert run.finish() == []


# ---- CLI ---------------------------------------------------------------------

def _cli(*args, cwd=REPO_ROOT):
    import os
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO_ROOT) + os.pathsep + \
        env.get("PYTHONPATH", "")
    return subprocess.run([sys.executable, "-m", "tputopo.lint", *args],
                          cwd=cwd, capture_output=True, text=True,
                          timeout=120, env=env)


class TestCli:
    def test_exit_0_on_clean_file(self, tmp_path):
        clean = tmp_path / "clean.py"
        clean.write_text("x = 1\n")
        res = _cli(str(clean))
        assert res.returncode == 0, res.stdout + res.stderr

    def test_exit_1_on_findings(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("x = 1  # tpulint: disable=nocopy\n")  # reasonless
        res = _cli(str(bad))
        assert res.returncode == 1
        assert "waiver must carry a reason" in res.stdout

    def test_exit_2_on_usage_error(self, tmp_path):
        assert _cli("--select", "bogus").returncode == 2
        assert _cli(str(tmp_path / "missing.py")).returncode == 2

    def test_list_rules_names_all_checkers(self):
        res = _cli("--list-rules")
        assert res.returncode == 0
        for rule in ("determinism", "clock", "nocopy", "lock",
                     "single-def", "waiver",
                     "lockset", "release-on-all-paths", "effect-purity",
                     "hot-path-scan",
                     "ownership-flow", "kill-switch-audit",
                     "schema-additivity"):
            assert rule in res.stdout

    def test_select_subset_runs_clean_on_repo(self):
        """Scoped runs must not manufacture waiver findings for the
        deselected rules' reasoned waivers (regression: `--select
        nocopy,lock` flagged the determinism waivers as unknown)."""
        res = _cli("--select", "nocopy,lock")
        assert res.returncode == 0, res.stdout + res.stderr

    def test_directory_outside_repo_root_is_linted_not_crashed(self, tmp_path):
        (tmp_path / "sub").mkdir()
        (tmp_path / "sub" / "ok.py").write_text("x = 1\n")
        res = _cli(str(tmp_path / "sub"))
        assert res.returncode == 0, res.stdout + res.stderr
        assert "Traceback" not in res.stderr


# ---- the contract ------------------------------------------------------------

def test_whole_repo_runs_clean():
    """``python -m tputopo.lint`` exits 0 on this tree: the standing
    contract.  A future PR that trips a checker either fixes the
    violation or waives it with a reason — never deletes this test."""
    findings, run = run_lint(root=REPO_ROOT)
    assert findings == [], "\n".join(f.render() for f in findings)
    # the seventeen project checkers were all active
    assert {c.rule for c in run.checkers} == {
        "determinism", "clock", "nocopy", "lock", "single-def",
        "lock-order", "clock-flow", "nocopy-flow", "except-contract",
        "counter-drift",
        "lockset", "release-on-all-paths", "effect-purity",
        "hot-path-scan",
        "ownership-flow", "kill-switch-audit", "schema-additivity"}
    # every waiver in the tree carries a reason (reasonless ones would be
    # active findings above; this pins the invariant explicitly)
    for mod in run.modules:
        for w in mod.waivers:
            assert w.reason, f"{mod.relpath}:{w.line} waiver lacks a reason"


def test_whole_repo_waiver_budget_is_pinned():
    """The tree's waivers are a BUDGET, not a drift channel: every one is
    enumerated here by rule with its justification class.  Adding a
    waiver means adding it to this table in the same PR — so review sees
    each new escape, and stale entries fail loudly when removed."""
    _, run = run_lint(root=REPO_ROOT)
    by_rule: dict[str, int] = {}
    for mod in run.modules:
        for w in mod.waivers:
            assert w.reason, f"{mod.relpath}:{w.line} waiver lacks a reason"
            for rule in w.rules:
                by_rule[rule] = by_rule.get(rule, 0) + 1
    assert by_rule == {
        # 2 sim CLI wall timings + 2 engine run_trace wall stamps: the
        # documented throughput-block exception.
        "determinism": 4,
        # 2 deliberate-mutation digest-guard tests (tests/test_k8s.py).
        "nocopy": 2,
        # bind read-back boundary (scheduler), startup recovery boundary
        # (server main), watch-thread main loop (informer), do_POST
        # fail-closed 503 boundary (server).
        "except-contract": 4,
        # ClusterState._list, state.list_pods_nocopy (the shared shim —
        # moved from defrag.planner when the GC sweep joined its
        # consumers), _gang_members: the three documented read-only
        # copy=False handout shims.
        "nocopy-flow": 3,
        # stdlib serve_forever Thread target: request handling enters
        # repo code at the do_* handlers, which ARE enumerated roots.
        "lockset": 1,
        # The amortized full-store scans, each with its argument:
        # state.full_sync — the ONE shared counted cache-miss/fallback
        # rebuild behind every delta-maintained state (it replaced the 2
        # scheduler _state fallback waivers AND BaselinePolicy.place's
        # invalidate-drop sync, the ROADMAP fleet-scale bottleneck this
        # budget tracked as debt until the baselines folded deltas);
        # and the defrag-period demand listing.  The GC expiry-scan
        # waiver was DELETED by the fleet hot-path PR (list_assignments
        # index + watermark); the preemption VICTIM-LISTING waiver is
        # DELETED by the contract-lint PR — _try_preempt reads the same
        # assignment-key index (every victim holds chips, so its pod
        # carries the chip-group annotation; plan_preemption's
        # fail-closed default protects anything outside it), with the
        # whole-store shim only as the index-less-reader fallback bound
        # at construction; the gated preemption-PLANNING state-sync
        # waiver is DELETED by the XL hot-path PR — the plan phase
        # reuses the policy's delta-maintained planning state
        # (SimEngine.PLAN_STATE_REUSE), with the off-path routed through
        # full_sync's single already-counted site.
        "hot-path-scan": 2,
    }, by_rule
    # 16 waived findings total (17 before the XL hot-path PR deleted
    # the preemption-planning state-sync waiver; 18 before the
    # contract-lint PR deleted the preemption victim-listing waiver; 19
    # before the fleet hot-path PR deleted the GC expiry-scan waiver;
    # 21 before the incremental-baseline PR deleted the BaselinePolicy
    # full-drop waiver and collapsed the two scheduler cache-miss
    # fallbacks onto full_sync's single site): the waivers above each
    # suppress exactly one finding (none is stale — core flags unused
    # waivers).
    assert len(run.waived) == 16, [f.render() for f in run.waived]


# ---- call graph (ISSUE 8 tentpole substrate) ---------------------------------

from tputopo.lint.callgraph import CallGraph  # noqa: E402
from tputopo.lint.clockflow import ClockFlowChecker  # noqa: E402
from tputopo.lint.counters import CounterDriftChecker  # noqa: E402
from tputopo.lint.excepts import ExceptContractChecker  # noqa: E402
from tputopo.lint.lockorder import LockOrderChecker  # noqa: E402
from tputopo.lint.nocopyflow import NocopyFlowChecker  # noqa: E402
from tputopo.lint.core import Module  # noqa: E402


def build_graph(*sources: tuple[str, str]) -> CallGraph:
    return CallGraph.build([Module.parse(rel, textwrap.dedent(src))
                            for rel, src in sources])


def resolve_in(graph: CallGraph, relpath: str, qualname: str):
    """All resolved callee displays of one function, in source order."""
    fn = graph.functions[(relpath, qualname)]
    return [s.callee.display if s.callee else None
            for s in graph.callees(fn)]


class TestCallGraph:
    def test_aliased_imports_resolve(self):
        g = build_graph(
            ("tputopo/a.py", """\
                def helper():
                    return 1
            """),
            ("tputopo/b.py", """\
                from tputopo.a import helper as h
                import tputopo.a as mod
                def caller():
                    h()
                    mod.helper()
            """))
        assert resolve_in(g, "tputopo/b.py", "caller") == [
            "tputopo/a.py::helper", "tputopo/a.py::helper"]

    def test_reexport_chain_resolves(self):
        g = build_graph(
            ("tputopo/impl.py", "def f():\n    return 1\n"),
            ("tputopo/pkg/__init__.py", "from tputopo.impl import f\n"),
            ("tputopo/use.py", """\
                from tputopo.pkg import f
                def caller():
                    f()
            """))
        assert resolve_in(g, "tputopo/use.py", "caller") == [
            "tputopo/impl.py::f"]

    def test_self_method_and_class_hierarchy(self):
        g = build_graph(
            ("tputopo/c.py", """\
                class Base:
                    def shared(self):
                        return 1

                class Child(Base):
                    def caller(self):
                        self.shared()
                        super().shared()
            """))
        # (the inner ``super()`` call itself is an unresolved site)
        assert [c for c in resolve_in(g, "tputopo/c.py", "Child.caller")
                if c is not None] == [
            "tputopo/c.py::Base.shared", "tputopo/c.py::Base.shared"]

    def test_nested_class_methods_are_defs(self):
        g = build_graph(
            ("tputopo/n.py", """\
                class Outer:
                    class Inner:
                        def m(self):
                            return self.m2()
                        def m2(self):
                            return 2
            """))
        assert resolve_in(g, "tputopo/n.py", "Outer.Inner.m") == [
            "tputopo/n.py::Outer.Inner.m2"]

    def test_decorator_passthrough(self):
        g = build_graph(
            ("tputopo/d.py", """\
                import functools

                @functools.lru_cache(maxsize=8)
                def cached():
                    return 1

                def caller():
                    cached()
            """))
        assert resolve_in(g, "tputopo/d.py", "caller") == [
            "tputopo/d.py::cached"]

    def test_nested_function_resolution(self):
        g = build_graph(
            ("tputopo/f.py", """\
                def outer():
                    def inner():
                        return 1
                    return inner()
            """))
        assert resolve_in(g, "tputopo/f.py", "outer") == [
            "tputopo/f.py::outer.<locals>.inner"]

    def test_attr_type_inference_param_and_factory(self):
        g = build_graph(
            ("tputopo/api.py", """\
                class Api:
                    def get(self):
                        return 1
            """),
            ("tputopo/user.py", """\
                from tputopo.api import Api

                def make() -> Api:
                    return Api()

                class User:
                    def __init__(self, api: Api, other=None):
                        self.api = api
                        self.made = make()
                        self.other = other
                    def caller(self):
                        self.api.get()
                        self.made.get()
                        self.other.get()
            """))
        got = resolve_in(g, "tputopo/user.py", "User.caller")
        assert got == ["tputopo/api.py::Api.get", "tputopo/api.py::Api.get",
                       None]  # the untyped attribute stays unresolved

    def test_conflicting_attr_assignments_block_resolution(self):
        g = build_graph(
            ("tputopo/x.py", """\
                class A:
                    def m(self):
                        return 1
                class B:
                    def m(self):
                        return 2
                class Holder:
                    def __init__(self, a: A, b: B, flip):
                        self.x = a
                        if flip:
                            self.x = b
                    def caller(self):
                        self.x.m()
            """))
        assert resolve_in(g, "tputopo/x.py", "Holder.caller") == [None]

    def test_dynamic_calls_are_conservatively_unresolved(self):
        """getattr/dict-dispatch/call-result calls must neither crash
        the build nor resolve to anything."""
        g = build_graph(
            ("tputopo/dyn.py", """\
                def caller(table, obj):
                    getattr(obj, "anything")()
                    table["k"]()
                    (lambda: 1)()
                    obj.method().chained()
            """))
        assert all(c is None for c in
                   resolve_in(g, "tputopo/dyn.py", "caller"))


# ---- lock-order --------------------------------------------------------------

def run_checkers(checkers, *sources):
    findings, run = lint_sources(
        checkers, *sources)
    return findings


class TestLockOrderChecker:
    def test_opposite_nesting_through_call_edge_is_a_cycle(self):
        findings = run_checkers(
            [LockOrderChecker()],
            ("tputopo/k8s/fix.py", """\
                import threading

                class S:
                    def __init__(self):
                        self._a = threading.Lock()
                        self._b = threading.Lock()

                    def one(self):
                        with self._a:
                            self._take_b()

                    def _take_b(self):
                        with self._b:
                            return 1

                    def two(self):
                        with self._b:
                            with self._a:
                                return 2
            """))
        assert [f.rule for f in findings] == ["lock-order"]
        assert "cycle" in findings[0].message
        assert "S._a" in findings[0].message and "S._b" in findings[0].message

    def test_consistent_nesting_is_clean(self):
        findings = run_checkers(
            [LockOrderChecker()],
            ("tputopo/k8s/fix.py", """\
                import threading

                class S:
                    def __init__(self):
                        self._a = threading.Lock()
                        self._b = threading.Lock()

                    def one(self):
                        with self._a:
                            with self._b:
                                return 1

                    def two(self):
                        with self._a:
                            self._take_b()

                    def _take_b(self):
                        with self._b:
                            return 2
            """))
        assert findings == []

    def test_nonreentrant_reacquisition_direct_and_via_call(self):
        findings = run_checkers(
            [LockOrderChecker()],
            ("tputopo/k8s/fix.py", """\
                import threading

                class S:
                    def __init__(self):
                        self._l = threading.Lock()
                        self._r = threading.RLock()

                    def direct(self):
                        with self._l:
                            with self._l:
                                return 1

                    def via_call(self):
                        with self._l:
                            self.helper()

                    def helper(self):
                        with self._l:
                            return 2

                    def reentrant_ok(self):
                        with self._r:
                            with self._r:
                                return 3
            """))
        msgs = [f.message for f in findings]
        assert len(findings) == 2
        assert any("self-deadlock" in m and "re-acquisition" in m
                   for m in msgs)
        assert any("via_call" not in m and "helper" in m for m in msgs)

    def test_condition_aliases_its_base_lock(self):
        findings = run_checkers(
            [LockOrderChecker()],
            ("tputopo/k8s/fix.py", """\
                import threading

                class S:
                    def __init__(self):
                        self._l = threading.RLock()
                        self._cond = threading.Condition(self._l)

                    def ok(self):
                        with self._l:
                            with self._cond:
                                return 1
            """))
        # _cond IS _l (reentrant) — no edge, no self-deadlock.
        assert findings == []

    def test_declared_order_violation_and_unknown_name(self):
        findings = run_checkers(
            [LockOrderChecker()],
            ("tputopo/k8s/fix.py", """\
                import threading

                # lock-order: S._outer > S._inner > S._ghost

                class S:
                    def __init__(self):
                        self._outer = threading.Lock()
                        self._inner = threading.Lock()

                    def backwards(self):
                        with self._inner:
                            with self._outer:
                                return 1
            """))
        rules = [f.rule for f in findings]
        assert rules.count("lock-order") == len(rules)
        msgs = " | ".join(f.message for f in findings)
        assert "unknown lock" in msgs and "'S._ghost'" in msgs
        assert "while holding" in msgs  # the order violation itself

    def test_holds_lock_annotation_seeds_held_set(self):
        findings = run_checkers(
            [LockOrderChecker()],
            ("tputopo/k8s/fix.py", """\
                import threading

                # lock-order: S._a > S._b

                class S:
                    def __init__(self):
                        self._a = threading.Lock()
                        self._b = threading.Lock()

                    def helper(self):  # holds-lock: _b
                        with self._a:
                            return 1
            """))
        assert len(findings) == 1
        assert "declared lock-order" in findings[0].message

    def test_real_tree_declared_order_matches_derived_edges(self):
        """The canonical directive in scheduler.py must stay consistent
        with the acquisition edges actually derivable from the tree —
        run the real checker over the real repo files it audits."""
        findings, _ = lint_sources(
            [LockOrderChecker()],
            *[(rel, (REPO_ROOT / rel).read_text())
              for rel in ("tputopo/extender/scheduler.py",
                          "tputopo/k8s/fakeapi.py",
                          "tputopo/k8s/informer.py")])
        assert findings == [], [f.render() for f in findings]


# ---- clock-flow --------------------------------------------------------------

class TestClockFlowChecker:
    def test_clock_taking_fn_reaching_wall_via_helper(self):
        findings = run_checkers(
            [ClockFlowChecker()],
            ("tputopo/extender/fix.py", """\
                import time

                def helper():
                    return time.time()

                def outer(clock):
                    return helper()
            """))
        assert [f.rule for f in findings] == ["clock-flow"]
        assert findings[0].line == 4  # attached at the wall-clock site
        assert "outer" in findings[0].message

    def test_helper_without_virtual_time_callers_is_clean(self):
        findings = run_checkers(
            [ClockFlowChecker()],
            ("tputopo/extender/fix.py", """\
                import time

                def helper():
                    return time.time()

                def outer():
                    return helper()
            """))
        assert findings == []

    def test_deterministic_module_reaching_wall_cross_module(self):
        findings = run_checkers(
            [ClockFlowChecker()],
            ("tputopo/extender/util.py", """\
                import time
                def stamp():
                    return time.perf_counter()
            """),
            ("tputopo/sim/fix.py", """\
                from tputopo.extender.util import stamp
                def tick():
                    return stamp()
            """))
        assert len(findings) == 1
        assert findings[0].path == "tputopo/extender/util.py"
        assert "tputopo/sim/fix.py::tick" in findings[0].message

    def test_propagation_stops_at_clock_taking_helper(self):
        """A helper that itself takes clock re-promises virtual time:
        its wall call is the direct ``clock`` rule's finding, and this
        rule must not double-report it through the caller."""
        findings = run_checkers(
            [ClockFlowChecker()],
            ("tputopo/sim/fix.py", """\
                import time

                def helper(clock):
                    return time.time()

                def tick():
                    return helper(None)
            """))
        assert findings == []

    def test_injectable_wall_hook_is_the_fix_shape(self):
        findings = run_checkers(
            [ClockFlowChecker()],
            ("tputopo/extender/fix.py", """\
                import time

                class Verb:
                    def __init__(self, wall=time.perf_counter):
                        self._wall = wall
                    def serve(self):
                        return self._wall()
            """),
            ("tputopo/sim/fix.py", """\
                from tputopo.extender.fix import Verb
                def tick():
                    return Verb().serve()
            """))
        assert findings == []


# ---- nocopy-flow -------------------------------------------------------------

class TestNocopyFlowChecker:
    def check(self, *sources):
        findings, _ = lint_sources([NocopyFlowChecker()], *sources)
        return findings

    def test_copyfree_list_escape_is_flagged(self):
        findings = self.check(
            ("tputopo/extender/fix.py", """\
                def hand_out(api):
                    return api.list("pods", copy=False)
            """))
        assert [f.rule for f in findings] == ["nocopy-flow"]
        assert "escapes via return" in findings[0].message

    def test_laundered_result_mutation_caught_at_caller(self):
        findings = self.check(
            ("tputopo/sim/engine.py", """\
                def members(api):
                    return api.list_nocopy("pods")
            """),
            ("tputopo/extender/fix.py", """\
                from tputopo.sim.engine import members
                def bad(api):
                    for pod in members(api):
                        pod["spec"]["nodeName"] = "n1"
            """))
        # engine is an owner (returning is its contract); the caller's
        # mutation is the interprocedural finding.
        assert [f.path for f in findings] == ["tputopo/extender/fix.py"]
        assert "mutation" in findings[0].message

    def test_tainted_arg_into_param_mutating_callee(self):
        findings = self.check(
            ("tputopo/extender/fix.py", """\
                def scrub(pods):
                    pods.clear()

                def bad(api):
                    view = api.list("pods", copy=False)
                    scrub(view)
            """))
        msgs = [f.message for f in findings]
        assert any("mutates its 'pods' parameter" in m for m in msgs)

    def test_identity_helper_propagates_taint(self):
        findings = self.check(
            ("tputopo/extender/fix.py", """\
                def ident(x):
                    return x

                def bad(api):
                    pod = ident(api.get_nocopy("pods", "p"))
                    pod["spec"] = {}
            """))
        assert any("mutation" in f.message for f in findings)

    def test_classmethod_identity_helper_propagates_taint(self):
        findings = self.check(
            ("tputopo/extender/fix.py", """\
                class H:
                    @classmethod
                    def ident(cls, x):
                        return x

                def bad(api):
                    pod = H.ident(api.get_nocopy("pods", "p"))
                    pod["spec"] = {}
            """))
        assert any("mutation" in f.message for f in findings)

    def test_read_only_flow_and_copy_are_clean(self):
        findings = self.check(
            ("tputopo/extender/fix.py", """\
                import copy

                def reader(api):
                    names = [p["metadata"]["name"]
                             for p in api.list("pods", copy=False)]
                    mine = copy.deepcopy(api.list("pods", copy=False))
                    mine[0]["x"] = 1
                    return names
            """))
        assert findings == []


# ---- except-contract ---------------------------------------------------------

class TestExceptContractChecker:
    def check(self, *sources):
        findings, _ = lint_sources([ExceptContractChecker()], *sources)
        return findings

    def test_broad_catch_around_api_verb_is_flagged(self):
        findings = self.check(
            ("tputopo/extender/fix.py", """\
                def fetch(api):
                    try:
                        return api.get("pods", "p")
                    except Exception:
                        return None
            """))
        assert [f.rule for f in findings] == ["except-contract"]
        assert "over-broad" in findings[0].message

    def test_named_classified_catches_are_clean(self):
        findings = self.check(
            ("tputopo/extender/fix.py", """\
                from tputopo.k8s.fakeapi import Conflict, NotFound
                from tputopo.k8s.retry import ApiTimeout, ApiUnavailable

                def fetch(api):
                    try:
                        return api.get("pods", "p")
                    except NotFound:
                        return None
                    except (ApiUnavailable, Conflict):
                        return None
            """))
        assert findings == []

    def test_cross_module_raiser_classifies_try_body(self):
        findings = self.check(
            ("tputopo/k8s/errors.py", """\
                class ApiUnavailable(RuntimeError):
                    pass

                def flaky():
                    raise ApiUnavailable("nope")
            """),
            ("tputopo/defrag/fix.py", """\
                from tputopo.k8s.errors import flaky

                def leg():
                    try:
                        flaky()
                    except:
                        pass
            """))
        assert [f.path for f in findings] == ["tputopo/defrag/fix.py"]
        assert "<bare>" in findings[0].message

    def test_outside_control_plane_not_flagged(self):
        findings = self.check(
            ("tputopo/workloads/fix.py", """\
                def fetch(api):
                    try:
                        return api.get("x")
                    except Exception:
                        return None
            """))
        assert findings == []

    def test_broad_catch_without_fault_surface_is_clean(self):
        findings = self.check(
            ("tputopo/extender/fix.py", """\
                def parse(s):
                    try:
                        return int(s)
                    except Exception:
                        return 0
            """))
        assert findings == []

    def test_verb_reference_argument_classifies_retry_wrappers(self):
        findings = self.check(
            ("tputopo/extender/fix.py", """\
                def leg(self_, api):
                    try:
                        self_._api_call("get", api.get, "pods", "p")
                    except Exception:
                        return None
            """))
        assert len(findings) == 1


# ---- counter-drift -----------------------------------------------------------

_REGISTRY_FIXTURE = ("tputopo/obs/counters.py", """\
    COUNTERS = (
        "bind_requests",
        "ghost_counter",
    )
    COUNTER_PREFIXES = (
        "defrag_",
    )
    DEFRAG_LAZY_COUNTERS = ()
""")

_KEEP_FIXTURE = ("tputopo/sim/report.py", """\
    SCHEMA = "x/v0"
    SCHEDULER_COUNTER_KEEP = (
        "bind_requests",
        "never_incremented",
    )
""")


class TestCounterDriftChecker:
    def check(self, *sources):
        findings, _ = lint_sources([CounterDriftChecker()], *sources)
        return findings

    def test_unregistered_increment_is_flagged(self):
        findings = self.check(
            _REGISTRY_FIXTURE,
            ("tputopo/extender/fix.py", """\
                def verb(metrics):
                    metrics.inc("bind_requests")
                    metrics.inc("bind_requets")
            """))
        msgs = [f.message for f in findings]
        assert any("'bind_requets' is not registered" in m for m in msgs)
        assert not any("'bind_requests'" in m and "not registered" in m
                       for m in msgs)

    def test_dead_registration_and_dead_keep_entry(self):
        findings = self.check(
            _REGISTRY_FIXTURE, _KEEP_FIXTURE,
            ("tputopo/extender/fix.py", """\
                def verb(metrics):
                    metrics.inc("bind_requests")
            """))
        msgs = [f.message for f in findings]
        assert any("dead registered counter 'ghost_counter'" in m
                   for m in msgs)
        assert any("'never_incremented' is never incremented" in m
                   for m in msgs)
        # dead entries point at their own line inside the literal
        ghost = next(f for f in findings if "ghost_counter" in f.message)
        assert ghost.path == "tputopo/obs/counters.py" and ghost.line == 3

    def test_fstring_family_must_be_registered(self):
        findings = self.check(
            _REGISTRY_FIXTURE,
            ("tputopo/extender/fix.py", """\
                def verb(metrics, reason):
                    metrics.inc(f"defrag_{reason}")
                    metrics.inc(f"mystery_{reason}")
            """))
        msgs = [f.message for f in findings]
        assert any("'mystery_'" in m and "no registered prefix" in m
                   for m in msgs)
        assert not any("'defrag_'" in m and "no registered prefix" in m
                       for m in msgs)

    def test_ifexp_literals_both_checked(self):
        findings = self.check(
            _REGISTRY_FIXTURE,
            ("tputopo/extender/fix.py", """\
                def verb(metrics, ok):
                    metrics.inc("bind_requests" if ok else "oops")
            """))
        assert any("'oops' is not registered" in f.message
                   for f in findings)

    def test_dynamic_relay_is_conservatively_skipped(self):
        findings = self.check(
            _REGISTRY_FIXTURE,
            ("tputopo/sim/fix.py", """\
                def relay(policy, name):
                    policy.inc_chaos(name)
            """))
        # The bare-variable relay yields no unregistered-increment
        # finding; only the fixture registry's (genuinely dead here)
        # entries are reported.
        assert all("dead" in f.message for f in findings), \
            [f.render() for f in findings]

    def test_real_registry_round_trips(self):
        """The shipped registry must exactly cover the tree — this is
        the drift gate: a new counter needs a registry entry in the same
        PR, and a removed increment must retire its entry."""
        findings, _ = lint_sources(
            [CounterDriftChecker()],
            *[(rel, (REPO_ROOT / rel).read_text())
              for rel in ("tputopo/obs/counters.py",
                          "tputopo/obs/timeline.py",
                          "tputopo/sim/report.py",
                          "tputopo/defrag/controller.py",
                          "tputopo/extender/scheduler.py",
                          "tputopo/extender/server.py",
                          "tputopo/extender/gc.py",
                          "tputopo/k8s/retry.py",
                          "tputopo/sim/policies.py",
                          "tputopo/sim/engine.py")])
        assert findings == [], [f.render() for f in findings]


# ---- CLI output modes / --changed-only ---------------------------------------

class TestCliOutputs:
    def test_json_output_is_stable_and_clean_on_repo(self):
        res = _cli("--output", "json")
        assert res.returncode == 0, res.stdout + res.stderr
        doc = __import__("json").loads(res.stdout)
        assert doc["schema"] == "tputopo.lint/v1"
        assert doc["count"] == 0 and doc["findings"] == []
        assert doc["files"] > 100
        assert "lock-order" in doc["rules"] and "clock-flow" in doc["rules"]
        assert "lockset" in doc["rules"] and "hot-path-scan" in doc["rules"]
        assert "ownership-flow" in doc["rules"]
        assert "kill-switch-audit" in doc["rules"]
        assert "schema-additivity" in doc["rules"]
        assert len(doc["waived"]) == 16
        # rule_version + by_rule: the CI artifact's attribution fields.
        assert doc["rule_version"]["lockset"] >= 1
        assert set(doc["rule_version"]) == set(doc["rules"])
        assert doc["by_rule"]["hot-path-scan"]["waived"] == 2
        assert all(set(v) == {"findings", "waived", "duration_s"}
                   for v in doc["by_rule"].values())

    def test_json_findings_shape_on_bad_file(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("x = 1  # tpulint: disable=nocopy\n")
        res = _cli("--output", "json", str(bad))
        assert res.returncode == 1
        doc = __import__("json").loads(res.stdout)
        assert doc["count"] == 1 == len(doc["findings"])
        f = doc["findings"][0]
        assert set(f) == {"path", "line", "col", "rule", "message"}
        assert f["rule"] == "waiver"

    def test_github_annotations_on_bad_file(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("x = 1  # tpulint: disable=nocopy\n")
        res = _cli("--output", "github", str(bad))
        assert res.returncode == 1
        line = res.stdout.strip().splitlines()[0]
        assert line.startswith("::error file=")
        assert "title=tputopo.lint waiver" in line

    def _git(self, cwd, *args):
        return subprocess.run(["git", *args], cwd=cwd, capture_output=True,
                              text=True, timeout=60)

    def test_changed_only_filters_to_git_diff(self, tmp_path):
        (tmp_path / "tputopo" / "sim").mkdir(parents=True)
        clean = tmp_path / "tputopo" / "sim" / "clean.py"
        clean.write_text("x = 1\n")
        self._git(tmp_path, "init", "-q")
        self._git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
                  "add", "-A")
        self._git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
                  "commit", "-qm", "seed")
        bad = tmp_path / "tputopo" / "sim" / "bad.py"
        bad.write_text("import time\nt = time.time()\n")
        # Untracked bad file is "changed": reported, exit 1.
        res = _cli("--changed-only", "--root", str(tmp_path),
                   cwd=str(tmp_path))
        assert res.returncode == 1, res.stdout + res.stderr
        assert "bad.py" in res.stdout and "determinism" in res.stdout
        # Committed, nothing changed: same violation is OUT of scope
        # (fast local iteration mode), full run still sees it.
        self._git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
                  "add", "-A")
        self._git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
                  "commit", "-qm", "bad")
        res = _cli("--changed-only", "--root", str(tmp_path),
                   cwd=str(tmp_path))
        assert res.returncode == 0, res.stdout + res.stderr
        res = _cli("--root", str(tmp_path), cwd=str(tmp_path))
        assert res.returncode == 1

    def test_changed_only_falls_back_without_git(self, tmp_path):
        (tmp_path / "tputopo").mkdir()
        bad = tmp_path / "tputopo" / "bad.py"
        bad.write_text("import threading\n")
        (tmp_path / "tputopo" / "worse.py").write_text(
            "x = 1  # tpulint: disable=nocopy\n")
        res = _cli("--changed-only", "--root", str(tmp_path),
                   cwd=str(tmp_path))
        # no .git: degrade to the FULL report (never silently narrower)
        assert res.returncode == 1, res.stdout + res.stderr
        assert "full report" in res.stderr
