"""All-to-all (Ulysses-style) sequence parallelism on the 8-device CPU mesh:
the second sp strategy next to ring — same math, different collectives."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# tputopo.workloads.ulysses imports jax.shard_map at module level (jax
# >= 0.8); on an older JAX this is a clean module-wide skip, not a
# collection error.
pytest.importorskip(
    "tputopo.workloads.ulysses", exc_type=ImportError,
    reason="tputopo.workloads.ulysses needs jax >= 0.8 (jax.shard_map)")

from tputopo.workloads.attention import reference_attention
from tputopo.workloads.model import ModelConfig, forward, init_params
from tputopo.workloads.sharding import activate, build_mesh
from tputopo.workloads.ulysses import a2a_attention

CFG = ModelConfig(vocab_size=64, d_model=32, n_layers=2, n_heads=4,
                  n_kv_heads=2, d_ff=64, max_seq=64,
                  compute_dtype=jnp.float32, sp_impl="a2a")


def qkv(shape, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.normal(size=shape), jnp.float32)
                 for _ in range(3))


@pytest.mark.parametrize("causal", [True, False])
def test_a2a_matches_reference(causal):
    q, k, v = qkv((2, 32, 4, 8))
    plan = build_mesh({"dp": 2, "sp": 4, "tp": 1})
    out = a2a_attention(q, k, v, plan, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-5, rtol=3e-5)


@pytest.mark.slow
def test_a2a_grad_matches_reference():
    q, k, v = qkv((1, 16, 8, 8))
    plan = build_mesh({"dp": 1, "sp": 8, "tp": 1})
    gr = jax.grad(lambda a: a2a_attention(a, k, v, plan).sum())(q)
    gf = jax.grad(lambda a: reference_attention(a, k, v).sum())(q)
    np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                               atol=3e-5, rtol=3e-5)


def test_a2a_with_tp_axis():
    q, k, v = qkv((2, 16, 8, 8))
    plan = build_mesh({"dp": 1, "sp": 2, "tp": 4})
    out = a2a_attention(q, k, v, plan, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-5, rtol=3e-5)


def test_a2a_gqa_narrow_kv():
    """K/V travel the all_to_all with their narrow GQA head count when it
    divides sp; expansion happens at compute time."""
    rng = np.random.default_rng(3)
    B, S, N, KV, H = 2, 32, 8, 4, 8
    q = jnp.asarray(rng.normal(size=(B, S, N, H)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, KV, H)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, KV, H)), jnp.float32)
    plan = build_mesh({"dp": 2, "sp": 2, "tp": 2})
    out = a2a_attention(q, k, v, plan, causal=True, kv_group=N // KV)
    ref = reference_attention(q, jnp.repeat(k, N // KV, axis=2),
                              jnp.repeat(v, N // KV, axis=2), causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-5, rtol=3e-5)


def test_a2a_rejects_indivisible_heads():
    q, k, v = qkv((2, 32, 2, 8))  # 2 heads cannot split over sp=4
    plan = build_mesh({"dp": 2, "sp": 4, "tp": 1})
    with pytest.raises(ValueError, match="a2a sequence parallelism"):
        a2a_attention(q, k, v, plan, causal=True)


def test_a2a_flash_matches_reference():
    q, k, v = qkv((2, 32, 4, 8))
    plan = build_mesh({"dp": 2, "sp": 2, "tp": 2})
    out = a2a_attention(q, k, v, plan, causal=True, impl="flash")
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-5, rtol=3e-5)


def test_model_forward_a2a_matches_unsharded():
    """Full model under an sp=2 plan with sp_impl='a2a' must match the
    unsharded forward AND the ring strategy — strategy is layout, not
    math."""
    params = init_params(CFG, jax.random.key(0))
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 32)))
    ref = forward(params, tokens, dataclasses.replace(CFG, sp_impl="ring"))

    plan = build_mesh({"dp": 2, "sp": 2, "tp": 2})
    with activate(plan):
        out = jax.jit(lambda p, t: forward(p, t, CFG))(params, tokens)
        ring = jax.jit(lambda p, t: forward(
            p, t, dataclasses.replace(CFG, sp_impl="ring")))(params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ring),
                               atol=2e-4, rtol=2e-4)


@pytest.mark.slow
def test_train_step_with_a2a_runs():
    from tputopo.workloads.train import (make_sharded_state,
                                         make_sharded_train_step)

    plan = build_mesh({"dp": 2, "sp": 2, "tp": 2})
    state = make_sharded_state(plan, CFG, jax.random.key(0))
    step = make_sharded_train_step(plan, CFG)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 64, (4, 32)))
    prev = None
    for _ in range(3):
        state, loss = step(state, toks)
        assert bool(jnp.isfinite(loss))
        if prev is not None:
            assert float(loss) < prev
        prev = float(loss)
