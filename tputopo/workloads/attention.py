"""Blockwise (flash) causal attention as Pallas TPU kernels, fwd + bwd.

The flagship workload's hot op.  The einsum attention in model.py
materializes the full [B, N, S, S] score matrix in HBM — O(S^2) memory
traffic.  These kernels stream K/V blocks through VMEM with the standard
online-softmax recurrence, keeping the working set at
O(block_q x block_kv), so long sequences stay HBM-bandwidth-friendly and
the matmuls stay MXU-shaped.

Grid dimension semantics matter as much as the math: the (batch*heads,
q_block) grid axes carry no cross-step state, so they are declared
``parallel`` (only the innermost kv/q accumulation axis is ``arbitrary``),
letting Mosaic software-pipeline DMA against compute across grid steps.
Measured on a real v5e (B*N=128, H=128, bf16): blocks of 512 with the
parallel semantics run the S=2048 causal forward in 6.8 ms vs 12.5 ms for
the einsum path (1.84x) — the same kernel without the semantics
declaration is 11.8 ms, i.e. the declaration alone is ~1.7x.  Blocks
default to 512 accordingly (256/128 fallback for short sequences).
Parallel-iq holds on EVERY generation, megacore (v4/v5p) included: the
LSE residual is laid out [BN, n_q, 1, bq] so each (b, iq) flush owns a
disjoint window (VERDICT r3 #3) — an in-run v5e A/B of this layout vs
the old revisited [BN, n_q, bq] window measured 0.64x wall (faster),
with bit-identical o and LSE; v4/v5p gains the former ~1.7x arbitrary-iq
penalty back by construction (unmeasurable here — no megacore chip).

Forward: grid (batch*heads, q_blocks, kv_blocks), sequential on TPU; the
running max/denominator/accumulator live in VMEM scratch that persists
across the kv_block steps of one q_block.  Emits the per-row logsumexp
(LSE) alongside the output — the only O(S) residual the backward needs.

Backward: the FlashAttention-2 scheme, two kernels so each output has a
single accumulation order — dQ iterates (q_block outer, kv inner), dK/dV
iterate (kv_block outer, q inner).  P is recomputed from Q, K and the
saved LSE; dS = P * (dP - D) with D = rowsum(dO * O) precomputed.
Causal blocks off the diagonal are predicated off in all three kernels.

Used by model.forward when ``ModelConfig.attn_impl`` resolves to flash
(auto: TPU platform + divisible shapes); tests run the same kernels in
Pallas interpret mode on CPU against the einsum reference.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def pltpu_vmem(shape, dtype):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, dtype)


def _compiler_params(interpret: bool):
    """Mosaic grid semantics: (batch*heads, outer block) axes are
    independent -> ``parallel``; the innermost axis accumulates into VMEM
    scratch across steps -> ``arbitrary`` (sequential).  Interpret mode
    (CPU tests) takes no TPU compiler params."""
    if interpret:
        return None
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))


def _fwd_compiler_params(interpret: bool):
    """Forward-kernel grid semantics: iq is ``parallel`` on EVERY
    generation, including megacore (v4/v5p) pairs (VERDICT r2 #3 / r3 #3).

    This is race-free because every output window is keyed by iq: the
    LSE is laid out [BN, n_q, 1, bq] so each (b, iq) flush writes its own
    disjoint (1, 1, 1, bq) block — tiling-legal because each block axis
    either equals the array dim or spans the full lane tile, costing zero
    padding.  (History: [BN, n_q] with a revisited (1, n_q, bq) window
    forced iq to ``arbitrary`` on 2-core chips — the measured ~1.7x
    megacore penalty; an 8-padded (8, bq) window costed 1.7x on v5e.)
    Measured on v5e: parallel-iq is the difference between 6.8 ms and
    11.8 ms at B*N=128, S=2048, block 512."""
    if interpret:
        return None
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))


# ---- shared tile math -------------------------------------------------------

def _masked_scores(q_ref, k_ref, iq, ik, *, scale, causal):
    """scale * Q K^T for one (q_block, kv_block) tile, causal positions
    above the diagonal set to NEG_INF — the ONE definition of the score
    tile, shared by the forward kernel and the backward recompute so the
    two can never drift apart.

    The dot runs in the INPUT dtype with f32 accumulation: upcasting bf16
    operands to f32 first would push the matmul off the MXU's native
    bf16 path (~8x slower); scaling happens on the f32 result, which is
    exact either way."""
    s = jax.lax.dot_general(q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        bq = q_ref.shape[1]
        bkv = k_ref.shape[1]
        q_pos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 0)
        k_pos = ik * bkv + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 1)
        s = jnp.where(k_pos <= q_pos, s, NEG_INF)
    return s


# ---- forward ----------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                      m_ref, l_ref, acc_ref,
                      *, scale: float, causal: bool, n_kv: int):
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = (ik <= iq) if causal else True

    @pl.when(run)
    def _step():
        s = _masked_scores(q_ref, k_ref, iq, ik,
                           scale=scale, causal=causal)    # (bq, bkv)
        m_prev = m_ref[:, :1]                             # (bq, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                            # (bq, bkv) f32
        alpha = jnp.exp(m_prev - m_new)                   # (bq, 1)
        l_ref[:, :1] = l_ref[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # P @ V in V's dtype (f32 accumulate): bf16 inputs stay on the
        # MXU's fast path; f32 inputs are unchanged.
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:, :1] = m_new

    @pl.when(ik == n_kv - 1)
    def _flush():
        l = l_ref[:, :1]
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        # LSE is laid out [BN, n_q, 1, bq] and each (b, iq) step owns its
        # own (1, 1, 1, bq) window — disjoint across iq, which is what
        # lets _fwd_compiler_params declare iq ``parallel`` on megacore
        # chips too (a revisited [BN, n_q, bq] window would be a
        # cross-core write race there).
        lse_ref[0, 0, 0] = (m_ref[:, :1] + jnp.log(l))[:, 0]


# ---- backward ---------------------------------------------------------------

def _recompute_p(q_ref, k_ref, lse_row, iq, ik, *, scale, causal):
    """P = exp(scale*QK^T - LSE) for one (q_block, kv_block) tile; masked
    entries come out exactly 0 via the NEG_INF score.  ``lse_row`` is this
    q block's (bq,) slice of the LSE row."""
    s = _masked_scores(q_ref, k_ref, iq, ik, scale=scale, causal=causal)
    return jnp.exp(s - lse_row[:, None])


def _flash_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, d_ref,
                     dq_ref, acc_ref,
                     *, scale: float, causal: bool, n_kv: int):
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = (ik <= iq) if causal else True

    @pl.when(run)
    def _step():
        lse_row = lse_ref[0, iq]
        d_row = d_ref[0, iq]
        p = _recompute_p(q_ref, k_ref, lse_row, iq, ik,
                         scale=scale, causal=causal)     # (bq, bkv) f32
        dp = jax.lax.dot_general(do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - d_row[:, None]) * scale           # (bq, bkv) f32
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ik == n_kv - 1)
    def _flush():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _flash_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, d_ref,
                      dk_ref, dv_ref, dk_acc, dv_acc,
                      *, scale: float, causal: bool, n_q: int):
    ikv = pl.program_id(1)
    iq = pl.program_id(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = (iq >= ikv) if causal else True

    @pl.when(run)
    def _step():
        lse_row = lse_ref[0, iq]
        d_row = d_ref[0, iq]
        p = _recompute_p(q_ref, k_ref, lse_row, iq, ikv,
                         scale=scale, causal=causal)     # (bq, bkv) f32
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bkv, H)
        dp = jax.lax.dot_general(do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - d_row[:, None]) * scale           # (bq, bkv) f32
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bkv, H)

    @pl.when(iq == n_q - 1)
    def _flush():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


# ---- public API -------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_kv",
                                             "interpret"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, block_q: int = 512,
                    block_kv: int = 512, interpret: bool = False) -> jax.Array:
    """q/k/v: [B, S, N, H] (same head count — expand GQA groups first, as
    model.py does).  Returns [B, S, N, H] in q's dtype.

    Fully kernelized: forward saves only O and the per-row LSE; the
    backward pass runs the FlashAttention-2 dQ and dK/dV kernels — nothing
    O(S^2) is ever resident in HBM in either direction."""
    return _flash_vjp(q, k, v, causal, block_q, block_kv, interpret)


def _validate(q, k, v, causal, block_q, block_kv):
    B, S, N, H = q.shape
    if k.shape != q.shape or v.shape != q.shape:
        raise ValueError(f"q/k/v shapes differ: {q.shape} {k.shape} {v.shape}")
    block_q = min(block_q, S)
    block_kv = min(block_kv, S)
    if S % block_q or S % block_kv:
        raise ValueError(f"seq len {S} not divisible by blocks "
                         f"({block_q}, {block_kv})")
    if causal and block_q != block_kv:
        raise ValueError("causal path requires block_q == block_kv")
    return block_q, block_kv


def _to_heads(x):
    B, S, N, H = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * N, S, H)


def _from_heads(x, B, N):
    BN, S, H = x.shape
    return x.reshape(B, N, S, H).transpose(0, 2, 1, 3)


def _flash_forward_lse(q, k, v, *, causal, block_q, block_kv, interpret):
    B, S, N, H = q.shape
    block_q, block_kv = _validate(q, k, v, causal, block_q, block_kv)
    scale = 1.0 / (H ** 0.5)
    qh, kh, vh = _to_heads(q), _to_heads(k), _to_heads(v)
    n_q, n_kv = S // block_q, S // block_kv

    out, lse = pl.pallas_call(
        functools.partial(_flash_fwd_kernel, scale=scale, causal=causal,
                          n_kv=n_kv),
        grid=(B * N, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, H), lambda b, iq, ik: (b, iq, 0)),
            pl.BlockSpec((1, block_kv, H), lambda b, iq, ik: (b, ik, 0)),
            pl.BlockSpec((1, block_kv, H), lambda b, iq, ik: (b, ik, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, H), lambda b, iq, ik: (b, iq, 0)),
            pl.BlockSpec((1, 1, 1, block_q), lambda b, iq, ik: (b, iq, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * N, S, H), q.dtype),
            jax.ShapeDtypeStruct((B * N, n_q, 1, block_q), jnp.float32),
        ],
        scratch_shapes=[
            pltpu_vmem((block_q, 128), jnp.float32),  # running max (col 0)
            pltpu_vmem((block_q, 128), jnp.float32),  # running denom (col 0)
            pltpu_vmem((block_q, H), jnp.float32),    # accumulator
        ],
        compiler_params=_fwd_compiler_params(interpret),
        interpret=interpret,
    )(qh, kh, vh)
    # Squeeze the per-iq window axis: consumers (the backward row specs)
    # read the LSE as [BN, n_q, bq].
    return _from_heads(out, B, N), lse.reshape(B * N, n_q, block_q)


def _flash_backward(q, k, v, o, lse, do, *, causal, block_q, block_kv,
                    interpret):
    B, S, N, H = q.shape
    block_q, block_kv = _validate(q, k, v, causal, block_q, block_kv)
    scale = 1.0 / (H ** 0.5)
    qh, kh, vh = _to_heads(q), _to_heads(k), _to_heads(v)
    doh = _to_heads(do)
    n_q, n_kv = S // block_q, S // block_kv
    # D = rowsum(dO * O): the only other O(S) residual FlashAttention-2
    # needs; cheap elementwise work, no reason to kernelize.  Same
    # [BN, n_q, bq] layout as the LSE.
    d = _to_heads((do.astype(jnp.float32) * o.astype(jnp.float32))
                  .sum(axis=-1, keepdims=True))[..., 0]
    d = d.reshape(B * N, n_q, block_q)

    qspec = pl.BlockSpec((1, block_q, H), lambda b, i, j: (b, i, 0))
    row_spec = pl.BlockSpec((1, n_q, block_q), lambda b, i, j: (b, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel, scale=scale, causal=causal,
                          n_kv=n_kv),
        grid=(B * N, n_q, n_kv),
        in_specs=[
            qspec,
            pl.BlockSpec((1, block_kv, H), lambda b, iq, ik: (b, ik, 0)),
            pl.BlockSpec((1, block_kv, H), lambda b, iq, ik: (b, ik, 0)),
            qspec,      # dO
            row_spec,   # LSE
            row_spec,   # D
        ],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((B * N, S, H), q.dtype),
        scratch_shapes=[pltpu_vmem((block_q, H), jnp.float32)],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(qh, kh, vh, doh, lse, d)

    kv_spec = pl.BlockSpec((1, block_kv, H), lambda b, ikv, iq: (b, ikv, 0))
    q_spec2 = pl.BlockSpec((1, block_q, H), lambda b, ikv, iq: (b, iq, 0))
    row_spec2 = pl.BlockSpec((1, n_q, block_q), lambda b, ikv, iq: (b, 0, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_dkv_kernel, scale=scale, causal=causal,
                          n_q=n_q),
        grid=(B * N, n_kv, n_q),
        in_specs=[q_spec2, kv_spec, kv_spec, q_spec2, row_spec2, row_spec2],
        out_specs=[kv_spec, kv_spec],
        out_shape=[
            jax.ShapeDtypeStruct((B * N, S, H), k.dtype),
            jax.ShapeDtypeStruct((B * N, S, H), v.dtype),
        ],
        scratch_shapes=[pltpu_vmem((block_kv, H), jnp.float32),
                        pltpu_vmem((block_kv, H), jnp.float32)],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(qh, kh, vh, doh, lse, d)

    return (_from_heads(dq, B, N), _from_heads(dk, B, N),
            _from_heads(dv, B, N))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_vjp(q, k, v, causal, block_q, block_kv, interpret):
    out, _ = _flash_forward_lse(q, k, v, causal=causal, block_q=block_q,
                                block_kv=block_kv, interpret=interpret)
    return out


def _flash_vjp_fwd(q, k, v, causal, block_q, block_kv, interpret):
    out, lse = _flash_forward_lse(q, k, v, causal=causal, block_q=block_q,
                                  block_kv=block_kv, interpret=interpret)
    # Named so a remat policy can SAVE the kernel's residuals: pallas_call
    # is not a dot, so under dots_saveable alone the whole flash forward
    # re-runs inside the backward just to regenerate (out, lse) — the
    # "dots" policy in model.apply_remat saves these names to skip that.
    from jax.ad_checkpoint import checkpoint_name

    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, block_q, block_kv, interpret, res, g):
    q, k, v, o, lse = res
    return _flash_backward(q, k, v, o, lse, g, causal=causal,
                           block_q=block_q, block_kv=block_kv,
                           interpret=interpret)


_flash_vjp.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def reference_attention(q, k, v, *, causal: bool = True) -> jax.Array:
    """Einsum reference (the model.py path), for kernel verification."""
    B, S, N, H = q.shape
    scale = 1.0 / (H ** 0.5)
    logits = jnp.einsum("bqnh,bknh->bnqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        q_pos = jax.lax.broadcasted_iota(jnp.int32, (S, S), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (S, S), 1)
        logits = jnp.where(k_pos <= q_pos, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bnqk,bknh->bqnh", probs,
                      v.astype(jnp.float32)).astype(q.dtype)
