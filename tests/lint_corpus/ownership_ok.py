# lint-corpus-relpath: tputopo/corpus/ownership_ok.py
"""Corrected ownership-flow corpus: the shared-writer paths fold
copy-on-write, and the in-place primitive survives only inside the
sanctioned ``_single_owner`` downgrade branch."""


class Scheduler:
    def __init__(self):
        self._single_owner = False

    def apply_events(self, state, events):
        if self._single_owner:
            # the documented downgrade arm: statically dead under
            # shared writers, so the closure never traverses it
            return state.fold_inplace(events)
        return state.with_events(events)

    def bind(self, state, pa):
        new = (state.bind_inplace(pa) if self._single_owner
               else state.with_bind(pa))
        return new


class ReplicaSet:
    def __init__(self, schedulers: list[Scheduler]):
        self.schedulers = list(schedulers)

    def deliver(self, state, events):
        for s in self.schedulers:
            s.apply_events(state, events)


def start_replicas(make_config, api):
    cfg = make_config(shared_writers=True)
    server = api(nocopy_writes=False)  # the deepcopy write path
    return cfg, server
