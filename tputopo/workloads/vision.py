"""Second model family: a convolutional image classifier.

The reference's only end-to-end workload evidence is MNIST classifiers
trained under both schedulers (Gaia PDF §IV Exp.6, Fig. 11-12 — Caffe /
PyTorch / TensorFlow wall-time A/B).  This module is that acceptance
workload rebuilt TPU-first, so the framework ships the same *family* of
proof (a small vision model converging on the scheduled slice) alongside
the flagship LM:

- NHWC bf16 convolutions: `lax.conv_general_dilated` with feature counts
  in MXU-friendly multiples; compute dtype bf16 over f32 params, same
  policy as the LM.
- data parallel over ``dp`` (the parallelism Exp.6's jobs used), batch
  sharded at the input, gradient all-reduce inserted by XLA at the
  replicated-param boundary — riding the contiguous slice's ICI ring.
- static shapes, one jitted train step, no Python in the hot path.

Synthetic structured data (class-conditional patterns + noise) stands in
for MNIST — the image has no dataset dependency, and the convergence
check (loss must drop to near-zero memorization like Exp.6's short runs)
is what the pod exit code reports.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import optax

from tputopo.workloads import sharding as shardlib
from tputopo.workloads.sharding import constrain


@dataclass(frozen=True)
class VisionConfig:
    image_size: int = 28
    channels: int = 1
    n_classes: int = 10
    widths: tuple = (32, 64)   # conv feature counts, stride-2 stages
    d_hidden: int = 128
    compute_dtype: Any = jnp.bfloat16


def init_vision_params(cfg: VisionConfig, key: jax.Array) -> dict:
    ks = jax.random.split(key, len(cfg.widths) + 2)

    def he(k, shape, fan_in):
        return jax.random.normal(k, shape, jnp.float32) * math.sqrt(2.0 / fan_in)

    params = {}
    c_in = cfg.channels
    for i, c_out in enumerate(cfg.widths):
        params[f"conv{i}"] = he(ks[i], (3, 3, c_in, c_out), 9 * c_in)
        c_in = c_out
    side = cfg.image_size // (2 ** len(cfg.widths))
    flat = side * side * c_in
    params["fc1"] = he(ks[-2], (flat, cfg.d_hidden), flat)
    params["fc2"] = he(ks[-1], (cfg.d_hidden, cfg.n_classes), cfg.d_hidden)
    return params


def vision_forward(params: dict, images: jax.Array,
                   cfg: VisionConfig) -> jax.Array:
    """images [B, H, W, C] float -> logits [B, n_classes] f32."""
    x = constrain(images.astype(cfg.compute_dtype), "dp", None, None, None)
    for i in range(len(cfg.widths)):
        w = params[f"conv{i}"].astype(x.dtype)
        x = jax.lax.conv_general_dilated(
            x, w, window_strides=(2, 2), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        x = jax.nn.relu(x)
        x = constrain(x, "dp", None, None, None)
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["fc1"].astype(x.dtype))
    logits = x.astype(jnp.float32) @ params["fc2"]
    return constrain(logits, "dp", None)


def synthetic_batch(cfg: VisionConfig, batch: int, seed: int
                    ) -> tuple[jax.Array, jax.Array]:
    """Class-conditional structured images: class k gets a bright kxk-ish
    block at a class-determined position plus noise — linearly separable
    enough to converge fast, non-trivial enough that a broken grad path
    shows up as a flat loss."""
    import numpy as np

    rng = np.random.default_rng(seed)
    labels = rng.integers(0, cfg.n_classes, batch)
    imgs = rng.normal(0, 0.3, (batch, cfg.image_size, cfg.image_size,
                               cfg.channels)).astype(np.float32)
    for i, k in enumerate(labels):
        r = (k * 2) % (cfg.image_size - 6)
        c = (k * 5) % (cfg.image_size - 6)
        imgs[i, r:r + 6, c:c + 6, :] += 2.0
    return jnp.asarray(imgs), jnp.asarray(labels)


def vision_loss(params: dict, images: jax.Array, labels: jax.Array,
                cfg: VisionConfig) -> jax.Array:
    logits = vision_forward(params, images, cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()


def make_vision_train_step(plan: shardlib.MeshPlan, cfg: VisionConfig,
                           lr: float = 1e-3):
    """Data-parallel jitted train step: params replicated, batch over dp,
    one gradient all-reduce per step (XLA-inserted) — the Exp.6 shape."""
    opt = optax.adam(lr)

    def step(params, opt_state, images, labels):
        with shardlib.activate(plan):
            loss, grads = jax.value_and_grad(vision_loss)(
                params, images, labels, cfg)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    repl = plan.replicated()
    batch_sh = plan.sharding("dp", None, None, None)
    label_sh = plan.sharding("dp")
    return jax.jit(step,
                   in_shardings=(repl, repl, batch_sh, label_sh),
                   out_shardings=(repl, repl, repl),
                   donate_argnums=(0, 1)), opt


def train_vision(plan: shardlib.MeshPlan, cfg: VisionConfig, *,
                 steps: int = 20, batch: int = 64, lr: float = 1e-3,
                 seed: int = 0) -> list[float]:
    """Run ``steps`` memorization steps on one synthetic batch; returns the
    loss trace (a working setup drives it sharply down, Exp.6-style)."""
    params = init_vision_params(cfg, jax.random.key(seed))
    step_fn, opt = make_vision_train_step(plan, cfg, lr)
    opt_state = opt.init(params)
    images, labels = synthetic_batch(cfg, batch, seed)
    losses = []
    for _ in range(steps):
        params, opt_state, loss = step_fn(params, opt_state, images, labels)
        losses.append(float(loss))
    return losses
