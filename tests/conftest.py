"""Test bootstrap: force JAX onto a virtual 8-device CPU platform.

Multi-chip TPU hardware is not available in CI; all sharding/collective tests
run against ``--xla_force_host_platform_device_count=8`` CPU devices, which
exercises the same Mesh/pjit/shard_map code paths XLA uses on a real slice.
Must run before the first ``import jax`` anywhere in the test session.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Some images pin a hardware platform through a sitecustomize hook that runs
# before this file and ignores JAX_PLATFORMS; jax.config wins over both.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# ---- slow-tier marker -------------------------------------------------------
#
# The compile-heaviest tests (serving engines, speculative decoding,
# pipeline) are marked ``slow`` and excluded by default so the default tier
# stays under ~10 minutes; run the FULL suite with ``--runslow`` or
# ``RUN_SLOW=1``.  CI/driver runs use the default tier; the full tier is
# for pre-merge validation of serving/speculative/pipeline changes.


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="also run tests marked slow (compile-heavy serving/pipeline)")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: compile-heavy test, excluded unless --runslow or RUN_SLOW=1")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow") or os.environ.get("RUN_SLOW") == "1":
        return
    skip = pytest.mark.skip(reason="slow tier: run with --runslow or RUN_SLOW=1")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
