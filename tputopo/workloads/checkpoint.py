"""Sharding-aware checkpoint/resume for the training workload (orbax).

The scheduler side needs no checkpointing — its durable state lives in
K8s object metadata (the reference's statelessness posture, SURVEY.md
§5.4).  The *workload* side does: a gang member preempted by the TTL GC
or a node failure must resume training rather than restart (the
elastic-recovery expectation a placement framework's users have).

Orbax handles the sharded TrainState natively: each host saves only its
addressable shards, and restore redistributes onto the current MeshPlan
— which may be a *different* slice than the one that saved, because the
extender may re-place the gang elsewhere on the torus.  That re-place-
and-resume flow is exactly what the two-phase handshake + GC enable.
"""

from __future__ import annotations

from pathlib import Path

import jax
import orbax.checkpoint as ocp

from tputopo.workloads.train import TrainState


def save(ckpt_dir: str | Path, state: TrainState) -> int:
    """Write one step's checkpoint; returns the step number saved."""
    step = int(state.step)
    path = Path(ckpt_dir).absolute() / f"step_{step}"
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state)
    return step


def latest_step(ckpt_dir: str | Path) -> int | None:
    root = Path(ckpt_dir)
    if not root.is_dir():
        return None
    steps = []
    for p in root.iterdir():
        if p.name.startswith("step_"):
            try:
                steps.append(int(p.name[len("step_"):]))
            except ValueError:
                continue
    return max(steps) if steps else None


def _restore_tree(path: Path, target):
    """Shared orbax restore: ``target`` supplies structure AND shardings."""
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, target)
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(path, abstract)


def save_params(ckpt_dir: str | Path, params: dict) -> None:
    """Serving deployment: persist a parameter tree — raw f32 masters or
    the int8-quantized serving tree (quantize once offline with
    :func:`tputopo.workloads.quant.quantize_params`, serve many).  Any
    pytree of arrays round-trips, {int8, scale} leaves included.
    Overwrites a previous save (the re-quantize-and-redeploy flow saves
    to the same path every time, unlike training's step_N dirs)."""
    path = Path(ckpt_dir).absolute() / "params"
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, params, force=True)


def restore_params(ckpt_dir: str | Path, target: dict) -> dict | None:
    """Restore a parameter tree saved by :func:`save_params` into
    ``target``'s structure and shardings (build ``target`` on the current
    mesh — a quantized tree restores onto a quantized template).  Returns
    None when nothing was saved."""
    path = Path(ckpt_dir).absolute() / "params"
    if not path.is_dir():
        return None
    return _restore_tree(path, target)


def restore(ckpt_dir: str | Path, target: TrainState,
            step: int | None = None) -> TrainState | None:
    """Restore the latest (or given) step into ``target``'s sharded layout.

    ``target`` supplies structure AND shardings (an abstract or concrete
    TrainState built on the *current* mesh), so a checkpoint written on a
    different slice lands correctly redistributed.  Returns None when the
    directory holds no checkpoint (fresh start).
    """
    if step is None:
        step = latest_step(ckpt_dir)
    if step is None:
        return None
    return _restore_tree(Path(ckpt_dir).absolute() / f"step_{step}", target)
