"""Cross-wake feasibility watermarks + vectorized gang composition (PR 17).

Two saturation-wake optimizations, each behind a registered kill switch:

- ``SimEngine.FEASIBILITY_WATERMARK`` — when a pending shape fails
  placement, the engine records the minimum freed-chip condition under
  which it could possibly succeed and skips the shape on subsequent
  wakes (with exact failure bookkeeping) until cumulative releases
  cross that threshold.  The skip must be an OUTCOME no-op: job
  outcomes, queue waits, and utilization are byte-identical on/off —
  only saved-work telemetry (sort counts, phase walls, policy
  plan/infeasible tallies) may move.
- ``ExtenderScheduler.VECTOR_GANG_PLAN`` — a numpy mask screen batched
  across all candidate domains before per-candidate host-grid probing.
  A *necessary-condition* screen: it may only drop domains the probe
  would reject, so reports are byte-identical on/off, full stop.

Both stand down (watermark) or stay invisible in report bytes (vector)
under --chaos and --replicas; the schema bumps to v8 exactly when the
watermark block can appear.
"""

from __future__ import annotations

import json

from tputopo.extender.scheduler import ExtenderScheduler
from tputopo.sim.engine import SimEngine, run_trace
from tputopo.sim.report import (SCHEMA, SCHEMA_CHAOS, SCHEMA_REPLICAS,
                                SCHEMA_WATERMARK)
from tputopo.sim.trace import TraceConfig

#: Contended enough that shapes fail and later succeed (the crossing
#: path), small enough for the fast tier.
SMALL = dict(nodes=16, arrivals=60)


def _canon(report: dict) -> str:
    r = dict(report)
    r.pop("throughput", None)
    r.pop("phase_wall", None)
    return json.dumps(r, sort_keys=True)


def _outcomes(report: dict) -> str:
    """The OUTCOME projection of a report: everything a job or operator
    observes — schedule results, waits, utilization, placement quality —
    with the saved-work telemetry (scheduler counters, per-phase walls,
    baseline plan/infeasible tallies, watermark block) stripped.  The
    watermark differential tests compare THIS, because skipping a
    hopeless sort legitimately changes how much work was done, never
    what was decided."""
    out = {"virtual_horizon_s": report["virtual_horizon_s"],
           "engine": report["engine"], "policies": {}}
    for name, p in report["policies"].items():
        out["policies"][name] = {
            k: p[k] for k in ("jobs", "queue_wait_s", "chip_utilization",
                              "fragmentation", "ici_bw_score")
            if k in p
        }
        for extra in ("tiers", "preempt", "defrag", "replicas", "chaos"):
            if extra in p:
                out["policies"][name][extra] = p[extra]
    return json.dumps(out, sort_keys=True)


# ---- schema + block shape ---------------------------------------------------


def test_watermark_block_schema_and_counter_shape():
    """Armed runs report v8 with the four-counter watermark block; the
    block is per-ici-policy, deterministic, and internally consistent."""
    cfg = TraceConfig(seed=0, **SMALL)
    ra = run_trace(cfg, ["ici", "naive"])
    rb = run_trace(cfg, ["ici", "naive"])
    assert _canon(ra) == _canon(rb)
    assert ra["schema"] == SCHEMA_WATERMARK
    for p in ra["policies"].values():
        wm = p["watermark"]
        assert set(wm) == {"recorded", "skips", "crossed", "invalidated"}
        assert all(v >= 0 for v in wm.values())
    # Contended trace: the optimization actually fires (a dead watermark
    # would silently revert every wake to full sorts).
    assert ra["policies"]["ici"]["watermark"]["recorded"] > 0
    assert ra["policies"]["ici"]["watermark"]["skips"] > 0


def test_watermark_stands_down_under_chaos_and_replicas():
    """Fault injection and replica sharding disarm the watermark: failed
    attempts draw the fault stream (a skip would shift every later
    injection) and per-shard twin views go stale — so those runs keep
    their own schemas and carry no watermark key anywhere."""
    chaos = run_trace(TraceConfig(seed=0, **SMALL), ["ici"],
                      chaos="api-flake")
    assert chaos["schema"] == SCHEMA_CHAOS
    assert "watermark" not in chaos["policies"]["ici"]
    rep = run_trace(TraceConfig(seed=0, **SMALL), ["ici"],
                    replicas={"count": 2})
    assert rep["schema"] == SCHEMA_REPLICAS
    assert "watermark" not in rep["policies"]["ici"]


def test_watermark_kill_switch_restores_prior_bytes(monkeypatch):
    """The registered kill switch: FEASIBILITY_WATERMARK False must
    replay the EXACT pre-PR bytes — v2 schema, no watermark key, and
    identical scheduler/phase telemetry (the off-path does the sorts)."""
    cfg = TraceConfig(seed=0, **SMALL)
    monkeypatch.setattr(SimEngine, "FEASIBILITY_WATERMARK", False)
    off = run_trace(cfg, ["ici", "naive"])
    assert off["schema"] == SCHEMA
    assert "watermark" not in off["policies"]["ici"]
    assert "watermark" not in off["policies"]["naive"]


# ---- the differential: outcomes never move ----------------------------------


def test_watermark_differential_plain_trace(monkeypatch):
    """Watermark on vs off on the contended v2 trace: identical job
    outcomes, waits, utilization, and placement quality — the skip only
    elides work whose failure was already proven."""
    cfg = TraceConfig(seed=0, **SMALL)
    on = run_trace(cfg, ["ici", "naive"])
    monkeypatch.setattr(SimEngine, "FEASIBILITY_WATERMARK", False)
    off = run_trace(cfg, ["ici", "naive"])
    assert _outcomes(on) == _outcomes(off)
    # And the engine genuinely saved sorts on the on-leg.
    on_sorts = on["policies"]["ici"]["scheduler"].get("sort_requests", 0)
    off_sorts = off["policies"]["ici"]["scheduler"].get("sort_requests", 0)
    assert on_sorts < off_sorts


def test_watermark_differential_mixed_preempt(monkeypatch):
    """Same differential on the mixed serving+training trace with
    targeted preemption on: tier outcomes, SLO attainment, and the
    preempt block all survive the skip path (preempt-eligible jobs are
    never watermark-skipped; executed preemptions invalidate)."""
    cfg = TraceConfig(seed=0, workload="mixed", **SMALL)
    on = run_trace(cfg, ["ici"], preempt={})
    monkeypatch.setattr(SimEngine, "FEASIBILITY_WATERMARK", False)
    off = run_trace(cfg, ["ici"], preempt={})
    assert _outcomes(on) == _outcomes(off)


def test_watermark_differential_chaos_and_replicas(monkeypatch):
    """Under --chaos and --replicas the watermark stands down, so on/off
    must be byte-identical WHOLESALE (not just outcome-identical) —
    including a --jobs 2 replica replay."""
    chaos_cfg = TraceConfig(seed=0, **SMALL)
    rep_cfg = TraceConfig(seed=0, **SMALL)
    on_chaos = run_trace(chaos_cfg, ["ici"], chaos="api-flake")
    on_rep = run_trace(rep_cfg, ["ici"], replicas={"count": 2})
    on_rep_j2 = run_trace(rep_cfg, ["ici"], replicas={"count": 2}, jobs=2)
    monkeypatch.setattr(SimEngine, "FEASIBILITY_WATERMARK", False)
    off_chaos = run_trace(chaos_cfg, ["ici"], chaos="api-flake")
    off_rep = run_trace(rep_cfg, ["ici"], replicas={"count": 2})
    assert _canon(on_chaos) == _canon(off_chaos)
    assert _canon(on_rep) == _canon(off_rep) == _canon(on_rep_j2)


# ---- crossing + invalidation ------------------------------------------------


def test_watermark_crossings_and_invalidation_fire():
    """The lifecycle counters move on real traces: crossings on any
    contended trace (releases un-skip shapes, which then place), and
    invalidation whenever a capacity-epoch event (preemption here)
    rewrites feasibility out from under the recorded thresholds."""
    contended = run_trace(TraceConfig(seed=0, **SMALL), ["ici"])
    wm = contended["policies"]["ici"]["watermark"]
    assert wm["crossed"] > 0
    mixed = run_trace(TraceConfig(seed=0, workload="mixed", **SMALL),
                      ["ici"], preempt={})
    mp = mixed["policies"]["ici"]
    if mp["preempt"]["plans_executed"] > 0:
        assert mp["watermark"]["invalidated"] > 0  # cleared-on-preempt path
    # The stats are self-consistent: every skip was against a recorded,
    # not-yet-crossed threshold.
    for rec in (wm, mp["watermark"]):
        assert rec["crossed"] <= rec["recorded"]


# ---- vectorized gang composition --------------------------------------------


def test_vector_gang_plan_byte_identical_on_off(monkeypatch):
    """VECTOR_GANG_PLAN is a pure work-elision screen: the report —
    schema, outcomes, AND scheduler telemetry (gang_domains_screened is
    deliberately outside the sim keep-list) — is byte-identical with the
    switch on and off, on both the contended standard trace and a
    multi-domain fleet slice."""
    small = TraceConfig(seed=0, **SMALL)
    fleet = TraceConfig(seed=0, nodes=64, arrivals=200, offered_load=0.73)
    on_small = run_trace(small, ["ici", "naive"])
    on_fleet = run_trace(fleet, ["ici", "naive"], flight_trace=False)
    monkeypatch.setattr(ExtenderScheduler, "VECTOR_GANG_PLAN", False)
    off_small = run_trace(small, ["ici", "naive"])
    off_fleet = run_trace(fleet, ["ici", "naive"], flight_trace=False)
    assert _canon(on_small) == _canon(off_small)
    assert _canon(on_fleet) == _canon(off_fleet)


def test_vector_screen_composes_with_batch_and_preempt(monkeypatch):
    """The screen sits under every composition path — joint batch
    admission and mixed+preempt replays stay byte-identical on/off."""
    mixed = TraceConfig(seed=0, workload="mixed", **SMALL)
    on_batch = run_trace(mixed, ["ici"], batch={}, preempt={})
    monkeypatch.setattr(ExtenderScheduler, "VECTOR_GANG_PLAN", False)
    off_batch = run_trace(mixed, ["ici"], batch={}, preempt={})
    assert _canon(on_batch) == _canon(off_batch)
