"""The ``lock`` checker: annotated attributes stay inside their lock.

The threaded modules (the fake API server, the informer, the extender
scheduler) guard shared attributes with explicit locks, but nothing
stopped a new method from touching ``self._store`` without taking
``self._lock``.  The discipline is declared where the attribute is born
and enforced everywhere it is used:

- ``self._store = {}  # guarded-by: _lock`` on an ``__init__`` assignment
  declares the attribute guarded.  Several acceptable locks may be given
  separated by ``|`` (e.g. ``_lock|_watch_cond`` — a Condition built on
  the same lock), and a ``(writes)`` suffix restricts enforcement to
  stores (the scheduler's published-pair pattern: lock-free readers,
  serialized writers).
- Every *other* method of the class must access the attribute inside a
  ``with self.<lock>:`` block for one of its declared locks, or carry a
  ``# holds-lock: <lock>`` annotation on its ``def`` line (the
  caller-holds-the-lock convention for private helpers — the static
  analogue of Clang's ``REQUIRES()``).
- ``__init__`` itself is exempt (the object is not yet shared).

Annotations live in comments, so declaring them costs nothing at run
time; the checker reads them token-level and matches accesses purely
lexically (nested functions conservatively drop held locks — a closure
runs later, when the lock may no longer be held).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from tputopo.lint.core import Checker, Finding, Module

_GUARDED_RE = re.compile(
    r"#\s*guarded-by:\s*(?P<locks>[\w|]+)\s*(?:\((?P<mode>writes)\))?")
_HOLDS_RE = re.compile(r"#\s*holds-lock:\s*(?P<locks>[\w|]+)")

_NESTED_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class _GuardDecl:
    __slots__ = ("locks", "writes_only", "line")

    def __init__(self, locks: frozenset[str], writes_only: bool,
                 line: int) -> None:
        self.locks = locks
        self.writes_only = writes_only
        self.line = line


def _self_attr(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class LockGuardChecker(Checker):
    rule = "lock"
    description = ("attributes declared `# guarded-by: <lock>` on their "
                   "__init__ assignment must be accessed under `with "
                   "self.<lock>:` (or in a `# holds-lock:` helper)")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("tputopo/")

    def check_module(self, mod: Module) -> Iterable[Finding]:
        if "guarded-by" not in mod.source:
            return
        for node in mod.nodes():
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(mod, node)

    # -- declarations --------------------------------------------------------

    def _declared_guards(self, mod: Module,
                         cls: ast.ClassDef) -> dict[str, _GuardDecl]:
        init = next((n for n in cls.body
                     if isinstance(n, ast.FunctionDef)
                     and n.name == "__init__"), None)
        if init is None:
            return {}
        guards: dict[str, _GuardDecl] = {}
        for node in ast.walk(init):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            for t in targets:
                attr = _self_attr(t)
                if attr is None:
                    continue
                m = _GUARDED_RE.search(mod.comment_on_or_above(t.lineno))
                if m is not None:
                    guards[attr] = _GuardDecl(
                        frozenset(m.group("locks").split("|")),
                        m.group("mode") == "writes", t.lineno)
        return guards

    def _held_by_annotation(self, mod: Module,
                            fn: ast.AST) -> frozenset[str]:
        lineno = getattr(fn, "lineno", None)
        if lineno is None:
            return frozenset()
        m = _HOLDS_RE.search(mod.comment_on_or_above(lineno))
        if m is not None:
            return frozenset(m.group("locks").split("|"))
        return frozenset()

    # -- enforcement ---------------------------------------------------------

    def _check_class(self, mod: Module,
                     cls: ast.ClassDef) -> Iterable[Finding]:
        guards = self._declared_guards(mod, cls)
        if not guards:
            return
        for fn in cls.body:
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and fn.name != "__init__":
                held = self._held_by_annotation(mod, fn)
                findings: list[Finding] = []
                for stmt in fn.body:
                    self._visit_stmt(mod, guards, stmt, held, findings)
                yield from findings

    def _visit_stmt(self, mod: Module, guards: dict[str, _GuardDecl],
                    node: ast.AST, held: frozenset[str],
                    out: list[Finding]) -> None:
        if isinstance(node, _NESTED_SCOPES):
            # A nested function may run after the lock is released —
            # conservatively drop held locks inside (a holds-lock
            # annotation on the nested def restores them).
            nested_held = self._held_by_annotation(mod, node)
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.stmt):
                    self._visit_stmt(mod, guards, child, nested_held, out)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = set()
            for item in node.items:
                attr = _self_attr(item.context_expr)
                if attr is not None:
                    acquired.add(attr)
                # the with-item expression itself evaluates un-acquired
                self._check_expr(mod, guards, item.context_expr, held, out)
                if item.optional_vars is not None:
                    self._check_expr(mod, guards, item.optional_vars,
                                     held, out)
            inner = held | acquired
            for stmt in node.body:
                self._visit_stmt(mod, guards, stmt, inner, out)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._check_expr(mod, guards, child, held, out)
            elif isinstance(child, (ast.stmt, ast.excepthandler)):
                self._visit_stmt(mod, guards, child, held, out)

    def _check_expr(self, mod: Module, guards: dict[str, _GuardDecl],
                    expr: ast.AST, held: frozenset[str],
                    out: list[Finding]) -> None:
        if isinstance(expr, _NESTED_SCOPES):
            # lambda inside an expression: same drop-held rule
            for child in ast.iter_child_nodes(expr):
                if isinstance(child, (ast.expr, ast.stmt)):
                    self._check_expr(mod, guards, child, frozenset(), out)
            return
        attr = _self_attr(expr)
        if attr is not None and attr in guards:
            decl = guards[attr]
            is_store = isinstance(expr.ctx, (ast.Store, ast.Del))
            if (is_store or not decl.writes_only) \
                    and not (held & decl.locks):
                locks = "|".join(sorted(decl.locks))
                out.append(Finding(
                    mod.relpath, expr.lineno, expr.col_offset, self.rule,
                    f"self.{attr} ({'write' if is_store else 'read'}) "
                    f"outside `with self.{locks}:` — declared guarded-by "
                    f"{locks} at {mod.relpath}:{decl.line}; wrap the access "
                    "or annotate the helper with `# holds-lock:`"))
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, (ast.expr, ast.stmt, ast.excepthandler)):
                self._check_expr(mod, guards, child, held, out)
            elif isinstance(child, ast.comprehension):
                for sub in ast.iter_child_nodes(child):
                    self._check_expr(mod, guards, sub, held, out)
