"""tputopo.elastic — checkpoint-aware disruption costing, live gang
migration, and elastic resize.

Three layers over the existing eviction machinery:

- :mod:`tputopo.elastic.ckpt` — the checkpoint cost model: jobs carry
  ``checkpoint_period_s`` / ``restore_cost_s`` in the trace vocabulary
  and every disruption is charged its *actual* destroyed work (the
  virtual seconds since the last checkpoint, plus the restore bill)
  instead of the whole runtime.
- :mod:`tputopo.elastic.migrate` — the migration verb: plan the
  destination box *before* eviction with the mask-native candidate
  vocabulary, then requeue with preserved progress and land through the
  engine's ``_MIGRATE`` event path.
- Elastic resize lives in the engine itself (shrink-under-pressure /
  grow-on-release of gangs tagged ``min_replicas``/``max_replicas``);
  the planners here only supply the costing and destination search.

Everything is behind the registered ``SimEngine.ELASTIC`` kill switch
(CLI ``--elastic``): off-path reports are byte-identical to the
evict-everything vocabulary, schema included.
"""

from tputopo.elastic.ckpt import (checkpoint_split, disruption_cost,
                                  victim_costs)
from tputopo.elastic.migrate import MIGRATE_ABORT_REASONS, plan_destination

__all__ = [
    "MIGRATE_ABORT_REASONS",
    "checkpoint_split",
    "disruption_cost",
    "plan_destination",
    "victim_costs",
]
