"""Checkpoint/resume of the sharded TrainState (orbax), including restore
onto a different mesh layout — the re-place-and-resume flow the extender's
GC + gang re-placement produce."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tputopo.workloads import checkpoint as ckpt
from tputopo.workloads.model import ModelConfig
from tputopo.workloads.sharding import build_mesh
from tputopo.workloads.train import make_sharded_state, make_sharded_train_step

CFG = ModelConfig(vocab_size=64, d_model=32, n_layers=2, n_heads=4,
                  n_kv_heads=2, d_ff=64, max_seq=32,
                  compute_dtype=jnp.float32)


def test_save_restore_roundtrip_across_meshes(tmp_path):
    plan = build_mesh({"dp": 2, "sp": 1, "tp": 4})
    state = make_sharded_state(plan, CFG, jax.random.key(0))
    step = make_sharded_train_step(plan, CFG)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 64, (4, 16)))
    for _ in range(3):
        state, _ = step(state, toks)
    assert ckpt.save(tmp_path, state) == 3
    assert ckpt.latest_step(tmp_path) == 3

    # Restore onto a different layout (the extender re-placed the gang).
    plan2 = build_mesh({"dp": 4, "sp": 1, "tp": 2})
    target = make_sharded_state(plan2, CFG, jax.random.key(9))
    restored = ckpt.restore(tmp_path, target)
    assert restored is not None and int(restored.step) == 3
    for a, b in zip(jax.tree.leaves(jax.device_get(state.params)),
                    jax.tree.leaves(jax.device_get(restored.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # wq lands in the NEW layout (tp=2 split).
    wq = restored.params["layers"]["wq"]
    assert {s.data.shape for s in wq.addressable_shards} == {
        (CFG.n_layers, CFG.d_model, CFG.n_heads * CFG.head_dim // 2)}

    # Training continues from the restored step.
    step2 = make_sharded_train_step(plan2, CFG)
    restored, loss = step2(restored, toks)
    assert int(restored.step) == 4 and bool(jnp.isfinite(loss))


@pytest.mark.slow
def test_moe_pipeline_state_restores_across_plans(tmp_path):
    """A pipelined-MoE TrainState (expert tables over ep, layer stacks over
    pp) checkpointed from one plan restores onto a plain dp/tp plan — the
    re-placement flow must not depend on the parallelism recipe."""
    from tputopo.workloads.moe import MoEConfig

    cfg = ModelConfig(vocab_size=64, d_model=32, n_layers=2, n_heads=4,
                      n_kv_heads=2, d_ff=64, max_seq=32,
                      compute_dtype=jnp.float32,
                      moe=MoEConfig(n_experts=4, top_k=2,
                                    capacity_factor=2.0))
    plan = build_mesh({"pp": 2, "ep": 2, "tp": 2})
    state = make_sharded_state(plan, cfg, jax.random.key(0))
    step = make_sharded_train_step(plan, cfg)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 64, (4, 16)))
    state, _ = step(state, toks)
    assert ckpt.save(tmp_path, state) == 1

    plan2 = build_mesh({"dp": 4, "sp": 1, "tp": 2})
    target = make_sharded_state(plan2, cfg, jax.random.key(9))
    restored = ckpt.restore(tmp_path, target)
    assert restored is not None and int(restored.step) == 1
    for a, b in zip(jax.tree.leaves(jax.device_get(state.params)),
                    jax.tree.leaves(jax.device_get(restored.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Expert tables land UNsplit on the ep-less plan, replicated layers.
    wg = restored.params["layers"]["moe"]["w_gate"]  # [L, E, D, F]
    assert {s.data.shape for s in wg.addressable_shards} == {
        (cfg.n_layers, 4, cfg.d_model, cfg.d_ff // 2)}
    step2 = make_sharded_train_step(plan2, cfg)
    restored, loss = step2(restored, toks)
    assert int(restored.step) == 2 and bool(jnp.isfinite(loss))


@pytest.mark.slow
def test_restore_empty_dir_returns_none(tmp_path):
    plan = build_mesh({"dp": 2, "sp": 1, "tp": 4})
    target = make_sharded_state(plan, CFG, jax.random.key(0))
    assert ckpt.restore(tmp_path / "missing", target) is None
    assert ckpt.latest_step(tmp_path / "missing") is None


@pytest.mark.slow
def test_latest_step_picks_max(tmp_path):
    plan = build_mesh({"dp": 2, "sp": 1, "tp": 4})
    state = make_sharded_state(plan, CFG, jax.random.key(0))
    step = make_sharded_train_step(plan, CFG)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 64, (4, 16)))
    ckpt.save(tmp_path, state)  # step 0
    state, _ = step(state, toks)
    ckpt.save(tmp_path, state)  # step 1
    assert ckpt.latest_step(tmp_path) == 1
    restored = ckpt.restore(tmp_path, state, step=0)
    assert int(restored.step) == 0
