"""Gaia-paper experiment analogs (reference PDF §IV, Tables I/III/IV):
determinism-by-repetition from staged occupancy fixtures.

The reference's evaluation ran each allocation 500x and asserted the choice
distribution — ties may split, but invalid choices must be 0 (SURVEY.md §4).
On the torus the policies are deterministic by construction, so the
repetition check asserts a single-outcome distribution; the staged fixtures
mirror the paper's hand-drawn occupancy states (PDF Fig. 7-9) translated to
ICI geometry.
"""

from collections import Counter

from tputopo.topology.model import parse_topology
from tputopo.topology.slices import Allocator

# The paper ran 500 reps against a live cluster with nondeterministic
# timing; our allocator is a pure function of staged state, so a smaller
# repetition count over fresh instances proves the same invariant (invalid
# choices == 0) without burning suite time.
REPS = 50


def staged_allocator(spec: str, used: list[tuple]) -> Allocator:
    alloc = Allocator(parse_topology(spec))
    if used:
        alloc.mark_used(used)
    return alloc


def test_exp1_single_chip_lands_on_lowest_impact_chip():
    """Exp.1 analog (Table I): on a partially used host, every 1-chip
    request must land on a chip adjacent to the used block (Singular,
    Gaia Alg. 3) — never on a chip that splits the free region."""
    # v5e 4x2 host: left column pair used.
    used = [(0, 0), (0, 1)]
    outcomes = Counter()
    for _ in range(REPS):
        alloc = staged_allocator("v5e:4x2:wrap=00", used)
        p = alloc.find(1)
        outcomes[p.chips[0]] += 1
    # (1,0)/(1,1) touch the used block (1 free neighbor after packing);
    # picking (2,*) or (3,*) would strand fragments: must never happen.
    assert sum(outcomes[c] for c in [(1, 0), (1, 1)]) == REPS, outcomes
    invalid = [c for c in outcomes if c[0] >= 2]
    assert not invalid, f"invalid anti-fragmentation choices: {invalid}"


def test_exp1_two_chip_request_takes_adjacent_pair():
    """Exp.1 analog (Table I, 2-GPU case): 500/500 on an ICI-adjacent pair."""
    outcomes = Counter()
    for _ in range(REPS):
        alloc = staged_allocator("v5p:2x2x4:wrap=000", [])
        p = alloc.find(2)
        topo = alloc.topo
        outcomes[topo.hop_distance(p.chips[0], p.chips[1])] += 1
    assert outcomes == {1: REPS}


def test_exp3_singular_preserves_tight_pair():
    """Exp.3 analog (Table III): from the paper's Fig. 8(a)-style state —
    one lone free chip next to a used block plus an untouched tight pair
    region — the 1-chip request takes the lone chip 500/500, never breaking
    the free pair (the stock scheduler's cheapest-index pick would)."""
    # v5e 4x2: chips (0,0),(0,1),(1,0) used -> (1,1) is the lone fragment;
    # columns 2-3 are an intact 2x2 block.
    used = [(0, 0), (0, 1), (1, 0)]
    outcomes = Counter()
    for _ in range(REPS):
        alloc = staged_allocator("v5e:4x2:wrap=00", used)
        outcomes[alloc.find(1).chips[0]] += 1
    assert outcomes == {(1, 1): REPS}, outcomes


def test_exp4_link_takes_the_true_adjacent_pair():
    """Exp.4 analog (Table IV): with scattered singles used, the 2-chip
    request must take a free ICI-adjacent pair 500/500 — never a pair of
    scattered leftovers."""
    # v5p host 2x2x2: use (0,0,0) and (1,1,1) (opposite corners) — the free
    # set still contains adjacent pairs.
    used = [(0, 0, 0), (1, 1, 1)]
    outcomes = Counter()
    for _ in range(REPS):
        alloc = staged_allocator("v5p:2x2x2:wrap=000", used)
        p = alloc.find(2)
        a, b = p.chips
        outcomes[alloc.topo.hop_distance(a, b)] += 1
    assert outcomes == {1: REPS}


def test_exp4_fragmented_fallback_is_still_connected():
    """When no box fits, the blob fallback must produce a *connected* set
    (invalid = disconnected choices must be 0 across repetitions)."""
    # v5e 4x2 with a wall of used chips leaving an L-shaped free region of 3.
    used = [(0, 1), (1, 1), (2, 1), (3, 1), (0, 0)]
    for _ in range(100):
        alloc = staged_allocator("v5e:4x2:wrap=00", used)
        p = alloc.find(3)
        assert p is not None
        chips = set(p.chips)
        # connectivity check
        seen = {next(iter(chips))}
        frontier = list(seen)
        while frontier:
            c = frontier.pop()
            for nb in alloc.topo.neighbors(c):
                if nb in chips and nb not in seen:
                    seen.add(nb)
                    frontier.append(nb)
        assert seen == chips, f"disconnected blob {sorted(chips)}"


def test_exp5_latency_overhead_vs_naive_count_scheduler():
    """Exp.5 analog (Fig. 10): the reference pays +0.2-1.0 s for topology
    awareness on a ~2.5 s base.  Here the topology-aware decision must cost
    < 50 ms per allocation on a 256-chip torus — orders of magnitude inside
    the reference's overhead envelope."""
    import time

    alloc = staged_allocator("v5e:16x16", [])
    t0 = time.perf_counter()
    n = 0
    for _ in range(16):
        p = alloc.allocate(4)
        assert p is not None
        n += 1
    per_alloc_ms = (time.perf_counter() - t0) * 1e3 / n
    # Absolute-ms gate policy (VERDICT r3 #8): this host's timings vary
    # ~2x under load, so wall-clock gates carry >= 10x headroom — typical
    # per-alloc here is well under 1 ms, and the bound's meaning is "inside
    # the reference's +200-1000 ms overhead envelope", not a perf claim.
    assert per_alloc_ms < 50.0, f"{per_alloc_ms:.1f} ms per allocation"


# ---- Exp.1 distribution methodology over RANDOMIZED occupancy ---------------
#
# The staged fixtures above assert single-outcome distributions; the paper's
# actual methodology (PDF SS IV Table I) was 500 repetitions over a LIVE
# cluster state with ties allowed to split (227/273) but invalid choices
# pinned at 0.  These tests adapt that to the torus: ~200 randomized
# occupancy states per policy case, asserting zero invalid picks, recheck-
# determinism per state, and sane tie-splitting across states.

DIST_REPS = 200


def _random_state(rng, spec: str):
    alloc = Allocator(parse_topology(spec))
    chips = list(alloc.topo.chips)
    rng.shuffle(chips)
    used = chips[:rng.randrange(0, int(len(chips) * 0.8) + 1)]
    if used:
        alloc.mark_used(used)
    return alloc, set(used)


def _fresh_twin(spec: str, used: set) -> Allocator:
    twin = Allocator(parse_topology(spec))
    if used:
        twin.mark_used(sorted(used))
    return twin


def test_dist_singular_zero_invalid_over_random_states():
    """k=1 over 200 random occupancies: every pick is a free chip, every
    pick is reproducible from the same state, and choices spread over the
    grid (ties split across states rather than pinning one coordinate)."""
    import random

    rng = random.Random(0xA11)
    outcomes = Counter()
    for _ in range(DIST_REPS):
        alloc, used = _random_state(rng, "v5e:4x4:wrap=00")
        p = alloc.find(1)
        if p is None:
            assert len(used) == alloc.topo.num_chips, "find(1) failed with free chips"
            continue
        (chip,) = p.chips
        assert chip not in used, f"invalid pick: used chip {chip}"
        twin = _fresh_twin("v5e:4x4:wrap=00", used)
        assert twin.find(1).chips == p.chips, "pick not deterministic"
        outcomes[chip] += 1
    assert sum(outcomes.values()) >= DIST_REPS * 0.9
    assert len(outcomes) > 1, "one coordinate absorbed every pick"
    assert max(outcomes.values()) / sum(outcomes.values()) < 0.9


def test_dist_link_pairs_adjacent_whenever_possible():
    """k=2 over 200 random occupancies: whenever ANY ICI-adjacent free pair
    exists, the pick must be one (the Link policy's 500/500 criterion);
    picks are deterministic and duplicates never appear."""
    import random

    rng = random.Random(0xB22)
    adjacent_available = 0
    for _ in range(DIST_REPS):
        alloc, used = _random_state(rng, "v5e:4x4:wrap=00")
        topo = alloc.topo
        free = [c for c in topo.chips if c not in used]
        has_adj = any(topo.hop_distance(a, b) == 1
                      for i, a in enumerate(free) for b in free[i + 1:])
        p = alloc.find(2)
        if p is None:
            assert not has_adj or len(free) < 2
            continue
        a, b = p.chips
        assert a != b and a not in used and b not in used
        if has_adj:
            adjacent_available += 1
            assert topo.hop_distance(a, b) == 1, \
                f"non-adjacent pair {p.chips} with adjacent pairs free"
        twin = _fresh_twin("v5e:4x4:wrap=00", used)
        assert twin.find(2).chips == p.chips
    assert adjacent_available > DIST_REPS // 2  # the assertion actually bit


def test_dist_box_contiguous_whenever_a_box_fits():
    """k=4 over 200 random occupancies: whenever any free 4-chip box
    (1x4/4x1/2x2) exists, the pick is a contiguous box; otherwise any
    returned fallback must still be 4 distinct free chips."""
    import random

    rng = random.Random(0xC33)
    box_available = 0
    for _ in range(DIST_REPS):
        alloc, used = _random_state(rng, "v5e:4x4:wrap=00")
        free = {c for c in alloc.topo.chips if c not in used}

        def box_fits():
            for (dx, dy) in ((1, 4), (4, 1), (2, 2)):
                for ox in range(4 - dx + 1):
                    for oy in range(4 - dy + 1):
                        if all((ox + i, oy + j) in free
                               for i in range(dx) for j in range(dy)):
                            return True
            return False

        p = alloc.find(4)
        if p is None:
            continue
        assert len(set(p.chips)) == 4 and set(p.chips) <= free
        if box_fits():
            box_available += 1
            assert p.is_contiguous_box, \
                f"blob {p.chips} while a free box existed"
    assert box_available > DIST_REPS // 3
