"""Incremental mask-native cluster state: apply_event/with_events folding
(watch-delta maintenance), its exact-equivalence contract against a fresh
sync, the informer's event journal, the bounded latency window, and the
differential delta-vs-full-rebuild sim replay."""

import json
import random

import pytest

from tests.cluster import build_cluster
from tputopo.extender.scheduler import Metrics
from tputopo.extender.state import ClusterState
from tputopo.k8s import objects as ko
from tputopo.k8s.informer import Informer
from tputopo.k8s.objects import make_pod


class _Clock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t


def _sync(api, clock):
    return ClusterState(api, clock=clock).sync()


def _occupancy(state):
    """Comparable occupancy snapshot: per-domain used mask + unhealthy."""
    return {sid: (dom.allocator.used_mask, frozenset(dom.unhealthy))
            for sid, dom in state.domains.items()}


def _bind(api, name, node, chips, clock, *, assigned=False, gang=None):
    anns = {
        ko.ANN_GROUP: ko.coords_to_ann(chips),
        ko.ANN_ASSUME_TIME: str(clock()),
        ko.ANN_ASSIGNED: "true" if assigned else "false",
    }
    if gang:
        anns[ko.ANN_GANG_ID] = gang
    api.create("pods", make_pod(name, chips=len(chips), annotations=anns,
                                node_name=node))
    return api.get("pods", name, "default")


def test_pending_pod_added_is_a_noop_fold():
    clock = _Clock()
    api, _ = build_cluster(clock=clock)
    state = _sync(api, clock)
    pod = make_pod("p", chips=4)
    new = state.apply_event("pods", {"type": "ADDED", "object": pod})
    assert new is not None and new is not state  # fresh COW instance
    assert _occupancy(new) == _occupancy(state)


def test_bind_event_folds_like_a_fresh_sync():
    clock = _Clock()
    api, _ = build_cluster(clock=clock)
    state = _sync(api, clock)
    obj = _bind(api, "p", "node-0", [(0, 0, 0), (0, 1, 0)], clock)
    new = state.apply_event("pods", {"type": "ADDED", "object": obj})
    assert new is not None
    assert _occupancy(new) == _occupancy(_sync(api, clock))
    assert (0, 0, 0) not in new.free_chips_on_node("node-0")
    # The receiver is copy-on-write untouched.
    assert (0, 0, 0) in state.free_chips_on_node("node-0")


def test_assumption_wipe_and_delete_release_chips():
    clock = _Clock()
    api, _ = build_cluster(clock=clock)
    _bind(api, "p", "node-0", [(0, 0, 0), (0, 1, 0)], clock)
    state = _sync(api, clock)
    # GC-style wipe: annotations cleared, pod object still around.
    api.patch_annotations("pods", "p", {ko.ANN_GROUP: None,
                                        ko.ANN_ASSUME_TIME: None,
                                        ko.ANN_ASSIGNED: None},
                          namespace="default")
    wiped = api.get("pods", "p", "default")
    new = state.apply_event("pods", {"type": "MODIFIED", "object": wiped})
    assert new is not None
    assert _occupancy(new) == _occupancy(_sync(api, clock))
    assert (0, 0, 0) in new.free_chips_on_node("node-0")
    # DELETED of a bound pod releases too (fold from the pre-wipe state).
    new2 = state.apply_event("pods", {"type": "DELETED", "object": wiped})
    assert new2 is not None
    assert (0, 0, 0) in new2.free_chips_on_node("node-0")


def test_confirm_flip_keeps_occupancy_and_updates_record():
    clock = _Clock()
    api, _ = build_cluster(clock=clock)
    _bind(api, "p", "node-0", [(0, 0, 0)], clock)
    state = _sync(api, clock)
    api.patch_annotations("pods", "p", {ko.ANN_ASSIGNED: "true"},
                          namespace="default")
    new = state.apply_event(
        "pods", {"type": "MODIFIED", "object": api.get("pods", "p", "default")})
    assert new is not None
    assert _occupancy(new) == _occupancy(state)
    dom = new.domain_of_node("node-0")
    assert [pa.assigned for pa in dom.assignments] == [True]
    # ...and the parent still holds the pre-confirm record (COW).
    assert [pa.assigned
            for pa in state.domain_of_node("node-0").assignments] == [False]


def test_overlapping_claim_falls_back_to_full_sync():
    clock = _Clock()
    api, _ = build_cluster(clock=clock)
    _bind(api, "a", "node-0", [(0, 0, 0)], clock)
    state = _sync(api, clock)
    overlap = _bind(api, "b", "node-0", [(0, 0, 0)], clock)
    assert state.apply_event(
        "pods", {"type": "ADDED", "object": overlap}) is None


def test_node_churn_falls_back_to_full_sync():
    clock = _Clock()
    api, _ = build_cluster(clock=clock)
    state = _sync(api, clock)
    node = api.get("nodes", "node-1")
    assert state.apply_event("nodes", {"type": "DELETED", "object": node}) is None
    assert state.apply_event("nodes", {"type": "ADDED", "object": node}) is None
    # A non-TPU node joining is the one node ADDED with no derived impact.
    assert state.apply_event(
        "nodes", {"type": "ADDED",
                  "object": {"metadata": {"name": "cpu-1", "annotations": {}}}}
    ) is not None


def test_unhealthy_report_folds_like_a_fresh_sync():
    clock = _Clock()
    api, _ = build_cluster(clock=clock)
    _bind(api, "p", "node-0", [(0, 0, 0)], clock, assigned=True)
    state = _sync(api, clock)
    # Two dead chips: one free (enters used), one held (stays accounted).
    api.patch_annotations("nodes", "node-0",
                          {ko.ANN_UNHEALTHY: "0,0,0;0,1,0"})
    new = state.apply_event(
        "nodes", {"type": "MODIFIED", "object": api.get("nodes", "node-0")})
    assert new is not None
    fresh = _sync(api, clock)
    assert _occupancy(new) == _occupancy(fresh)
    assert [f"{pa.namespace}/{pa.pod_name}" for pa in
            new.domain_of_node("node-0").on_unhealthy] == ["default/p"]
    # Recovery: the free dead chip comes back, the held one stays used.
    api.patch_annotations("nodes", "node-0", {ko.ANN_UNHEALTHY: None})
    newer = new.apply_event(
        "nodes", {"type": "MODIFIED", "object": api.get("nodes", "node-0")})
    assert newer is not None
    assert _occupancy(newer) == _occupancy(_sync(api, clock))


def test_randomized_event_folds_match_fresh_sync():
    """Equivalence fuzz: random bind/confirm/wipe/delete/unhealthy churn,
    folded event-by-event, must track a from-scratch sync's occupancy at
    every step (or explicitly fall back)."""
    clock = _Clock()
    api, _ = build_cluster(clock=clock)
    rng = random.Random(11)
    state = _sync(api, clock)
    topo_chips = [(x, y, z) for x in range(2) for y in range(2)
                  for z in range(4)]
    live: list[str] = []
    for step in range(120):
        op = rng.random()
        clock.t += rng.random()
        if op < 0.4 or not live:
            name = f"p{step}"
            node = f"node-{rng.randrange(4)}"
            k = rng.choice([1, 2, 4])
            free = set(ClusterState(api, clock=clock).sync()
                       .free_chips_on_node(node))
            chips = sorted(free)[:k]
            if len(chips) < k:
                continue
            obj = _bind(api, name, node, chips, clock,
                        assigned=rng.random() < 0.5)
            event = ("pods", {"type": "ADDED", "object": obj})
            live.append(name)
        elif op < 0.6:
            name = rng.choice(live)
            api.patch_annotations("pods", name, {ko.ANN_ASSIGNED: "true"},
                                  namespace="default")
            event = ("pods", {"type": "MODIFIED",
                              "object": api.get("pods", name, "default")})
        elif op < 0.8:
            name = live.pop(rng.randrange(len(live)))
            api.patch_annotations("pods", name,
                                  {ko.ANN_GROUP: None, ko.ANN_ASSIGNED: None,
                                   ko.ANN_ASSUME_TIME: None},
                                  namespace="default")
            event = ("pods", {"type": "MODIFIED",
                              "object": api.get("pods", name, "default")})
        elif op < 0.9:
            name = live.pop(rng.randrange(len(live)))
            obj = api.get("pods", name, "default")
            api.delete("pods", name, "default")
            event = ("pods", {"type": "DELETED", "object": obj})
        else:
            node = f"node-{rng.randrange(4)}"
            bad = rng.sample(topo_chips, rng.randrange(0, 3))
            api.patch_annotations(
                "nodes", node,
                {ko.ANN_UNHEALTHY: ko.coords_to_ann(bad) if bad else None})
            event = ("nodes", {"type": "MODIFIED",
                               "object": api.get("nodes", node)})
        folded = state.apply_event(*event)
        if folded is None:
            state = _sync(api, clock)  # explicit, counted fallback
        else:
            state = folded
        assert _occupancy(state) == _occupancy(_sync(api, clock)), \
            (step, event[0], event[1]["type"])


# ---- informer event journal --------------------------------------------------


def test_informer_events_since_contract():
    api, _ = build_cluster()
    inf = Informer(api, watch_timeout_s=1.0).start()
    try:
        assert inf.wait_synced(10)
        token = inf.version()
        assert inf.events_since(token) == ([], token)
        api.create("pods", make_pod("a", chips=1))
        api.create("pods", make_pod("b", chips=1))
        import time
        deadline = time.time() + 10
        while inf.version() == token and time.time() < deadline:
            time.sleep(0.005)
        got = inf.events_since(token)
        assert got is not None
        events, new_token = got
        assert new_token == inf.version()
        assert [e[0] for e in events] == ["pods"] * len(events)
        assert {e[2]["metadata"]["name"] for e in events} <= {"a", "b"}
        # A garbage/ancient token is a fallback, never a wrong answer.
        assert inf.events_since(("bogus",)) is None
        assert inf.events_since(("-5",)) is None
    finally:
        inf.stop()


def test_informer_journal_gap_forces_rebuild():
    """A relist bumps content without a journal entry: any span crossing
    it must answer None (only a full rebuild is exact)."""
    api, _ = build_cluster()
    inf = Informer(api, watch_timeout_s=1.0).start()
    try:
        assert inf.wait_synced(10)
        token = inf.version()
        inf._relist("pods")  # simulate a watch Gone -> relist
        assert inf.events_since(token) is None
    finally:
        inf.stop()


# ---- bounded latency window --------------------------------------------------


def test_metrics_latency_window_is_bounded_and_quantile_exact():
    m = Metrics()
    n = Metrics.LATENCY_WINDOW
    xs = [float(i % 997) for i in range(n + 500)]
    for x in xs:
        m.observe_ms("sort", x)
    assert len(m.latencies_ms["sort"]) == n  # bounded: oldest 500 dropped
    retained = xs[-n:]
    unbounded = Metrics()
    # The window's quantiles equal the unbounded computation over exactly
    # the retained samples (same ceil-rank convention).
    for x in retained:
        unbounded.observe_ms("x", x)
    assert m.quantiles_ms("sort", (0.5, 0.95, 0.99)) == \
        unbounded.quantiles_ms("x", (0.5, 0.95, 0.99))


# ---- differential replay: delta maintenance vs full rebuild ------------------


def _ici_run(force_full_rebuild: bool):
    from tputopo.sim.engine import SimEngine
    from tputopo.sim.trace import TraceConfig, generate_trace

    cfg = TraceConfig(seed=5, nodes=16, spec="v5p:2x2x4", arrivals=80,
                      ghost_prob=0.1, node_failures=2)
    engine = SimEngine(generate_trace(cfg), "ici")
    if force_full_rebuild:
        engine.policy.sched.config.state_delta = False
        engine.policy.sched.config.state_cache_s = 0.0
        engine.policy.sched.config.bind_from_cache = False
    stream = []
    place = engine.policy.place

    def recording_place(job, nodes, handles=None):
        out = place(job, nodes, handles=handles)
        stream.append((job.name, json.dumps(out, sort_keys=True, default=str)))
        return out

    engine.policy.place = recording_place
    report = engine.run()
    return stream, report


def test_delta_mode_decisions_match_full_rebuild_every_verb():
    """The tentpole's hard constraint, replayed: one seeded trace through
    the real scheduler twice — incremental delta maintenance vs a full
    sync on every verb — must yield identical decision streams and
    identical report placement fields."""
    delta_stream, delta_report = _ici_run(force_full_rebuild=False)
    full_stream, full_report = _ici_run(force_full_rebuild=True)
    assert delta_stream == full_stream
    # engine.run() returns one policy record; everything but the scheduler
    # counters and the flight-recorder phase counts (both legitimately
    # differ between the modes — they OBSERVE the maintenance strategy,
    # e.g. cache_hit vs full_rebuild span counters) must match.
    d = {k: v for k, v in delta_report.items()
         if k not in ("scheduler", "phases")}
    f = {k: v for k, v in full_report.items()
         if k not in ("scheduler", "phases")}
    assert json.dumps(d, sort_keys=True) == json.dumps(f, sort_keys=True)
    # And the delta run actually exercised the delta machinery.
    c = delta_report["scheduler"]
    assert c["state_delta_applied"] > 10 * c.get("state_full_rebuilds", 0)
    assert full_report["scheduler"].get("state_delta_applied", 0) == 0


def test_sim_report_carries_state_maintenance_counters():
    from tputopo.sim.engine import run_trace
    from tputopo.sim.trace import TraceConfig

    cfg = TraceConfig(seed=0, nodes=8, spec="v5p:2x2x4", arrivals=30)
    rep = run_trace(cfg, ["ici"])
    c = rep["policies"]["ici"]["scheduler"]
    assert "state_delta_applied" in c
    assert "state_full_rebuilds" in c
    assert c["state_delta_applied"] > c["state_full_rebuilds"]


# ---- differential replay: baseline delta folding vs the full drop ------------


def _baseline_run(cfg, delta_fold: bool, policy: str = "naive"):
    """One baseline-policy engine run, returning (decision stream, report,
    scheduler counters).  ``delta_fold=False`` flips the kill switch to
    the historical drop-on-every-invalidate implementation — the
    differential comparator."""
    from tputopo.sim.engine import SimEngine
    from tputopo.sim.trace import generate_trace

    engine = SimEngine(generate_trace(cfg), policy)
    engine.policy.delta_fold = delta_fold
    engine.run_events()
    rs = engine.run_state()
    stream = json.dumps(rs.decision_log, sort_keys=True)
    report = engine.finalize(engine.horizon_s)
    return stream, report, rs.counters


def test_baseline_delta_decisions_match_full_drop_standard_trace():
    """The tentpole's hard constraint for the BASELINE side, replayed on
    the standard 64/500 trace: the delta-folding baseline must emit a
    byte-identical decision log — and an identical report outside the
    state-maintenance counters that OBSERVE the strategy — vs the prior
    conservative full-drop implementation (mirrors the ici
    delta-vs-full-rebuild differential above)."""
    from tputopo.sim.trace import TraceConfig

    cfg = TraceConfig(seed=0, nodes=64, arrivals=500)
    d_stream, d_report, d_c = _baseline_run(cfg, delta_fold=True)
    f_stream, f_report, f_c = _baseline_run(cfg, delta_fold=False)
    assert d_stream == f_stream
    d = {k: v for k, v in d_report.items() if k != "scheduler"}
    f = {k: v for k, v in f_report.items() if k != "scheduler"}
    assert json.dumps(d, sort_keys=True) == json.dumps(f, sort_keys=True)
    # The delta run actually folded instead of dropping: full rebuilds
    # collapse to the node-churn events (trace default: 2 failures ->
    # fail + repair), everything else rode with_events.
    assert d_c["invalidate_delta_applied"] > 0
    assert d_c["invalidate_drops_avoided"] > 100
    assert d_c["invalidate_full_drops"] <= 2 * cfg.node_failures
    assert "invalidate_drops" not in d_c
    # And the comparator really ran the historical path, with its
    # historical counter vocabulary.
    assert f_c["invalidate_drops"] > 100
    assert "invalidate_delta_applied" not in f_c


def test_baseline_journal_gap_falls_back_and_stays_bit_stable(monkeypatch):
    """An event burst outrunning the bounded buffer (the fleet-scale
    journal-gap analog) must degrade to a counted full sync — and the
    decision stream must not move: the fallback is a perf event, never a
    behavior change."""
    from tputopo.sim import policies as pol
    from tputopo.sim.trace import TraceConfig

    cfg = TraceConfig(seed=3, nodes=16, arrivals=120, ghost_prob=0.1)
    ref_stream, _, ref_c = _baseline_run(cfg, delta_fold=True)
    assert ref_c.get("invalidate_full_drop_journal_gap", 0) == 0
    # A 2-event buffer: every completed gang's DELETED burst (and every
    # GC wipe batch) overflows it.
    monkeypatch.setattr(pol.BaselinePolicy, "_EVENT_BUFFER_MAX", 2)
    gap_stream, _, gap_c = _baseline_run(cfg, delta_fold=True)
    assert gap_c["invalidate_full_drop_journal_gap"] > 10
    assert gap_stream == ref_stream


def test_event_has_impact_prescreen():
    """The O(1) no-op screen: arrival ADDEDs and unknown DELETEDs are
    provably derived-state-neutral; recorded pods and node events always
    report impact."""
    clock = _Clock()
    api, _ = build_cluster(clock=clock)
    state = _sync(api, clock)
    pending = make_pod("idle-0", chips=1)
    assert not state.event_has_impact("pods", "ADDED", pending)
    assert not state.event_has_impact("pods", "DELETED", pending)
    bound = _bind(api, "held-0", "node-0", [(0, 0, 0)], clock)
    assert state.event_has_impact("pods", "ADDED", bound)  # carries a claim
    state2 = _sync(api, clock)
    assert state2.event_has_impact(
        "pods", "DELETED", {"metadata": {"name": "held-0",
                                         "namespace": "default"}})
    assert state2.event_has_impact("nodes", "MODIFIED", {"metadata": {}})


# ---- perf smoke (slow tier) --------------------------------------------------


@pytest.mark.slow
def test_sort_p95_stays_bounded_at_fleet_scale():
    """Gross-regression tripwire at the standing evaluation config
    (--nodes 64): the ici policy's sort p95 through a full trace must stay
    under a generous ceiling (typical is well under 5 ms; the 100 ms bound
    only catches complexity regressions, with ~30x headroom for shared-host
    variance)."""
    from tputopo.extender.scheduler import quantile
    from tputopo.sim.engine import SimEngine
    from tputopo.sim.trace import TraceConfig, generate_trace

    cfg = TraceConfig(seed=0, nodes=64, arrivals=200)
    engine = SimEngine(generate_trace(cfg), "ici")
    engine.run()
    sort_ms = sorted(engine.policy.sched.metrics.latencies_ms["sort"])
    assert sort_ms, "trace produced no sorts"
    assert quantile(sort_ms, 0.95) < 100.0
