"""Weight-only int8 quantization for the serving path.

Decode is HBM-bound: BENCH_r04 measures the bf16 decode loop at ~99% of
the chip's measured HBM stream bandwidth, so the only remaining lever on
tokens/s is streaming fewer bytes.  Weight-only int8 (symmetric,
per-output-channel) halves the streamed weight bytes for a near-lossless
accuracy cost — the standard serving trade, expressed TPU-first:

- A quantized weight is the pair ``{"int8": q, "scale": s}`` where ``q``
  is int8 and ``s`` is float32 with a kept (size-1) reduction axis, so
  every leaf still scans over the leading layer axis exactly like its
  unquantized twin — the decode/prefill `lax.scan` machinery is unchanged.
- Matmul sites use :func:`qdot`, which computes ``(x @ q) * s`` — the
  per-output-channel scale commutes with the contraction over the input
  axis, so the MXU dot reads the int8 tensor directly (XLA fuses the
  int8->bf16 convert into the dot operand) and the scale lands as one
  cheap output-row multiply.  Dequantize-then-dot would materialize a
  bf16 copy of the weight and stream HBM at the unquantized rate.
- Gather sites (the embedding) use :func:`deq_rows`: rows are quantized
  per-row so the gather fetches int8 rows + one scale each.

Scope: **inference only** (decode / serving / forward for parity checks).
Training keeps float32 masters — quantization is a deployment step, not
an optimizer state format.  The reference has no serving leg at all (it
schedules training containers, Gaia PDF §IV Exp.6); this module is part
of the workload layer (SURVEY §1 L5) that placement serves.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: Weight names quantized in the stacked-layer tree (dense + MoE FFN).
#: Router and norm weights stay float32: they are O(D) or O(E) — streaming
#: them quantized saves nothing and the router's softmax is scale-sensitive.
_LAYER_WEIGHTS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def is_quantized(w) -> bool:
    """True for a ``{"int8": ..., "scale": ...}`` quantized-leaf dict."""
    return isinstance(w, dict) and "int8" in w


def _quantize_leaf(w: jax.Array, axis: int) -> dict:
    """Symmetric absmax int8 over ``axis`` (kept), scale in float32.

    Zero channels get scale 1/127 so q is exactly 0 and dequant exact.
    """
    amax = jnp.max(jnp.abs(w), axis=axis, keepdims=True)
    scale = jnp.where(amax > 0, amax, 1.0) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return {"int8": q, "scale": scale.astype(jnp.float32)}


def quantize_params(params: dict) -> dict:
    """Quantize an LM parameter tree (init_params layout) for serving.

    Dense/MoE matmul weights ``[.., in, out]`` quantize per output channel
    (absmax over the contraction axis, ``axis=-2``); the embedding
    quantizes per row (``axis=-1``) because it is gathered, not
    contracted.  Norm weights and the MoE router stay float32.
    """
    layers = dict(params["layers"])
    for name in _LAYER_WEIGHTS:
        if name in layers:
            layers[name] = _quantize_leaf(layers[name], axis=-2)
    if "moe" in layers:
        moe = dict(layers["moe"])
        for name in ("w_gate", "w_up", "w_down"):
            moe[name] = _quantize_leaf(moe[name], axis=-2)
        layers["moe"] = moe
    out = dict(params)
    out["layers"] = layers
    out["embed"] = _quantize_leaf(params["embed"], axis=-1)
    out["lm_head"] = _quantize_leaf(params["lm_head"], axis=-2)
    return out


def qdot(x: jax.Array, w) -> jax.Array:
    """``x @ w`` for a raw or quantized weight.

    Quantized: ``(x @ q) * s`` — scale applied after the contraction, so
    the dot's HBM read is the int8 tensor.  ``w`` may carry leading batch
    axes (a scan slice or a stacked expert table); the scale's kept
    ``in`` axis is squeezed to broadcast over the dot output.
    """
    if is_quantized(w):
        s = jnp.squeeze(w["scale"], axis=-2).astype(x.dtype)
        return (x @ w["int8"].astype(x.dtype)) * s
    return x @ w.astype(x.dtype)


def deq(w, dtype) -> jax.Array:
    """Materialize a weight at ``dtype`` (for einsum sites that contract
    over a non-standard axis — e.g. the MoE capacity dispatch)."""
    if is_quantized(w):
        return w["int8"].astype(dtype) * w["scale"].astype(dtype)
    return w.astype(dtype)


def deq_rows(w, idx: jax.Array, dtype) -> jax.Array:
    """Row-gather (embedding lookup) for a raw or row-quantized table."""
    if is_quantized(w):
        return w["int8"][idx].astype(dtype) * w["scale"][idx].astype(dtype)
    return w.astype(dtype)[idx]


def quantize_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Quantize K or V rows for an int8 KV cache: symmetric absmax over
    the head_dim (last axis, kept), one f32 scale per (batch, position,
    kv-head).  At long context the cache read — not the weight stream —
    dominates decode's HBM traffic; int8 halves it.  The scales fold
    exactly into the attention einsums (per key position into the logits,
    per value position into the probabilities), so the cache is read at
    int8 with no dequantized copy."""
    d = _quantize_leaf(x, axis=-1)
    return d["int8"], d["scale"]


def fold_kv_scale(s: jax.Array) -> jax.Array:
    """[B, S, KV, 1] cache scales -> [B, KV, 1, 1, S], the broadcast
    layout of the grouped-GQA attention einsums' ``bkgts`` output — the
    per-key-position factor that makes the int8 contraction exact."""
    return jnp.moveaxis(s[..., 0], 1, -1)[:, :, None, None, :]


def streamed_bytes(params: dict, compute_itemsize: int = 2) -> int:
    """Bytes a decode step streams from HBM for this parameter tree.

    Every weight except the embedding (gathered, O(B) rows) is read once
    per step: quantized leaves stream int8 + their f32 scales; raw matmul
    weights — dense projections, MoE expert tables, the lm_head — stream
    at the model's COMPUTE dtype (``compute_itemsize`` bytes: 2 for the
    bf16 default; pass 4 for a compute_dtype=float32 model, whose casts
    are no-ops), because the model consumes every one of them through a
    cast-to-compute-dtype dot whose loop-invariant cast XLA hoists out of
    the decode scan.  Norms and the router are consumed at f32.  Mirrors
    the accounting bench_decode uses for the ceiling.
    """
    matmul_names = _LAYER_WEIGHTS + ("lm_head",)

    def leaf_bytes(name: str, v) -> int:
        if is_quantized(v):
            return v["int8"].size + v["scale"].size * 4
        return v.size * (compute_itemsize if name in matmul_names else 4)

    total = 0

    def walk(tree: dict):
        nonlocal total
        for k, v in tree.items():
            if isinstance(v, dict) and not is_quantized(v):
                walk(v)
            else:
                total += leaf_bytes(k, v)

    walk(params["layers"])
    total += leaf_bytes("final_norm", params["final_norm"])
    total += leaf_bytes("lm_head", params["lm_head"])
    return total
