"""Gaia-paper experiment analogs (reference PDF §IV, Tables I/III/IV):
determinism-by-repetition from staged occupancy fixtures.

The reference's evaluation ran each allocation 500x and asserted the choice
distribution — ties may split, but invalid choices must be 0 (SURVEY.md §4).
On the torus the policies are deterministic by construction, so the
repetition check asserts a single-outcome distribution; the staged fixtures
mirror the paper's hand-drawn occupancy states (PDF Fig. 7-9) translated to
ICI geometry.
"""

from collections import Counter

from tputopo.topology.model import parse_topology
from tputopo.topology.slices import Allocator

# The paper ran 500 reps against a live cluster with nondeterministic
# timing; our allocator is a pure function of staged state, so a smaller
# repetition count over fresh instances proves the same invariant (invalid
# choices == 0) without burning suite time.
REPS = 50


def staged_allocator(spec: str, used: list[tuple]) -> Allocator:
    alloc = Allocator(parse_topology(spec))
    if used:
        alloc.mark_used(used)
    return alloc


def test_exp1_single_chip_lands_on_lowest_impact_chip():
    """Exp.1 analog (Table I): on a partially used host, every 1-chip
    request must land on a chip adjacent to the used block (Singular,
    Gaia Alg. 3) — never on a chip that splits the free region."""
    # v5e 4x2 host: left column pair used.
    used = [(0, 0), (0, 1)]
    outcomes = Counter()
    for _ in range(REPS):
        alloc = staged_allocator("v5e:4x2:wrap=00", used)
        p = alloc.find(1)
        outcomes[p.chips[0]] += 1
    # (1,0)/(1,1) touch the used block (1 free neighbor after packing);
    # picking (2,*) or (3,*) would strand fragments: must never happen.
    assert sum(outcomes[c] for c in [(1, 0), (1, 1)]) == REPS, outcomes
    invalid = [c for c in outcomes if c[0] >= 2]
    assert not invalid, f"invalid anti-fragmentation choices: {invalid}"


def test_exp1_two_chip_request_takes_adjacent_pair():
    """Exp.1 analog (Table I, 2-GPU case): 500/500 on an ICI-adjacent pair."""
    outcomes = Counter()
    for _ in range(REPS):
        alloc = staged_allocator("v5p:2x2x4:wrap=000", [])
        p = alloc.find(2)
        topo = alloc.topo
        outcomes[topo.hop_distance(p.chips[0], p.chips[1])] += 1
    assert outcomes == {1: REPS}


def test_exp3_singular_preserves_tight_pair():
    """Exp.3 analog (Table III): from the paper's Fig. 8(a)-style state —
    one lone free chip next to a used block plus an untouched tight pair
    region — the 1-chip request takes the lone chip 500/500, never breaking
    the free pair (the stock scheduler's cheapest-index pick would)."""
    # v5e 4x2: chips (0,0),(0,1),(1,0) used -> (1,1) is the lone fragment;
    # columns 2-3 are an intact 2x2 block.
    used = [(0, 0), (0, 1), (1, 0)]
    outcomes = Counter()
    for _ in range(REPS):
        alloc = staged_allocator("v5e:4x2:wrap=00", used)
        outcomes[alloc.find(1).chips[0]] += 1
    assert outcomes == {(1, 1): REPS}, outcomes


def test_exp4_link_takes_the_true_adjacent_pair():
    """Exp.4 analog (Table IV): with scattered singles used, the 2-chip
    request must take a free ICI-adjacent pair 500/500 — never a pair of
    scattered leftovers."""
    # v5p host 2x2x2: use (0,0,0) and (1,1,1) (opposite corners) — the free
    # set still contains adjacent pairs.
    used = [(0, 0, 0), (1, 1, 1)]
    outcomes = Counter()
    for _ in range(REPS):
        alloc = staged_allocator("v5p:2x2x2:wrap=000", used)
        p = alloc.find(2)
        a, b = p.chips
        outcomes[alloc.topo.hop_distance(a, b)] += 1
    assert outcomes == {1: REPS}


def test_exp4_fragmented_fallback_is_still_connected():
    """When no box fits, the blob fallback must produce a *connected* set
    (invalid = disconnected choices must be 0 across repetitions)."""
    # v5e 4x2 with a wall of used chips leaving an L-shaped free region of 3.
    used = [(0, 1), (1, 1), (2, 1), (3, 1), (0, 0)]
    for _ in range(100):
        alloc = staged_allocator("v5e:4x2:wrap=00", used)
        p = alloc.find(3)
        assert p is not None
        chips = set(p.chips)
        # connectivity check
        seen = {next(iter(chips))}
        frontier = list(seen)
        while frontier:
            c = frontier.pop()
            for nb in alloc.topo.neighbors(c):
                if nb in chips and nb not in seen:
                    seen.add(nb)
                    frontier.append(nb)
        assert seen == chips, f"disconnected blob {sorted(chips)}"


def test_exp5_latency_overhead_vs_naive_count_scheduler():
    """Exp.5 analog (Fig. 10): the reference pays +0.2-1.0 s for topology
    awareness on a ~2.5 s base.  Here the topology-aware decision must cost
    < 50 ms per allocation on a 256-chip torus — orders of magnitude inside
    the reference's overhead envelope."""
    import time

    alloc = staged_allocator("v5e:16x16", [])
    t0 = time.perf_counter()
    n = 0
    for _ in range(16):
        p = alloc.allocate(4)
        assert p is not None
        n += 1
    per_alloc_ms = (time.perf_counter() - t0) * 1e3 / n
    # Absolute-ms gate policy (VERDICT r3 #8): this host's timings vary
    # ~2x under load, so wall-clock gates carry >= 10x headroom — typical
    # per-alloc here is well under 1 ms, and the bound's meaning is "inside
    # the reference's +200-1000 ms overhead envelope", not a perf claim.
    assert per_alloc_ms < 50.0, f"{per_alloc_ms:.1f} ms per allocation"
