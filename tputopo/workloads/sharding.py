"""Scheduler → JAX bridge: turn a scheduled slice into a named device mesh.

This is the seam the whole framework exists for (BASELINE.json north star):
the extender allocates a *contiguous* slice shape (e.g. 2x2x4 on a v5p-32)
precisely so that a `jax.sharding.Mesh` laid over those chips runs its
collectives at line-rate ICI.  The reference leaves this to the workload
("the ML framework inside does its own data-parallel training over the
devices it was handed", SURVEY.md §1 L5); here the contract is explicit:

- the physical mesh axes are the slice's torus axes (row-major, matching
  `ChipTopology.chips` order and the `TPU_VISIBLE_CHIPS` device order the
  device plugin injects);
- the logical axes (``dp``/``sp``/``tp``) are grouped onto physical axes
  with ``tp`` innermost, so tensor-parallel collectives — the chattiest —
  ride single contiguous torus rings, ``dp`` outermost so data-parallel
  gradient all-reduces span whole replica blocks.

Activation sharding inside model code goes through :func:`constrain`, which
resolves logical axis names against the *active* plan — so the same forward
function runs unsharded on one chip (dev box), on an 8-device CPU mesh
(CI), or DP x SP x TP on a real slice, with zero code changes.
"""

from __future__ import annotations

import math
from contextlib import contextmanager
from dataclasses import dataclass, field

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Logical mesh axes, outermost to innermost.  Innermost axes map to the
# shortest physical rings (mesh_utils / row-major reshape both preserve
# this), so the ordering is a bandwidth policy: tp (per-token collectives,
# chattiest) innermost; ep (MoE all-to-all, per-layer) next; sp (ring
# attention ppermute) and dp (one gradient all-reduce per step) outside;
# pp outermost — pipeline traffic is point-to-point microbatch handoffs,
# the only traffic that tolerates the longest paths.
AXES = ("pp", "dp", "sp", "ep", "tp")


@dataclass
class MeshPlan:
    """A device mesh plus the logical-axis sizes laid over it."""

    mesh: Mesh
    axes: dict[str, int] = field(default_factory=dict)

    @property
    def n_devices(self) -> int:
        return math.prod(self.mesh.devices.shape)

    def spec(self, *names: str | None) -> P:
        """PartitionSpec from logical names, dropping axes of size 1."""
        return P(*(n if n is not None and self.axes.get(n, 1) > 1 else None
                   for n in names))

    def sharding(self, *names: str | None) -> NamedSharding:
        return NamedSharding(self.mesh, self.spec(*names))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())


_ACTIVE: MeshPlan | None = None


@contextmanager
def activate(plan: MeshPlan):
    """Make ``plan`` the target of :func:`constrain` within the block."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = plan
    try:
        with plan.mesh:
            yield plan
    finally:
        _ACTIVE = prev


def active_plan() -> MeshPlan | None:
    return _ACTIVE


def shard_map_kwargs(plan: MeshPlan, axis_names: set[str]) -> dict:
    """mesh/axis_names kwargs for a shard_map that must compose with an
    enclosing partial-manual region (the pp pipeline runs layer math under
    ``shard_map(..., axis_names={'pp'})``; an inner shard_map there must
    target the CONTEXT abstract mesh and exclude already-manual axes, or
    tracing fails with a mesh mismatch).  Outside any manual region this
    returns the plan's concrete mesh with the requested axes."""
    try:
        ctx = jax.sharding.get_abstract_mesh()
        manual = {n for n, t in zip(ctx.axis_names, ctx.axis_types)
                  if str(t).endswith("Manual")}
    except Exception:
        ctx = None
        manual = set()
    if manual:
        return {"mesh": ctx, "axis_names": set(axis_names) - manual}
    # Top level: classic full-manual shard_map over the concrete mesh
    # (partial axis_names here would demand specs over every size-1 axis).
    return {"mesh": plan.mesh}


def constrain(x: jax.Array, *names: str | None) -> jax.Array:
    """Logical activation-sharding constraint; no-op when no plan is active.

    ``names`` has one entry per array axis (a logical axis name or None).
    Names the active plan doesn't use (size 1) degrade to None, so model
    code states its *intent* once and runs under any parallelism degree.
    """
    plan = _ACTIVE
    if plan is None:
        return x
    return jax.lax.with_sharding_constraint(x, plan.sharding(*names))


def plan_mesh(n_devices: int, *, tp: int | None = None, sp: int | None = None,
              pp: int = 1, ep: int = 1,
              heads: int | None = None) -> dict[str, int]:
    """Choose axis sizes for ``n_devices``.

    Policy: tensor parallelism up to the host boundary (4 chips on v5p — TP
    traffic is per-token and latency-bound, keep it on the shortest rings),
    bounded by the head count it must divide; remaining factor goes to DP;
    SP, PP (pipeline stages) and EP (expert shards) only on explicit
    request — they are workload-shape decisions, not device-count ones.
    """
    if n_devices % (pp * ep):
        raise ValueError(f"pp={pp} x ep={ep} does not divide "
                         f"{n_devices} devices")
    if tp is None:
        tp = 1
        for cand in (4, 2):
            if (n_devices // (pp * ep)) % cand == 0 and \
                    (heads is None or heads % cand == 0):
                tp = cand
                break
    if n_devices % (pp * ep * tp):
        raise ValueError(f"pp={pp} x ep={ep} x tp={tp} does not divide "
                         f"{n_devices} devices")
    rest = n_devices // (pp * ep * tp)
    if sp is None:
        sp = 1
    if rest % sp:
        raise ValueError(f"sp={sp} does not divide {rest} remaining devices")
    return {"pp": pp, "dp": rest // sp, "sp": sp, "ep": ep, "tp": tp}


def build_mesh(axes: dict[str, int], devices=None) -> MeshPlan:
    """Build the Mesh for logical ``axes`` (sizes, keys from AXES).

    Device order: the scheduler hands a contiguous slice whose chips appear
    in row-major torus order (both in `ChipTopology.chips` and in the
    `TPU_VISIBLE_CHIPS` env the device plugin injects — reporter.py), and
    `jax.devices()` enumerates them in that same order on a TPU host.  On
    real TPU we let `mesh_utils.create_device_mesh` optimize the assignment
    against the physical coords; elsewhere (CPU CI) row-major reshape is
    exact by construction.
    """
    if devices is None:
        devices = jax.devices()
    shape = tuple(axes.get(a, 1) for a in AXES)
    if math.prod(shape) != len(devices):
        raise ValueError(f"axes {axes} need {math.prod(shape)} devices, "
                         f"got {len(devices)}")
    if devices and devices[0].platform == "tpu":
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    else:
        dev_array = np.asarray(devices).reshape(shape)
    return MeshPlan(mesh=Mesh(dev_array, AXES), axes=dict(axes))


def mesh_for_slice(slice_dims: tuple[int, ...], devices=None,
                   **plan_kw) -> MeshPlan:
    """Mesh over a scheduled slice of shape ``slice_dims`` — what a workload
    container calls after the extender placed it (its devices *are* the
    slice, in row-major order)."""
    n = math.prod(slice_dims)
    return build_mesh(plan_mesh(n, **plan_kw), devices=devices)


# ---- parameter shardings ----------------------------------------------------

def param_specs(plan: MeshPlan, config=None) -> dict:
    """Megatron-style TP layout for the model.py parameter pytree.

    Attention qkv projections and MLP up/gate split their output features
    over ``tp`` (column parallel); wo and w_down split input features (row
    parallel), so each block needs exactly one psum, which XLA inserts at
    the constrained boundary.  The lm_head splits the vocab.

    Stacked layer tensors carry a leading layer axis for the scan; when the
    plan runs pipeline parallelism (pp > 1) that axis is sharded over
    ``pp`` — each stage holds exactly its own layers, which is both the
    memory story (params / pp per device) and what the pipeline's
    shard_map consumes directly (pipeline.py).  ``config`` (a ModelConfig)
    switches the FFN leaves to the MoE layout (experts over ``ep``) when
    its ``moe`` field is set.
    """
    s = plan.spec
    pp = "pp" if plan.axes.get("pp", 1) > 1 else None

    def layer(*names):
        return s(pp, *names)

    layers = {
        "attn_norm": layer(None),
        "wq": layer(None, "tp"),
        "wk": layer(None, "tp"),
        "wv": layer(None, "tp"),
        "wo": layer("tp", None),
        "mlp_norm": layer(None),
    }
    if config is not None and config.moe is not None:
        layers["moe"] = {
            "router": layer(None, None),
            "w_gate": layer("ep", None, "tp"),
            "w_up": layer("ep", None, "tp"),
            "w_down": layer("ep", "tp", None),
        }
    else:
        layers.update({
            "w_gate": layer(None, "tp"),
            "w_up": layer(None, "tp"),
            "w_down": layer("tp", None),
        })
    return {
        "embed": s(None, None),
        "layers": layers,
        "final_norm": s(None),
        "lm_head": s(None, "tp"),
    }


def param_shardings(plan: MeshPlan, config=None) -> dict:
    return jax.tree.map(lambda spec: NamedSharding(plan.mesh, spec),
                        param_specs(plan, config),
                        is_leaf=lambda x: isinstance(x, P))


def batch_sharding(plan: MeshPlan) -> NamedSharding:
    """Token batches: batch over dp, sequence over sp."""
    return plan.sharding("dp", "sp")
