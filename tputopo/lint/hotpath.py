"""The ``hot-path-scan`` checker: no O(pods) work on scheduler hot verbs.

The ROADMAP's fleet-scale item (1024 nodes / 10k arrivals) was blocked
by full-store scans that only a profiler used to find —
``BaselinePolicy.invalidate``'s conservative drop forced a full
``ClusterState.sync`` on the very next ``place()``, ~35% of sim wall,
carried as this rule's waived debt until the incremental-baseline PR
deleted the waiver by fixing it.  This rule turns that hunt into a CI
gate:

- **Hot roots** are the scheduler's verbs (``ExtenderScheduler.sort`` /
  ``.bind``) and the sim event loop (``SimEngine.run_events``), plus any
  ``def`` carrying a ``# hot-path-root: <reason>`` directive (how a new
  subsystem registers one).
- The **hot closure** is everything reachable from a root through the
  call graph — with *virtual dispatch* widened: a call resolving to a
  base-class method also reaches every subclass override (the sim's
  ``policy.place`` polymorphism is precisely how the expensive path
  hides from a naive closure).
- **Full-store primitives** are flagged at their call sites inside the
  closure: ``ClusterState.sync`` (the O(pods) rebuild),
  ``FakeApiServer.list`` / ``list_nocopy`` / ``list_with_version`` and
  the informer mirrors, and ``extender.state.list_pods_nocopy`` (the
  shared copy-free listing shim, re-exported by ``defrag.planner``).
  Constructor-chained calls (``ClusterState(...).sync()``) resolve too.

Every finding names the entry path from a hot root.  Deliberate,
amortized scans — the cache-miss rebuild fallback, the periodic GC
sweep, a defrag cycle — carry **reasoned budgeted waivers**; the pinned
per-rule waiver budget (tests/test_lint.py) is what keeps "just waive
it" from becoming the path of least resistance.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from tputopo.lint.callgraph import (CallGraph, FunctionInfo, graph_for,
                                    subclass_overrides)
from tputopo.lint.core import Checker, Finding, Module

_ROOT_RE = re.compile(r"#\s*hot-path-root:\s*(?P<reason>.*\S)")

#: The standing hot verbs (filter/score -> sort, bind) and the sim's
#: event loop.  New roots register via the directive, not this list.
HOT_ROOTS: tuple[tuple[str, str], ...] = (
    ("tputopo/extender/scheduler.py", "ExtenderScheduler.sort"),
    ("tputopo/extender/scheduler.py", "ExtenderScheduler.bind"),
    ("tputopo/sim/engine.py", "SimEngine.run_events"),
)

#: (class qualname, method) pairs that scan a whole store per call.
FULL_SCAN_METHODS = frozenset({
    ("ClusterState", "sync"),
    ("FakeApiServer", "list"),
    ("FakeApiServer", "list_nocopy"),
    ("FakeApiServer", "list_with_version"),
    ("Informer", "list"),
})

#: Bare function names that are full-store scans wherever they resolve.
FULL_SCAN_FUNCTIONS = frozenset({"list_pods_nocopy"})

#: Attribute names unambiguous enough to flag even unresolved (no other
#: meaning in this codebase).
FULL_SCAN_ATTRS = frozenset({"list_nocopy", "list_with_version"})


class HotPathChecker(Checker):
    rule = "hot-path-scan"
    description = ("functions reachable from the scheduler hot verbs "
                   "(sort/bind) or the sim event loop must not call "
                   "full-store O(pods) primitives (ClusterState.sync, "
                   "api.list*, list_pods_nocopy) — amortized scans "
                   "carry reasoned budgeted waivers")

    version = 1

    def __init__(self) -> None:
        self._mods: list[Module] = []

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(("tputopo/", "tests/"))

    def check_module(self, mod: Module) -> Iterable[Finding]:
        self._mods.append(mod)
        return ()

    # ---- closure -----------------------------------------------------------

    def _roots(self, graph: CallGraph, by_path) -> dict[tuple, str]:
        roots: dict[tuple, str] = {}
        for key in HOT_ROOTS:
            if key in graph.functions:
                roots[key] = "standing hot verb"
        for fn in graph.functions.values():
            if not fn.relpath.startswith("tputopo/"):
                continue
            mod = by_path.get(fn.relpath)
            if mod is None or "hot-path-root" not in mod.source:
                continue
            m = _ROOT_RE.search(mod.comment_on_or_above(fn.node.lineno))
            if m is not None:
                roots[fn.key] = f"declared: {m.group('reason')}"
        return roots

    def _closure(self, graph: CallGraph, roots: dict[tuple, str]
                 ) -> dict[tuple, tuple | None]:
        overrides = subclass_overrides(graph)  # shared widening memo
        return graph.closure_with_parents(
            roots, expand=lambda callee: overrides.get(callee.key, ()))

    # ---- scan-site detection -----------------------------------------------

    def _scan_callee(self, graph: CallGraph, fn: FunctionInfo,
                     call: ast.Call) -> str | None:
        """A display name when ``call`` is a full-store primitive."""
        callee = graph.resolve(call, fn)
        if callee is None and isinstance(call.func, ast.Attribute) \
                and isinstance(call.func.value, ast.Call):
            # Constructor-chained: ``ClusterState(...).sync()``.
            inner = graph.resolve(call.func.value, fn)
            if inner is not None and inner.cls is not None:
                callee = inner.cls.find_method(call.func.attr)
        if callee is not None:
            meth = callee.qualname.rsplit(".", 1)[-1]
            if callee.cls is not None \
                    and (callee.cls.qualname, meth) in FULL_SCAN_METHODS:
                return f"{callee.cls.qualname}.{meth}"
            if meth in FULL_SCAN_FUNCTIONS:
                return callee.qualname
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in (FULL_SCAN_ATTRS
                                       | FULL_SCAN_FUNCTIONS):
            return call.func.attr
        return None

    def _entry_path(self, graph: CallGraph, parent, roots,
                    key: tuple) -> str:
        return graph.render_entry_path(parent, key)

    # ---- the analysis ------------------------------------------------------

    def finalize(self) -> Iterable[Finding]:
        mods, self._mods = self._mods, []
        graph = graph_for(mods)
        by_path = {m.relpath: m for m in mods}
        roots = self._roots(graph, by_path)
        if not roots:
            return
        parent = self._closure(graph, roots)
        for key in sorted(parent):
            fn = graph.functions.get(key)
            if fn is None or not fn.relpath.startswith("tputopo/"):
                continue
            for site in graph.callees(fn):
                scan = self._scan_callee(graph, fn, site.node)
                if scan is None:
                    continue
                via = self._entry_path(graph, parent, roots, key)
                yield Finding(
                    fn.relpath, site.node.lineno, site.node.col_offset,
                    self.rule,
                    f"full-store scan {scan}() on the hot path "
                    f"({via}) — O(pods) per call blocks the fleet-scale "
                    "trace; make it incremental/indexed, or waive with "
                    "the amortization argument")
