"""Shared test fixture builder: a fake multi-host TPU cluster.

The rebuild's 'staged cluster states as fixtures' strategy (SURVEY.md §4:
Gaia stages occupancy states of a real cluster; we stage fake topology
snapshots — many nodes in one process, no kubelet)."""

from __future__ import annotations

import os

from tputopo.deviceplugin import FakeKubelet, TpuDevicePlugin
from tputopo.discovery.shim import _probe_python, _to_host_probe
from tputopo.k8s import FakeApiServer


def probe_for(spec: str):
    env = dict(os.environ)
    env["TPUTOPO_FAKE"] = spec
    return _to_host_probe(_probe_python(env))


def build_cluster(spec: str = "v5p:2x2x4", workers: int = 4,
                  slice_id: str = "slice-a",
                  api: FakeApiServer | None = None,
                  clock=None, node_prefix: str = "node"):
    """Bring up ``workers`` device plugins for one slice against a fake API
    server.  Returns (api_server, {node_name: plugin})."""
    api = api or FakeApiServer()
    plugins = {}
    for w in range(workers):
        probe = probe_for(f"{spec}@{w}")
        name = f"{node_prefix}-{w}"
        plugin = TpuDevicePlugin(
            node_name=name, slice_id=slice_id, kubelet=FakeKubelet(),
            api_server=api, probe=probe,
            clock=clock or (lambda: 1000.0),
        )
        plugin.start()
        plugins[name] = plugin
    return api, plugins
