# lint-corpus-relpath: tputopo/corpus/switches_ok.py
"""Corrected kill-switch-audit corpus: directive-registered switches,
both branch directions live, counters presence-gated (no eager seed of
switch-guarded names)."""


class Engine:
    TURBO = True  # kill-switch: the fast fold leg; off = historical path

    def __init__(self):
        self._counters = {"folds": 0}  # seeded, but never switch-guarded

    def run(self, state, events):
        if not self.TURBO:
            return self.slow(state, events)
        self.inc("turbo_folds")  # lazily counted: off-path bytes unchanged
        return self.fast(state, events)

    def slow(self, state, events):
        self.inc("folds")
        return state

    def fast(self, state, events):
        self.inc("folds")
        return state

    def inc(self, name):
        self._counters[name] = self._counters.get(name, 0) + 1


class Store:
    # Delegation: the class-level switch feeds a registered constructor
    # switch (the fake API's nocopy_writes), whose reads are audited.
    NOCOPY = True  # kill-switch: structural-sharing store writes

    def __init__(self, server):
        self.api = server(nocopy_writes=self.NOCOPY)
