"""Predicted-vs-measured all-reduce validation and cost-model calibration.

The reference never closed its own loop: the design left the link
bandwidth-weight table as an unresolved TODO ("带宽权值", design.md:47), so
its scores were rank-orderings with no physical unit.  This module closes
it for the TPU rebuild (SURVEY.md §7 "honest bandwidth model"):

- :func:`validate_slice` runs the real psum microbenchmark
  (:mod:`tputopo.workloads.collective`) over the devices a scheduled slice
  handed to this container and compares the measured algorithm bandwidth
  against :func:`tputopo.topology.score.predict_allreduce_gbps` for the
  slice shape — the BASELINE.md acceptance number ("scheduled slice vs
  ideal").
- :func:`calibrate_cost_model` backs a per-link GB/s out of a measured
  all-reduce so deployments can replace the public-spec defaults in
  :mod:`tputopo.topology.generations` with measured reality (via
  ExtenderConfig's cost-table override).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

from tputopo.topology.cost import LinkCostModel
from tputopo.topology.model import ChipTopology, parse_topology
from tputopo.topology.score import predict_allreduce_gbps
from tputopo.workloads.collective import AllReduceResult, measure_allreduce


@dataclass(frozen=True)
class ValidationReport:
    topology: str
    predicted_gbps: float
    measured: AllReduceResult

    @property
    def measured_gbps(self) -> float:
        return self.measured.algbw_gbps

    @property
    def efficiency(self) -> float:
        """measured / predicted — 1.0 means the model is honest; the
        BASELINE acceptance wants the *scheduled* slice to match the ideal
        directly-requested slice, i.e. equal efficiency on both."""
        return self.measured_gbps / self.predicted_gbps if self.predicted_gbps else 0.0

    def to_dict(self) -> dict:
        return {
            "topology": self.topology,
            "predicted_gbps": round(self.predicted_gbps, 3),
            "measured_gbps": round(self.measured_gbps, 3),
            "efficiency": round(self.efficiency, 4),
            **{f"measured_{k}": v for k, v in self.measured.to_dict().items()},
        }


def validate_slice(topo: ChipTopology | str, devices=None,
                   payload_mb: float = 16.0, iters: int = 10) -> ValidationReport:
    """Measure the all-reduce of the local devices (the slice a scheduled
    container was handed) and compare with the model's prediction for the
    slice shape.  ``topo`` is the slice topology — on a scheduled pod,
    parse it from the injected ``TPU_SLICE_TOPOLOGY``/``TPU_ACCELERATOR_TYPE``
    env (reporter.py)."""
    if isinstance(topo, str):
        topo = parse_topology(topo)
    cost = LinkCostModel.for_generation(topo.generation.name)
    predicted = predict_allreduce_gbps(topo, topo.dims, cost)
    measured = measure_allreduce(devices=devices, payload_mb=payload_mb,
                                 iters=iters)
    return ValidationReport(
        topology=topo.describe(),
        predicted_gbps=predicted,
        measured=measured,
    )


def calibrate_cost_model(topo: ChipTopology,
                         measured_algbw_gbps: float | None = None, *,
                         measured_hbm_gbps: float | None = None) -> LinkCostModel:
    """Back out the figures that make the model reproduce measurements
    exactly, keeping the rest of the cost table.

    - ``measured_algbw_gbps`` (an all-reduce over the full ``topo``) fits
      ``ici_link_gbps``: the box model is linear in it
      (:func:`predict_allreduce_gbps` sums per-axis ring terms scaled by
      it), so calibration is one division.
    - ``measured_hbm_gbps`` (a stream benchmark, e.g. bench.py's
      ``bench_hbm_gbps``) replaces ``hbm_gbps`` directly — the workload-
      heuristic half of the table (decode serving ceiling), which round 2
      measured at 0.706x the v5e spec sheet and nothing consumed
      (VERDICT r3 #4).

    Feed the result into ExtenderConfig's cost override to schedule (and
    plan serving) with measured numbers — the fix for the reference's
    unresolved weight-table TODO (design.md:47).
    """
    base = LinkCostModel.for_generation(topo.generation.name)
    fields: dict = {}
    if measured_algbw_gbps is not None:
        if measured_algbw_gbps <= 0:
            raise ValueError(
                f"measured_algbw_gbps must be > 0, got {measured_algbw_gbps}"
                " (a differencing artifact?)")
        unit = predict_allreduce_gbps(topo, topo.dims, base) / base.ici_link_gbps
        if unit <= 0:
            raise ValueError(
                f"topology {topo.describe()} has no multi-chip axis to calibrate on")
        fields["ici_link_gbps"] = measured_algbw_gbps / unit
    if measured_hbm_gbps is not None:
        if measured_hbm_gbps <= 0:
            raise ValueError(f"measured_hbm_gbps must be > 0, got {measured_hbm_gbps}")
        fields["hbm_gbps"] = float(measured_hbm_gbps)
    if not fields:
        raise ValueError("nothing to calibrate: pass at least one measurement")
    return dataclasses.replace(base, **fields)


def measured_vs_spec(cal: LinkCostModel, gen_name: str) -> dict:
    """The measured-vs-spec record a deployment carries next to its cost
    override (the generation table stays spec; this documents the delta)."""
    from tputopo.topology.generations import get_generation

    g = get_generation(gen_name)
    out = {}
    for fld, spec in (("ici_link_gbps", g.ici_link_gbps),
                      ("hbm_gbps", g.hbm_gbps),
                      ("dcn_host_gbps", g.dcn_host_gbps)):
        measured = getattr(cal, fld)
        out[fld] = {"spec": spec, "calibrated": round(measured, 1),
                    "calibrated_over_spec": round(measured / spec, 3)}
    return out
