"""HTTP front-end for the extender — the process kube-scheduler talks to.

Verb shapes follow the kube-scheduler extender contract the reference
registers (design.md:92-113): POST ``<prefix>/sort`` (Prioritize) takes the
pod plus candidate nodes and returns a host-priority list; POST
``<prefix>/bind`` takes {PodName, PodNamespace, Node} and returns
{"Error": ""} on success.  ``nodeCacheCapable: true`` (design.md:102) means
sort receives node *names*; topology comes from the extender's own cluster
state, never from a node round-trip.

Extras beyond the reference (SURVEY.md §5.1/§5.5 prescriptions): /healthz,
Prometheus-format /metrics with per-verb latency, and /state exposing the
fragmentation report and recent decision records.  Fail-closed posture
(ignorable=false, design.md:109): errors return non-2xx with a reason, so
scheduling of managed pods fails loudly rather than silently degrading.

Stdlib http.server only — this image has no Flask/grpcio, and a scheduler
extender needs nothing more.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tputopo.extender.config import ExtenderConfig
from tputopo.extender.scheduler import BindError, ExtenderScheduler


class _Handler(BaseHTTPRequestHandler):
    scheduler: ExtenderScheduler  # set by server factory
    config: ExtenderConfig

    # ---- plumbing ----------------------------------------------------------

    def log_message(self, fmt, *args):  # quiet; metrics cover observability
        pass

    def _send_json(self, code: int, obj) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code: int, text: str) -> None:
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_json(self):
        n = int(self.headers.get("Content-Length", "0"))
        raw = self.rfile.read(n) if n else b""
        if not raw:
            raise ValueError("empty request body")
        return json.loads(raw)

    # ---- routes ------------------------------------------------------------

    def do_POST(self) -> None:
        prefix = self.config.url_prefix
        try:
            if self.path == f"{prefix}/sort":
                self._handle_sort()
            elif self.path == f"{prefix}/bind":
                self._handle_bind()
            else:
                self._send_json(404, {"error": f"unknown path {self.path}"})
        except (ValueError, KeyError, json.JSONDecodeError) as e:
            self.scheduler.metrics.inc("bad_requests")
            self._send_json(400, {"error": str(e)})
        except Exception as e:  # API-server unreachable, etc. — fail closed
            # with a response, not a dropped socket (a real KubeApiClient
            # raises URLError/RuntimeError the in-memory fake never did).
            self.scheduler.metrics.inc("api_errors")
            self._send_json(503, {"error": f"{type(e).__name__}: {e}"})

    def do_GET(self) -> None:
        try:
            if self.path == "/healthz":
                self._send_text(200, "ok\n")
            elif self.path == "/metrics":
                self._send_text(200, self._render_metrics())
            elif self.path == "/state":
                # Serve from the informer mirror exactly like the verbs do
                # (nodeCacheCapable posture, design.md:102): a monitoring
                # scraper polling /state must cost zero API LISTs in steady
                # state, not an authoritative full-cluster sync per hit.
                sched = self.scheduler
                reader = (sched.informer if sched.informer is not None
                          and sched.informer.synced else None)
                state = sched._state(allow_cache=True, reader=reader)
                self._send_json(200, {
                    "fragmentation": state.fragmentation_report(),
                    "decisions": self.scheduler.decisions[-20:],
                })
            elif self.path == "/policy":
                self._send_json(200, self.config.policy_json())
            else:
                self._send_json(404, {"error": f"unknown path {self.path}"})
        except Exception as e:
            self.scheduler.metrics.inc("api_errors")
            self._send_json(503, {"error": f"{type(e).__name__}: {e}"})

    def _handle_sort(self) -> None:
        req = self._read_json()
        pod = req.get("Pod")
        if pod is None:
            raise ValueError("sort request needs a Pod")
        node_names = req.get("NodeNames")
        if node_names is None:
            items = (req.get("Nodes") or {}).get("Items") or []
            node_names = [n["metadata"]["name"] for n in items]
        self._send_json(200, self.scheduler.sort(pod, list(node_names)))

    def _handle_bind(self) -> None:
        req = self._read_json()
        for field in ("PodName", "PodNamespace", "Node"):
            if field not in req:
                raise ValueError(f"bind request needs {field}")
        try:
            self.scheduler.bind(req["PodName"], req["PodNamespace"], req["Node"])
            self._send_json(200, {"Error": ""})
        except BindError as e:
            # Non-empty Error => kube-scheduler treats the bind as failed and
            # requeues the pod; with ignorable=false nothing silently binds.
            self._send_json(200, {"Error": str(e)})

    def _render_metrics(self) -> str:
        m = self.scheduler.metrics
        lines = []
        for name, v in sorted(m.counters.items()):
            lines.append(f"tputopo_extender_{name}_total {v}")
        for verb in sorted(m.latencies_ms):
            qs = m.quantiles_ms(verb, (0.5, 0.95))
            if qs is not None:
                # Tail latency is what a scheduling SLO is written against
                # (the scale bench gates on p95 for the same reason).
                lines.append(f"tputopo_extender_{verb}_latency_p50_ms {qs[0]:.3f}")
                lines.append(f"tputopo_extender_{verb}_latency_p95_ms {qs[1]:.3f}")
        return "\n".join(lines) + "\n"


class ExtenderHTTPServer:
    """Owns the ThreadingHTTPServer; start()/stop() for tests and main()."""

    def __init__(self, scheduler: ExtenderScheduler,
                 config: ExtenderConfig | None = None,
                 host: str = "127.0.0.1", port: int | None = None) -> None:
        self.config = config or scheduler.config
        handler = type("Handler", (_Handler,), {
            "scheduler": scheduler, "config": self.config,
        })
        self.httpd = ThreadingHTTPServer(
            (host, self.config.port if port is None else port), handler)
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> tuple[str, int]:
        return self.httpd.server_address[:2]

    def start(self) -> "ExtenderHTTPServer":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name="tputopo-extender", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)


def main() -> None:  # pragma: no cover - thin CLI wrapper
    import argparse
    import os

    ap = argparse.ArgumentParser(description="tputopo scheduler extender")
    ap.add_argument("--config", help="path to ExtenderConfig JSON")
    ap.add_argument("--port", type=int, default=None)
    ap.add_argument("--api-url", default=None,
                    help="API server base URL (default: in-cluster when "
                         "KUBERNETES_SERVICE_HOST is set, else in-memory fake)")
    ap.add_argument("--host", default="0.0.0.0",
                    help="listen address (kube-scheduler calls from outside "
                         "this pod; default all interfaces)")
    args = ap.parse_args()
    config = ExtenderConfig.load(args.config) if args.config else ExtenderConfig()
    if args.port is not None:
        config.port = args.port
    if args.api_url or os.environ.get("KUBERNETES_SERVICE_HOST"):
        from tputopo.k8s.client import KubeApiClient

        api_server = KubeApiClient(base_url=args.api_url)
    else:
        # Standalone smoke mode: empty in-memory API (for /policy generation
        # and local poking).
        from tputopo.k8s.fakeapi import FakeApiServer

        api_server = FakeApiServer()
    # List+watch cache: sort serves from this mirror (zero LISTs per verb
    # in steady state); bind still re-syncs authoritatively.
    from tputopo.k8s.informer import Informer

    informer = Informer(api_server).start()
    scheduler = ExtenderScheduler(api_server, config, informer=informer)
    server = ExtenderHTTPServer(scheduler, config, host=args.host)

    from tputopo.extender.gc import AssumptionGC

    gc = AssumptionGC(api_server, assume_ttl_s=config.assume_ttl_s)
    stop = threading.Event()

    def gc_loop() -> None:
        while not stop.wait(max(1.0, config.assume_ttl_s / 2)):
            try:
                released = gc.sweep()
            except Exception as e:  # API blip must not kill the GC thread —
                # a dead sweeper strands expired reservations forever.
                print(f"gc: sweep failed ({type(e).__name__}: {e}); retrying")
                continue
            if released:
                print(f"gc: released stale assumptions for {released}")

    threading.Thread(target=gc_loop, name="tputopo-gc", daemon=True).start()
    print(f"tputopo extender listening on {server.address} "
          f"(prefix {config.url_prefix}, gc every {config.assume_ttl_s / 2:.0f}s)")
    server.start()
    try:
        stop.wait()
    except KeyboardInterrupt:
        stop.set()
        server.stop()


if __name__ == "__main__":  # pragma: no cover
    main()
