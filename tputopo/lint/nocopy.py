"""The ``nocopy`` checker: copy-free read results must stay read-only.

``FakeApiServer.list_nocopy`` / ``get_nocopy`` / ``ObjectHandle.fetch``
(and their informer mirrors) return the *stored* dicts — the contract is
single-threaded readers that NEVER mutate the result (PR 3's perf win
rests on it; the runtime digest guard catches violations only in guarded
test runs).  This checker makes the contract static: within each
function it taints names bound from nocopy calls and flags

- mutation through the taint (subscript/attribute stores, ``del``,
  augmented assignment, mutating method calls like ``.update()``), and
  direct mutation of an unnamed call result
  (``api.get_nocopy(...)["x"] = 1``);
- storing a tainted object onto ``self`` (aliasing beyond the read);
- returning a tainted object (escape), outside the allowlisted *owner*
  modules that legitimately hand nocopy views onward.

Taint is propagated through assignment aliases, ``for`` targets over a
tainted list, and subscript loads (an element of a nocopy list is a
stored dict too).  The analysis is per-function and name-based — it is
a contract linter, not an escape analysis; cross-function flows stay
the runtime guard's job.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tputopo.lint.core import Checker, Finding, Module, subscript_root

#: Method names whose call results carry the nocopy contract.
NOCOPY_SOURCES = frozenset({"list_nocopy", "get_nocopy", "fetch"})

#: Modules that own the copy-free surfaces and may return/hold nocopy
#: views as part of their documented contract: the fake API server and
#: informer (they ARE the stores) and the sim engine (the single-threaded
#: copy-free facade over them).  Mutation is still flagged even here.
OWNER_MODULES = frozenset({
    "tputopo/k8s/fakeapi.py",
    "tputopo/k8s/informer.py",
    "tputopo/sim/engine.py",
})

_MUTATING_METHODS = frozenset({
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "setdefault", "sort", "reverse", "add", "discard",
})


def _is_nocopy_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in NOCOPY_SOURCES)


class _FunctionScan:
    def __init__(self, checker: "NocopyChecker", mod: Module,
                 fn: ast.AST) -> None:
        self.checker = checker
        self.mod = mod
        self.fn = fn
        self.tainted: set[str] = set()
        self.findings: list[Finding] = []

    # -- taint bookkeeping ---------------------------------------------------

    def _value_tainted(self, node: ast.AST) -> bool:
        """Does evaluating ``node`` yield a nocopy-contract object?"""
        if _is_nocopy_call(node):
            return True
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Subscript):
            return self._value_tainted(node.value)  # element of tainted list
        if isinstance(node, ast.IfExp):
            return (self._value_tainted(node.body)
                    or self._value_tainted(node.orelse))
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self._value_tainted(e) for e in node.elts)
        return False

    def _bind(self, target: ast.AST, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, tainted)

    # -- violations ----------------------------------------------------------

    def _flag(self, node: ast.AST, what: str) -> None:
        self.findings.append(Finding(
            self.mod.relpath, node.lineno, node.col_offset,
            self.checker.rule,
            f"{what} — list_nocopy/get_nocopy/handle().fetch() results are "
            "read-only stored objects (copy first, or go through the "
            "copying API)"))

    def _check_store_target(self, target: ast.AST) -> None:
        """Subscript/attribute stores whose base chain roots at a tainted
        object are mutations of a stored dict."""
        if isinstance(target, (ast.Subscript, ast.Attribute)):
            root = subscript_root(target)
            if self._value_tainted(root) or _is_nocopy_call(root):
                self._flag(target, "mutation of a nocopy result")
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._check_store_target(e)

    # -- walk ----------------------------------------------------------------

    def run(self) -> list[Finding]:
        body = self.fn.body if hasattr(self.fn, "body") else []
        for stmt in body:
            self._walk(stmt)
        return self.findings

    def _walk(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return  # nested scopes are scanned as their own functions
        handler = getattr(self, f"_visit_{type(node).__name__}", None)
        if handler is not None:
            handler(node)
        for child in ast.iter_child_nodes(node):
            self._walk(child)

    def _visit_Assign(self, node: ast.Assign) -> None:
        tainted = self._value_tainted(node.value)
        for target in node.targets:
            self._check_store_target(target)
            if isinstance(target, ast.Attribute) and tainted \
                    and isinstance(target.value, ast.Name) \
                    and target.value.id == "self" \
                    and not self.checker.is_owner(self.mod.relpath):
                self._flag(node, "nocopy result stored onto self")
            self._bind(target, tainted)

    def _visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is None:
            return
        tainted = self._value_tainted(node.value)
        self._check_store_target(node.target)
        self._bind(node.target, tainted)

    def _visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_store_target(node.target)
        if isinstance(node.target, ast.Name) \
                and node.target.id in self.tainted:
            self._flag(node, "augmented assignment to a nocopy result")

    def _visit_Delete(self, node: ast.Delete) -> None:
        for target in node.targets:
            self._check_store_target(target)

    def _visit_For(self, node: ast.For) -> None:
        self._bind(node.target, self._value_tainted(node.iter))

    def _visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATING_METHODS:
            base = node.func.value
            if self._value_tainted(base):
                self._flag(node, f"mutating call .{node.func.attr}() "
                                 "on a nocopy result")

    def _visit_Return(self, node: ast.Return) -> None:
        if node.value is not None and self._value_tainted(node.value) \
                and not self.checker.is_owner(self.mod.relpath):
            self._flag(node, "nocopy result escapes via return")


class NocopyChecker(Checker):
    rule = "nocopy"
    description = ("results of list_nocopy/get_nocopy/handle().fetch() must "
                   "not be mutated, stored onto self, or returned outside "
                   "owner modules")

    def __init__(self, owners: frozenset[str] = OWNER_MODULES) -> None:
        self.owners = owners

    def is_owner(self, relpath: str) -> bool:
        return relpath in self.owners

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(("tputopo/", "tests/"))

    def check_module(self, mod: Module) -> Iterable[Finding]:
        # Cheap pre-filter: a module that never names a nocopy source
        # cannot have a finding, and most modules never do.
        if not any(name in mod.source for name in NOCOPY_SOURCES):
            return ()
        findings: list[Finding] = []
        # Module level plus every function/method, each its own scope.
        findings.extend(_FunctionScan(self, mod, mod.tree).run())
        for node in mod.nodes():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(_FunctionScan(self, mod, node).run())
        return findings
