"""tputopo.sim — trace-driven cluster simulator for topology-aware
scheduling.

The evaluation engine behind every scheduler perf/policy claim in this
repo: a deterministic, seedable discrete-event simulator that replays
synthetic workload traces (Poisson/bursty gang arrivals, lognormal
durations, node churn, never-confirming "ghost" jobs) against the real
``ExtenderScheduler`` + ``FakeApiServer`` stack on a virtual clock, and
reports queue-wait quantiles, chip utilization, fragmentation, and
achieved-vs-ideal ICI bandwidth per policy — with count-only baselines
(:mod:`tputopo.topology.baselines`) run over the identical trace for A/B
deltas.  ``python -m tputopo.sim --help`` is the front door; bench.py's
``sim`` scenario feeds a compact summary into the BENCH record.
"""

from tputopo.sim.engine import SimEngine, SimError, VirtualClock, run_trace  # noqa: F401
from tputopo.sim.policies import available_policies, get_policy  # noqa: F401
from tputopo.sim.report import SCHEMA, build_report  # noqa: F401
from tputopo.sim.trace import JobSpec, Trace, TraceConfig, generate_trace  # noqa: F401
