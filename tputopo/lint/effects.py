"""The ``effect-purity`` checker: nocopy views demand pure receivers.

``nocopy`` (per-function) and ``nocopy-flow`` (interprocedural taint)
walk statements in AST order, which makes them *flow-insensitive
across branches*: a function that copies its argument in one branch and
mutates the original in the other is laundered clean, because the
rebind is "seen" before the mutation in source order::

    def thin(pods, aggressive):
        if aggressive:
            pods = [dict(p) for p in pods]   # copies on THIS path only
        pods.sort(...)                        # mutates the STORE on the other

This rule upgrades the contract to an **effect system over the CFG**:

- Compute, per function, whether any *nocopy view* can reach each
  parameter — interprocedurally: a view is a direct source result
  (``list_nocopy`` / ``get_nocopy`` / ``fetch`` / the ``copy=False``
  read family), the result of a *returns-view* function (summary
  fixpoint over the call graph), or a view-receiving parameter passed
  onward.
- For each view-receiving parameter, run a **may-hold-view** dataflow
  (:mod:`dataflow`, union join) over the function's CFG: per path,
  rebinding a name kills the view; aliasing, ``for`` targets and
  subscript loads propagate it.
- Any **store or mutation effect** through a name that may still hold
  the view on SOME path — subscript/attribute store, ``del``, augmented
  assignment, a mutating method call, storing it onto ``self`` — is a
  finding at the effect site, with one example caller that hands the
  view in.

Read-only effects (returns, iteration, passing onward to pure callees)
are exactly what the contract allows, so they are not findings here —
escapes are ``nocopy-flow``'s department.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tputopo.lint.callgraph import CallGraph, FunctionInfo, graph_for
from tputopo.lint.cfg import CFGNode, cfg_for, walk_exprs
from tputopo.lint.core import Checker, Finding, Module, subscript_root
from tputopo.lint.nocopy import _MUTATING_METHODS, NOCOPY_SOURCES
from tputopo.lint.nocopyflow import _is_copyfree_call, _is_direct_source


def _callee_param_names(callee: FunctionInfo) -> list[str]:
    names = callee.param_names()
    if names[:1] in (["self"], ["cls"]):
        names = names[1:]
    return names


def _own_nodes(fn_node: ast.AST):
    """Every AST node of a function's own body — nested function/class
    bodies excluded (they are separate functions)."""
    stack = list(getattr(fn_node, "body", []))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class _ViewWorld:
    """Interprocedural facts: which functions return views, which
    (function, param) pairs can receive one, and one example caller
    per receiving param (for the finding message)."""

    def __init__(self) -> None:
        self.returns_view: set[tuple] = set()
        self.receives: dict[tuple, set[str]] = {}      # fn key -> params
        self.example: dict[tuple, str] = {}            # (fn key, param)


class EffectPurityChecker(Checker):
    rule = "effect-purity"
    description = ("a function receiving a list_nocopy/get_nocopy/fetch/"
                   "copy=False view through a parameter must have no "
                   "store or mutation effect on it along ANY CFG path "
                   "(a copy on one branch does not excuse the other)")

    version = 1

    def __init__(self) -> None:
        self._mods: list[Module] = []

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(("tputopo/", "tests/"))

    def check_module(self, mod: Module) -> Iterable[Finding]:
        self._mods.append(mod)
        return ()

    # ---- interprocedural seeding -------------------------------------------

    @staticmethod
    def _touchy(mods: list[Module]) -> set[str]:
        return {m.relpath for m in mods
                if any(s in m.source for s in NOCOPY_SOURCES)
                or "copy=False" in m.source}

    def _value_is_view(self, node: ast.AST, world: _ViewWorld,
                       graph: CallGraph, fn: FunctionInfo,
                       local_views: set[str]) -> bool:
        """Does evaluating ``node`` (flow-insensitively, for seeding)
        yield a view?  ``local_views`` are names the caller already
        knows hold one."""
        if _is_direct_source(node) or _is_copyfree_call(node):
            return True
        if isinstance(node, ast.Call):
            callee = graph.resolve(node, fn)
            return callee is not None and callee.key in world.returns_view
        if isinstance(node, ast.Name):
            return node.id in local_views
        if isinstance(node, ast.Subscript):
            return self._value_is_view(node.value, world, graph, fn,
                                       local_views)
        if isinstance(node, (ast.IfExp,)):
            return (self._value_is_view(node.body, world, graph, fn,
                                        local_views)
                    or self._value_is_view(node.orelse, world, graph, fn,
                                           local_views))
        return False

    def _seed_world(self, graph: CallGraph, fns: list[FunctionInfo]
                    ) -> _ViewWorld:
        """Fixpoint over (returns-view, receives-view) summaries.  Name
        propagation here is deliberately coarse (any bind of a view to
        a name marks the name); precision lives in the per-path report
        pass below."""
        world = _ViewWorld()
        changed = True
        rounds = 0
        # Each round can only ADD summary facts, and a fact needs at
        # most one round per call-chain hop — 64 is far above any real
        # forwarding depth.  Exhausting it means a bug, and a truncated
        # summary silently un-flags real mutations, so fail LOUDLY
        # (same posture as dataflow.py's fixpoint backstop).
        while changed:
            if rounds >= 64:
                raise RuntimeError(
                    "effect-purity summary fixpoint did not converge "
                    f"after {rounds} rounds over {len(fns)} functions")
            changed = False
            rounds += 1
            for fn in fns:
                local: set[str] = set(world.receives.get(fn.key, ()))
                for node in _own_nodes(fn.node):
                    if isinstance(node, ast.Assign):
                        if self._value_is_view(node.value, world, graph,
                                               fn, local):
                            for t in node.targets:
                                if isinstance(t, ast.Name):
                                    local.add(t.id)
                    elif isinstance(node, ast.For):
                        if self._value_is_view(node.iter, world, graph,
                                               fn, local) \
                                and isinstance(node.target, ast.Name):
                            local.add(node.target.id)
                    elif isinstance(node, ast.Return) \
                            and node.value is not None:
                        if self._value_is_view(node.value, world, graph,
                                               fn, local) \
                                and fn.key not in world.returns_view:
                            world.returns_view.add(fn.key)
                            changed = True
                    elif isinstance(node, ast.Call):
                        callee = graph.resolve(node, fn)
                        if callee is None:
                            continue
                        pnames = _callee_param_names(callee)
                        for i, arg in enumerate(node.args):
                            if i < len(pnames) and self._value_is_view(
                                    arg, graph=graph, fn=fn,
                                    world=world, local_views=local):
                                got = world.receives.setdefault(
                                    callee.key, set())
                                if pnames[i] not in got:
                                    got.add(pnames[i])
                                    world.example.setdefault(
                                        (callee.key, pnames[i]),
                                        f"{fn.relpath}:{node.lineno} "
                                        f"({fn.qualname})")
                                    changed = True
                        for kw in node.keywords:
                            if kw.arg in pnames and self._value_is_view(
                                    kw.value, graph=graph, fn=fn,
                                    world=world, local_views=local):
                                got = world.receives.setdefault(
                                    callee.key, set())
                                if kw.arg not in got:
                                    got.add(kw.arg)
                                    world.example.setdefault(
                                        (callee.key, kw.arg),
                                        f"{fn.relpath}:{node.lineno} "
                                        f"({fn.qualname})")
                                    changed = True
        return world

    # ---- the per-path report pass ------------------------------------------

    def finalize(self) -> Iterable[Finding]:
        mods, self._mods = self._mods, []
        graph = graph_for(mods)
        touchy = self._touchy(mods)
        fns = sorted((f for f in graph.functions.values()
                      if f.relpath in touchy), key=lambda f: f.key)
        world = self._seed_world(graph, fns)
        for fn in fns:
            params = world.receives.get(fn.key)
            if not params or not fn.relpath.startswith("tputopo/"):
                continue
            yield from self._check_fn(graph, world, fn, params)

    def _check_fn(self, graph: CallGraph, world: _ViewWorld,
                  fn: FunctionInfo, params: set[str]) -> Iterable[Finding]:
        cfg = cfg_for(fn)
        checker = self

        class _A:
            """fact: frozenset[(name, origin-param)] — names that MAY
            still hold the view on some path into the node."""

            def entry_fact(self):
                return frozenset((p, p) for p in params)

            def join(self, a, b):
                return a | b

            def transfer(self, node: CFGNode, fact):
                s = node.stmt
                if s is None:
                    return fact
                if node.kind == "test" \
                        and isinstance(s, (ast.For, ast.AsyncFor)):
                    # Iterating a view list yields stored dicts: the
                    # loop target inherits the iterable's origins.
                    origins = checker._expr_origins(s.iter, fact)
                    names = checker._target_names(s.target)
                    out = {e for e in fact if e[0] not in names}
                    for n in names:
                        out |= {(n, o) for o in origins}
                    return frozenset(out)
                if node.kind != "stmt":
                    return fact
                if isinstance(s, ast.Assign):
                    origins = checker._expr_origins(s.value, fact)
                    out = set(fact)
                    for t in s.targets:
                        names = checker._target_names(t)
                        out = {e for e in out if e[0] not in names}
                        for n in names:
                            out |= {(n, o) for o in origins}
                    return frozenset(out)
                return fact

        findings: list[Finding] = []

        def visit(node: CFGNode, fact) -> None:
            if node.kind != "stmt" or node.stmt is None:
                return
            findings.extend(self._effects_at(node, fact, fn, world))

        from tputopo.lint.dataflow import run_forward

        run_forward(cfg, _A(), visit=visit)
        yield from findings

    @staticmethod
    def _target_names(t: ast.AST) -> set[str]:
        if isinstance(t, ast.Name):
            return {t.id}
        if isinstance(t, (ast.Tuple, ast.List)):
            out = set()
            for e in t.elts:
                if isinstance(e, ast.Name):
                    out.add(e.id)
            return out
        return set()

    @staticmethod
    def _expr_origins(expr: ast.AST, fact) -> set[str]:
        """Origin params whose view the expression may evaluate to."""
        if isinstance(expr, ast.Name):
            return {o for (n, o) in fact if n == expr.id}
        if isinstance(expr, ast.Subscript):
            return EffectPurityChecker._expr_origins(expr.value, fact)
        if isinstance(expr, ast.IfExp):
            return (EffectPurityChecker._expr_origins(expr.body, fact)
                    | EffectPurityChecker._expr_origins(expr.orelse, fact))
        if isinstance(expr, ast.BoolOp):
            out = set()
            for v in expr.values:
                out |= EffectPurityChecker._expr_origins(v, fact)
            return out
        return set()

    def _effects_at(self, node: CFGNode, fact, fn: FunctionInfo,
                    world: _ViewWorld) -> list[Finding]:
        out: list[Finding] = []
        s = node.stmt

        def flag(ast_node, what: str, origin: str) -> None:
            example = world.example.get((fn.key, origin), "a caller")
            out.append(Finding(
                fn.relpath, ast_node.lineno, ast_node.col_offset,
                self.rule,
                f"{what} on parameter {origin!r} of {fn.qualname}(), "
                f"which receives a copy-free view (e.g. from {example}) "
                "— the view is the stored object; copy before mutating, "
                "on EVERY path"))

        def root_origins(expr: ast.AST) -> set[str]:
            root = subscript_root(expr)
            if isinstance(root, ast.Name):
                return {o for (n, o) in fact if n == root.id}
            return set()

        if isinstance(s, ast.Assign):
            for t in s.targets:
                if isinstance(t, (ast.Subscript, ast.Attribute)):
                    for o in sorted(root_origins(t)):
                        flag(t, "store through a view", o)
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    for o in sorted(self._expr_origins(s.value, fact)):
                        flag(s, "storing the view onto self", o)
        elif isinstance(s, ast.AugAssign):
            if isinstance(s.target, (ast.Subscript, ast.Attribute)):
                for o in sorted(root_origins(s.target)):
                    flag(s.target, "augmented store through a view", o)
            elif isinstance(s.target, ast.Name):
                # ``views += [...]`` mutates the underlying list in place.
                for o in sorted({o for (n, o) in fact
                                 if n == s.target.id}):
                    flag(s, "augmented assignment to a view", o)
        elif isinstance(s, ast.Delete):
            for t in s.targets:
                if isinstance(t, (ast.Subscript, ast.Attribute)):
                    for o in sorted(root_origins(t)):
                        flag(t, "del through a view", o)
        # Mutating method calls anywhere in the statement's expressions.
        for sub in walk_exprs(node):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in _MUTATING_METHODS:
                for o in sorted(root_origins(sub.func.value)):
                    flag(sub, f"mutating call .{sub.func.attr}()", o)
        return out
