"""The ``lockset`` checker: path-sensitive race detection.

The lexical ``lock`` rule (locks.py) proves a guarded attribute sits
inside *some* ``with self.<lock>:`` block, trusting every ``#
holds-lock:`` annotation it meets.  This rule re-derives the same
contract over the control-flow graph (:mod:`cfg`) with a must-hold
lockset dataflow (:mod:`dataflow`), composed interprocedurally on the
call graph — which buys three things the lexical rule cannot see:

- **Thread roots are enumerated, not assumed.**  Concurrency enters this
  codebase at known points: ``threading.Thread(target=...)`` call sites
  (the informer watch loops, the server's GC/defrag loops), the threaded
  HTTP server's ``do_*`` handler methods, and any ``def`` carrying a
  ``# thread-root: <reason>`` directive (how a new subsystem registers
  one — e.g. the chaos-injected crash/restart path).  Enforcement covers
  every function reachable from a thread root plus every method of a
  lock-owning class.
- **``# guarded-by:`` / ``# holds-lock:`` are demoted from trusted input
  to checked claim.**  A ``# holds-lock: _x`` annotation seeds the entry
  lockset — and every *caller* of that function is checked to actually
  hold ``_x`` at the call site.  A claim nobody establishes is a
  finding, not a free pass.
- **Non-atomic read-modify-write detection.**  A value read from a
  guarded attribute under one lock region that flows into a write of the
  same attribute under a *different* region (the lock was released and
  re-taken in between — including across a ``Condition.wait()``, which
  drops the lock mid-``with``) is a lost-update window even though both
  accesses are individually "under the lock".  Attributes declared
  ``(writes)`` are exempt: lock-free readers + serialized check-then-act
  writers is that pattern's documented design.

Locks, Condition aliasing, and canonicalization are shared with
``lock-order`` (:func:`lockorder.discover_locks`); guard declarations
are shared with ``lock`` (the ``# guarded-by:`` grammar).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from tputopo.lint.callgraph import (CallGraph, FunctionInfo, graph_for)
from tputopo.lint.cfg import CFGNode, cfg_for, walk_exprs as _walk_exprs
from tputopo.lint.core import Checker, Finding, Module, dotted_name
from tputopo.lint.dataflow import run_forward
from tputopo.lint.lockorder import (LockKey, canonical_lock, discover_locks,
                                    entry_held_locks)
from tputopo.lint.locks import _GUARDED_RE, _GuardDecl, _self_attr
from tputopo.lint.nocopy import _MUTATING_METHODS

_THREAD_ROOT_RE = re.compile(r"#\s*thread-root:\s*(?P<reason>.*\S)")

#: Text markers that make a module worth scanning for roots/claims.
_ROOT_MARKERS = ("Thread(", "thread-root", "BaseHTTPRequestHandler")


class _ClassGuards:
    """Guard declarations of one class: attr -> (_GuardDecl, canonical
    lock keys the declaration accepts)."""

    __slots__ = ("decls",)

    def __init__(self) -> None:
        self.decls: dict[str, tuple[_GuardDecl, frozenset[LockKey]]] = {}


# Fact shape (immutable, hashable):
#   held:  tuple of (LockKey, frozenset[region]) sorted by key
#   taint: frozenset of (name, attr, LockKey, frozenset[region])
# A region is ("with", id(With-node)) / ("acq", node-idx) / ("entry",)
# / ("wait", node-idx, owner) — the OWNER (the With that created the
# hold) survives a Condition.wait() re-region, so the matching
# with_exit still releases it; an id-offset scheme would leak the hold
# past the with after any wait().
_EMPTY_FACT = ((), frozenset())


def _held_to_map(held) -> dict:
    return {k: r for k, r in held}


def _map_to_held(m: dict) -> tuple:
    return tuple(sorted(m.items()))


def _region_owner(region) -> int | None:
    """The id() of the With node a region belongs to, or None for
    entry/manual-acquire holds (released by annotation scope or
    ``.release()``, never by a with_exit)."""
    if region[0] == "with":
        return region[1]
    if region[0] == "wait":
        return region[2]
    return None


class _LocksetAnalysis:
    """The per-function must-hold dataflow (see module docstring)."""

    def __init__(self, checker: "LocksetChecker", fn: FunctionInfo,
                 graph: CallGraph, entry_held: frozenset[LockKey]) -> None:
        self.checker = checker
        self.fn = fn
        self.graph = graph
        self.entry_held = entry_held
        self.locks = checker.locks
        self.aliases = checker.aliases

    def entry_fact(self):
        return (tuple(sorted((k, frozenset({("entry",)}))
                             for k in self.entry_held)),
                frozenset())

    def join(self, a, b):
        am, bm = _held_to_map(a[0]), _held_to_map(b[0])
        held = {k: am[k] | bm[k] for k in am.keys() & bm.keys()}
        return (_map_to_held(held), a[1] | b[1])

    # -- helpers -------------------------------------------------------------

    def _lock_of_expr(self, expr: ast.AST):
        attr = _self_attr(expr)
        if attr is None:
            return None
        return canonical_lock(self.fn, attr, self.locks, self.aliases)

    def transfer(self, node: CFGNode, fact):
        held = _held_to_map(fact[0])
        taint = fact[1]
        s = node.stmt
        if node.kind == "with_enter":
            for item in s.items:
                decl = self._lock_of_expr(item.context_expr)
                if decl is not None:
                    held[decl.key] = (held.get(decl.key, frozenset())
                                      | {("with", id(s))})
            return (_map_to_held(held), taint)
        if node.kind == "with_exit":
            # Release the regions THIS with owns (wait-re-regioned ones
            # included — the owner survives the re-region); a reentrant
            # outer hold of the same lock keeps its other regions.
            for item in s.items:
                decl = self._lock_of_expr(item.context_expr)
                if decl is not None and decl.key in held:
                    regions = {r for r in held[decl.key]
                               if _region_owner(r) != id(s)}
                    if regions:
                        held[decl.key] = regions
                    else:
                        del held[decl.key]
            return (_map_to_held(held), taint)
        changed = False
        new_taint = taint
        for sub in _walk_exprs(node):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute):
                if sub.func.attr in ("acquire", "release", "wait"):
                    decl = self._lock_of_expr(sub.func.value)
                    if decl is not None:
                        if sub.func.attr == "acquire":
                            held[decl.key] = (held.get(decl.key, frozenset())
                                              | {("acq", node.idx)})
                        elif sub.func.attr == "release":
                            held.pop(decl.key, None)
                        elif decl.key in held:
                            # Condition.wait() drops and re-takes the
                            # lock: same hold (same owning with), NEW
                            # region — a read-before / write-after pair
                            # spans a real race window.
                            held[decl.key] = frozenset(
                                {("wait", node.idx, _region_owner(r))
                                 for r in held[decl.key]})
                        changed = True
        # RMW taint bookkeeping: name <- guarded-attr read.
        if isinstance(s, ast.Assign) and node.kind == "stmt":
            src_attr = _self_attr(s.value)
            guards = self.checker.guards_of(self.fn)
            # EVERY rebound name kills its stale taint — tuple-unpacking
            # targets included (a Name-only kill left stale taint behind
            # `v, other = ...` and produced spurious RMW findings).
            bound = []
            for t in s.targets:
                if isinstance(t, ast.Name):
                    bound.append(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    bound.extend(e.id for e in t.elts
                                 if isinstance(e, ast.Name))
            if bound:
                # Rebinds kill stale taint for these names.
                kept = frozenset(e for e in new_taint if e[0] not in bound)
                taint_bound = [t.id for t in s.targets
                               if isinstance(t, ast.Name)]
                if src_attr is not None and guards is not None \
                        and taint_bound and src_attr in guards.decls:
                    decl, lock_keys = guards.decls[src_attr]
                    if not decl.writes_only:
                        for lk in lock_keys:
                            regions = held.get(lk)
                            if regions:
                                kept = kept | {(n, src_attr, lk, regions)
                                               for n in taint_bound}
                if kept != new_taint:
                    new_taint = kept
                    changed = True
        if changed or new_taint is not taint:
            return (_map_to_held(held), new_taint)
        return fact


class LocksetChecker(Checker):
    rule = "lockset"
    description = ("path-sensitive lockset analysis from enumerated "
                   "thread roots: guarded attributes must be reached "
                   "with the lock held on EVERY path, # holds-lock: "
                   "claims are verified at call sites, and non-atomic "
                   "read-modify-write across lock regions is flagged")

    version = 1

    def __init__(self) -> None:
        self._mods: list[Module] = []
        self.locks = {}
        self.aliases = {}
        self._guards_by_class: dict[tuple, _ClassGuards] = {}

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(("tputopo/", "tests/"))

    def check_module(self, mod: Module) -> Iterable[Finding]:
        self._mods.append(mod)
        return ()

    # ---- guard declarations ------------------------------------------------

    def _collect_init_attrs(self, graph: CallGraph) -> None:
        """Instance attributes born in ``__init__`` of LOCK-OWNING
        classes: mutating one of these (container mutation, not a plain
        rebind) from a thread-reachable method with no class lock held
        is shared-state corruption waiting for load — flagged even
        WITHOUT a ``# guarded-by:`` declaration (the unguarded-shared-
        attribute half of this rule)."""
        self._init_attrs: dict[tuple, set[str]] = {}
        lock_classes = {k[0] for k in self.locks}
        for ci in graph.classes.values():
            if ci.key not in lock_classes:
                continue
            init = ci.methods.get("__init__")
            if init is None:
                continue
            attrs = set()
            for node in ast.walk(init.node):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AnnAssign):
                    targets = [node.target]
                for t in targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        attrs.add(attr)
            amap = self.aliases.get(ci.key, {})
            self._init_attrs[ci.key] = attrs - set(amap)

    def _collect_guards(self, graph: CallGraph,
                        by_path: dict[str, Module]) -> None:
        for ci in graph.classes.values():
            mod = by_path.get(ci.relpath)
            if mod is None or "guarded-by" not in mod.source:
                continue
            init = ci.methods.get("__init__")
            if init is None:
                continue
            cg = _ClassGuards()
            for node in ast.walk(init.node):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AnnAssign):
                    targets = [node.target]
                for t in targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    m = _GUARDED_RE.search(mod.comment_on_or_above(t.lineno))
                    if m is None:
                        continue
                    decl = _GuardDecl(
                        frozenset(m.group("locks").split("|")),
                        m.group("mode") == "writes", t.lineno)
                    keys = set()
                    for lname in decl.locks:
                        ld = canonical_lock(init, lname, self.locks,
                                            self.aliases)
                        if ld is not None:
                            keys.add(ld.key)
                    cg.decls[attr] = (decl, frozenset(keys))
            if cg.decls:
                self._guards_by_class[ci.key] = cg

    def guards_of(self, fn: FunctionInfo) -> _ClassGuards | None:
        if fn.cls is None:
            return None
        merged: _ClassGuards | None = None
        for c in fn.cls.mro():
            cg = self._guards_by_class.get(c.key)
            if cg is None:
                continue
            if merged is None:
                merged = cg
            else:  # subclass sees base guards too (rare; merge lazily)
                both = _ClassGuards()
                both.decls = {**cg.decls, **merged.decls}
                merged = both
        return merged

    # ---- thread roots ------------------------------------------------------

    def _thread_roots(self, graph: CallGraph,
                      by_path: dict[str, Module]
                      ) -> tuple[dict[tuple, str], list[Finding]]:
        """{function key: reason} for every discovered thread root."""
        roots: dict[tuple, str] = {}
        findings: list[Finding] = []
        for fn in graph.functions.values():
            if not fn.relpath.startswith("tputopo/"):
                continue
            mod = by_path.get(fn.relpath)
            if mod is None or not any(mk in mod.source
                                      for mk in _ROOT_MARKERS):
                continue
            # (a) explicit directive on the def line
            m = _THREAD_ROOT_RE.search(
                mod.comment_on_or_above(fn.node.lineno))
            if m is not None:
                roots[fn.key] = f"declared: {m.group('reason')}"
            # (b) threading.Thread(target=...) call sites
            for site in graph.callees(fn):
                if site.dotted is None or \
                        site.dotted.rsplit(".", 1)[-1] != "Thread":
                    continue
                target = next((kw.value for kw in site.node.keywords
                               if kw.arg == "target"), None)
                if target is None:
                    continue
                resolved = graph._resolve_target(target, fn)
                if isinstance(resolved, FunctionInfo):
                    roots.setdefault(
                        resolved.key,
                        f"Thread target at {fn.relpath}:"
                        f"{site.node.lineno}")
                else:
                    findings.append(Finding(
                        fn.relpath, site.node.lineno,
                        site.node.col_offset, self.rule,
                        "thread root could not be resolved: Thread("
                        "target=...) does not name a known function — "
                        "name it directly or mark the target def with "
                        "`# thread-root: <reason>`"))
        # (c) HTTP handler methods (ThreadingHTTPServer runs each
        # request on its own thread).
        for ci in graph.classes.values():
            if not ci.relpath.startswith("tputopo/"):
                continue
            base_names = {b for e in ci.base_exprs
                          if (b := dotted_name(e)) is not None}
            if not any("BaseHTTPRequestHandler" in b or "_Handler" in b
                       for b in base_names):
                continue
            for name, meth in ci.methods.items():
                if name.startswith("do_"):
                    roots.setdefault(meth.key,
                                     "HTTP handler (threaded server)")
        return roots, findings

    # ---- the analysis ------------------------------------------------------

    def finalize(self) -> Iterable[Finding]:
        mods, self._mods = self._mods, []
        graph = graph_for(mods)
        by_path = {m.relpath: m for m in mods}
        self._mods_by_path = by_path
        self.locks, self.aliases = discover_locks(graph)
        if not self.locks:
            return
        self._collect_guards(graph, by_path)
        self._collect_init_attrs(graph)
        roots, findings = self._thread_roots(graph, by_path)

        # Reachability from thread roots, remembering one example path
        # for messages (shared helper with hot-path-scan).
        parent = graph.closure_with_parents(roots)

        lock_classes = {k[0] for k in self.locks}
        enforce: set[tuple] = set(parent)
        for fn in graph.functions.values():
            if fn.cls is not None and fn.cls.key in lock_classes:
                enforce.add(fn.key)

        for key in sorted(enforce):
            fn = graph.functions.get(key)
            if fn is None or not fn.relpath.startswith("tputopo/"):
                continue
            if fn.qualname.endswith("__init__"):
                continue  # the object is not shared yet
            mod = by_path.get(fn.relpath)
            if mod is None:
                continue
            findings.extend(self._check_function(graph, mod, fn, roots,
                                                 parent))
        yield from findings

    def _root_path(self, graph: CallGraph, parent, roots,
                   key: tuple) -> str:
        via = graph.render_entry_path(parent, key)
        root_key = key
        while parent.get(root_key) is not None:
            root_key = parent[root_key]
        reason = roots.get(root_key, "")
        return f"{via} [{reason}]" if reason else via

    def _check_function(self, graph: CallGraph, mod: Module,
                        fn: FunctionInfo, roots, parent) -> list[Finding]:
        guards = self.guards_of(fn)
        # Cheap relevance gate: the function must touch a guarded attr,
        # a lock primitive, or call an annotated helper.
        callee_claims: dict[int, tuple[FunctionInfo, frozenset]] = {}
        for site in graph.callees(fn):
            callee = site.callee
            if callee is None or not callee.relpath.startswith("tputopo/"):
                continue
            cmod = self._mod_of(callee.relpath)
            if cmod is None or "holds-lock" not in cmod.source:
                continue
            claimed = entry_held_locks(cmod, callee, self.locks,
                                       self.aliases)
            if claimed:
                callee_claims[id(site.node)] = (callee, claimed)
        touches_guard = guards is not None and any(
            attr in mod.source for attr in guards.decls)
        reachable = fn.key in parent
        shared_attrs = self._shared_attrs_of(fn) if reachable else frozenset()
        if not touches_guard and not callee_claims and not shared_attrs:
            return []

        entry = entry_held_locks(mod, fn, self.locks, self.aliases)
        analysis = _LocksetAnalysis(self, fn, graph, entry)
        cfg = cfg_for(fn)
        out: list[Finding] = []
        in_facts = run_forward(cfg, analysis)

        for node in cfg.nodes:
            fact = in_facts.get(node.idx)
            if fact is None:
                continue
            # The fact AFTER this node's own acquisitions: accesses in a
            # with_enter node (none) / statements see the pre-state; for
            # plain statements the pre-state is correct (an acquire in
            # the same statement cannot guard its own expression).
            held = _held_to_map(fact[0])
            taint = fact[1]
            if guards is not None:
                out.extend(self._check_accesses(mod, fn, node, held, taint,
                                                guards, roots, parent,
                                                graph, reachable))
            if shared_attrs and not held:
                out.extend(self._check_unannotated(mod, fn, node,
                                                   shared_attrs,
                                                   guards, roots, parent,
                                                   graph))
            for sub in _walk_exprs(node):
                if isinstance(sub, ast.Call):
                    claim = callee_claims.get(id(sub))
                    if claim is None:
                        continue
                    callee, locks_needed = claim
                    missing = [lk for lk in locks_needed if lk not in held]
                    if missing:
                        names = ", ".join(self.locks[lk].display
                                          for lk in missing)
                        out.append(Finding(
                            mod.relpath, sub.lineno, sub.col_offset,
                            self.rule,
                            f"call to {callee.qualname}() which claims "
                            f"`# holds-lock: {names}` — but this path "
                            "does not hold it; take the lock here or "
                            "fix the annotation (claims are checked, "
                            "not trusted)"))
        return out

    _mods_by_path: dict[str, Module] | None = None

    def _mod_of(self, relpath: str) -> Module | None:
        return (self._mods_by_path or {}).get(relpath)

    def _shared_attrs_of(self, fn: FunctionInfo) -> frozenset[str]:
        """Init-born attrs of ``fn``'s (lock-owning) class hierarchy."""
        if fn.cls is None:
            return frozenset()
        out: set[str] = set()
        for c in fn.cls.mro():
            out |= self._init_attrs.get(c.key, set())
        return frozenset(out)

    @staticmethod
    def _self_attr_root(expr: ast.AST) -> str | None:
        """The ``self.<attr>`` prefix under at least one more
        subscript/attribute layer (``self.m["k"]``, ``self.m.field``) —
        a store here mutates the CONTAINER, not the attribute slot."""
        seen_layer = False
        while isinstance(expr, (ast.Subscript, ast.Attribute)):
            attr = _self_attr(expr)
            if attr is not None:
                return attr if seen_layer else None
            seen_layer = True
            expr = expr.value
        return None

    def _check_unannotated(self, mod, fn, node: CFGNode, shared_attrs,
                           guards, roots, parent, graph) -> list[Finding]:
        """Container mutations of unannotated init-born attributes with
        no class lock held, in thread-reachable code.  Plain attribute
        rebinds (``self.x = y``) are NOT flagged — a pointer swap is
        atomic under the GIL and is the published-pair pattern's
        foundation; what races is in-place container mutation."""
        declared = set(guards.decls) if guards is not None else set()
        out = []

        def flag(ast_node, attr: str, what: str) -> None:
            via = self._root_path(graph, parent, roots, fn.key)
            out.append(Finding(
                mod.relpath, ast_node.lineno, ast_node.col_offset,
                self.rule,
                f"unguarded {what} of shared self.{attr} with no lock "
                f"held — reachable from thread root via {via}; declare "
                f"it `# guarded-by: <lock>` on its __init__ assignment "
                "and take the lock (or move the mutation under one)"))

        for sub in _walk_exprs(node):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in _MUTATING_METHODS:
                # Direct container mutation only (self.attr.pop(...)) —
                # a method on an ELEMENT (self._synced[k].clear()) may
                # be that object's own thread-safe primitive.
                attr = _self_attr(sub.func.value)
                if attr in shared_attrs and attr not in declared:
                    flag(sub, attr, f"mutating call .{sub.func.attr}()")
        s = node.stmt
        if isinstance(s, ast.Assign):
            for t in s.targets:
                attr = self._self_attr_root(t)
                if attr in shared_attrs and attr not in declared:
                    flag(t, attr, "container store")
        elif isinstance(s, ast.AugAssign):
            attr = self._self_attr_root(s.target) or _self_attr(s.target)
            if attr in shared_attrs and attr not in declared:
                flag(s, attr, "read-modify-write")
        elif isinstance(s, ast.Delete):
            for t in s.targets:
                attr = self._self_attr_root(t)
                if attr in shared_attrs and attr not in declared:
                    flag(t, attr, "del")
        return out

    def _check_accesses(self, mod, fn, node: CFGNode, held, taint, guards,
                        roots, parent, graph, reachable) -> list[Finding]:
        out = []
        for sub in _walk_exprs(node):
            attr = _self_attr(sub)
            if attr is None or attr not in guards.decls:
                continue
            decl, lock_keys = guards.decls[attr]
            is_store = isinstance(sub.ctx, (ast.Store, ast.Del))
            if decl.writes_only and not is_store:
                continue
            held_regions = set()
            for lk in lock_keys:
                held_regions |= held.get(lk, set())
            if not held_regions:
                what = "write" if is_store else "read"
                where = ""
                if reachable:
                    where = (" — reachable from thread root via "
                             + self._root_path(graph, parent, roots,
                                               fn.key))
                locks_txt = "|".join(sorted(
                    self.locks[lk].display for lk in lock_keys)) or \
                    "|".join(sorted(decl.locks))
                out.append(Finding(
                    mod.relpath, sub.lineno, sub.col_offset, self.rule,
                    f"self.{attr} ({what}) on a path where no declared "
                    f"guard ({locks_txt}) is held{where}; wrap the "
                    "access or annotate the helper with "
                    "`# holds-lock:` (the claim is then checked at "
                    "every call site)"))
                continue
            # Non-atomic RMW: this write's value derives from a read of
            # the same attribute taken under a DIFFERENT lock region.
            if is_store and not decl.writes_only \
                    and isinstance(node.stmt, (ast.Assign, ast.AugAssign)):
                value = getattr(node.stmt, "value", None)
                if value is None:
                    continue
                used = {n.id for n in ast.walk(value)
                        if isinstance(n, ast.Name)}
                for (tname, tattr, tlk, tregions) in taint:
                    if tattr != attr or tname not in used:
                        continue
                    if not (tregions & held_regions):
                        out.append(Finding(
                            mod.relpath, sub.lineno, sub.col_offset,
                            self.rule,
                            f"non-atomic read-modify-write of self."
                            f"{attr}: the value derives from a read "
                            f"(via {tname!r}) taken under a different "
                            "lock region — the lock was released in "
                            "between, so a concurrent writer can be "
                            "lost; hold the lock across the full "
                            "sequence"))
                        break
        return out
