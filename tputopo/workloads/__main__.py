"""``python -m tputopo.workloads`` — in-container acceptance workload.

This is what runs inside a pod the extender scheduled (the rebuild's analog
of Gaia's MNIST acceptance containers, PDF §IV Exp.6).  Two subcommands:

- ``allreduce``: measure all-reduce over the chips this container was
  handed and compare against the cost model's prediction for the slice
  topology in the injected env (``TPU_SLICE_TOPOLOGY`` — reporter.py).
  Exit code 1 when efficiency falls below ``--min-efficiency``.
- ``train``: run N sharded training steps of the flagship LM over the
  local devices (mesh planned from the device count).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys


def cmd_allreduce(args) -> int:
    from tputopo.workloads.validate import validate_slice

    spec = args.topology or os.environ.get("TPU_SLICE_TOPOLOGY")
    gen = os.environ.get("TPU_ACCELERATOR_TYPE", "")
    if spec and ":" not in spec and gen:
        # Allocate-injected env carries bare dims ("2x2x4"); prepend the
        # generation from the accelerator type ("v5p-32" -> "v5p").
        spec = f"{gen.split('-')[0]}:{spec}"
    if not spec:
        print("error: no --topology and no TPU_SLICE_TOPOLOGY env",
              file=sys.stderr)
        return 2
    report = validate_slice(spec, payload_mb=args.payload_mb, iters=args.iters)
    print(json.dumps(report.to_dict()))
    if args.min_efficiency and report.efficiency < args.min_efficiency:
        print(f"FAIL: efficiency {report.efficiency:.3f} < "
              f"{args.min_efficiency}", file=sys.stderr)
        return 1
    return 0


def cmd_train(args) -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tputopo.workloads.model import ModelConfig
    from tputopo.workloads.sharding import mesh_for_slice
    from tputopo.workloads.train import make_sharded_state, make_sharded_train_step

    n = jax.device_count()
    moe = None
    if args.experts:
        from tputopo.workloads.moe import MoEConfig

        moe = MoEConfig(n_experts=args.experts)
    elif args.ep and args.ep > 1:
        print("error: --ep needs --experts (a dense model would replicate "
              "over the ep axis and waste those chips)", file=sys.stderr)
        return 2
    config = ModelConfig(vocab_size=2048, d_model=256, n_layers=4, n_heads=8,
                         n_kv_heads=4, d_ff=512, max_seq=args.seq, moe=moe,
                         sp_impl=getattr(args, "sp_impl", "ring"))
    plan = mesh_for_slice((n,), heads=config.n_heads, pp=args.pp, ep=args.ep,
                          sp=args.sp, tp=args.tp)
    if config.n_layers % plan.axes["pp"]:
        print(f"error: --pp {args.pp} must divide {config.n_layers} layers",
              file=sys.stderr)
        return 2
    lora_rank = getattr(args, "lora_rank", 0)
    if lora_rank:
        # Parameter-efficient finetuning: the base tree is frozen (here a
        # fresh init standing in for restored pretrained weights; point
        # --ckpt-dir at an adapter dir to resume the ADAPTER), only the
        # LoRA TrainState trains/checkpoints.
        from functools import partial

        from tputopo.workloads import sharding as shardlib
        from tputopo.workloads.lora import (make_sharded_lora_state,
                                            make_sharded_lora_train_step)
        from tputopo.workloads.model import init_params

        with plan.mesh:
            base = jax.jit(
                partial(init_params, config),
                out_shardings=shardlib.param_shardings(plan, config),
            )(jax.random.key(0))
        state = make_sharded_lora_state(plan, config, jax.random.key(1),
                                        rank=lora_rank)
        lora_step = make_sharded_lora_train_step(
            plan, config, state.params, accum_steps=max(1, args.accum))
    else:
        state = make_sharded_state(plan, config, jax.random.key(0))
    resumed_from = None
    if args.ckpt_dir:
        from tputopo.workloads import checkpoint as ckptlib

        restored = ckptlib.restore(args.ckpt_dir, state)
        if restored is not None:
            state = restored
            resumed_from = int(state.step)
    if lora_rank:
        step = lambda s, t: lora_step(s, base, t)  # noqa: E731
    else:
        step = make_sharded_train_step(plan, config,
                                       accum_steps=max(1, args.accum))
    rng = np.random.default_rng(0)
    # Batch must shard over dp, split into pp microbatches, AND divide
    # into gradient-accumulation microbatches.
    q = (max(1, plan.axes["dp"]) * max(1, plan.axes["pp"])
         * max(1, args.accum))
    batch = max(q, args.batch // q * q)
    data_path = getattr(args, "data", None)
    batch_for = None
    if data_path:
        # Real corpus: deterministic disjoint shards per (step, process) —
        # resumable from the checkpointed step (workloads/data.py).
        from tputopo.workloads.data import TokenDataset

        ds = TokenDataset(data_path, dtype=args.data_dtype)
        hi = ds.max_token()
        if hi >= config.vocab_size:
            print(f"error: corpus has token id {hi} >= vocab "
                  f"{config.vocab_size}", file=sys.stderr)
            return 2
        nproc = jax.process_count()
        if batch % nproc:
            print(f"error: batch {batch} not divisible by {nproc} "
                  "processes", file=sys.stderr)
            return 2
        if nproc > 1 and plan.axes.get("dp", 1) % nproc:
            # Per-process shards stitch into the global batch along dp;
            # a dp axis that doesn't split over the processes would
            # declare differing host-local halves "replicated" — silent
            # divergence, the one failure mode worse than an error.
            print(f"error: --data with {nproc} processes needs the dp "
                  f"axis ({plan.axes.get('dp', 1)}) divisible by the "
                  "process count", file=sys.stderr)
            return 2

        def batch_for(i: int):
            local = ds.batch(i, batch // nproc, args.seq,
                             rank=jax.process_index(), world=nproc)
            arr = jnp.asarray(local)
            if nproc > 1:
                from jax.experimental import multihost_utils
                from jax.sharding import PartitionSpec as P

                arr = multihost_utils.host_local_array_to_global_array(
                    arr, plan.mesh, P("dp", None))
            return arr

    # Fixed synthetic batch otherwise: the convergence check is
    # memorization, which must always reduce loss — fresh random batches
    # each step need not.
    tokens = jnp.asarray(rng.integers(0, config.vocab_size, (batch, args.seq)))

    # Graceful preemption: kubernetes sends SIGTERM (then SIGKILL after
    # terminationGracePeriodSeconds) when it evicts or preempts the pod —
    # e.g. the extender re-placing a gang after a chip failure.  Finish
    # the in-flight step, save a checkpoint, and exit cleanly so the
    # replacement pod resumes instead of losing the epoch.  The flag flips
    # between steps; nothing async-unsafe happens in the handler.
    import signal

    preempted = {"flag": False}

    def _on_preempt(signum, frame):
        preempted["flag"] = True

    try:
        prev_term = signal.signal(signal.SIGTERM, _on_preempt)
    except ValueError:  # non-main thread (tests driving main() directly)
        prev_term = None
    # Multi-host gangs must AGREE on the stop step: kubelet delivers
    # SIGTERM to each pod independently, and a rank that breaks one step
    # before its peers leaves them blocked in a collective (then the
    # checkpoint save — itself a cross-host collective — deadlocks too).
    # One tiny allgather per step settles it; against real step times the
    # cost is noise.
    sync_preempt = None
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        def sync_preempt(local: bool) -> bool:
            got = multihost_utils.process_allgather(
                np.asarray([1 if local else 0], dtype=np.int32))
            return bool(np.asarray(got).max())

    if args.profile and args.steps < 2:
        print("warning: --profile needs --steps >= 2 (step 0 is the "
              "compile step and is excluded); no trace will be written",
              file=sys.stderr)
    losses = []
    last_saved = None
    profiling = False
    try:
        for i in range(args.steps):
            if batch_for is not None:
                tokens = batch_for(i + (resumed_from or 0))
            state, loss = step(state, tokens)
            losses.append(float(loss))
            if args.profile and i == 0 and args.steps > 1:
                # Trace steady-state steps only: step 0 is the compile.
                jax.profiler.start_trace(args.profile)
                profiling = True
            if args.ckpt_dir and args.save_every and (i + 1) % args.save_every == 0:
                from tputopo.workloads import checkpoint as ckptlib

                last_saved = ckptlib.save(args.ckpt_dir, state)
            stop = preempted["flag"]
            if sync_preempt is not None:
                stop = sync_preempt(stop)
            if stop:
                preempted["flag"] = True
                break
        if profiling:
            jax.profiler.stop_trace()
            profiling = False
        # Final save INSIDE the handler's scope — a second SIGTERM during
        # the save must not kill the very write that preserves the run.
        # Skipped when the in-loop save already wrote this exact step
        # (orbax refuses to overwrite an existing step_N directory, which
        # would fail the pod after a fully successful run).
        if args.ckpt_dir and last_saved != int(state.step):
            from tputopo.workloads import checkpoint as ckptlib

            ckptlib.save(args.ckpt_dir, state)
    finally:
        if profiling:  # crash mid-trace: flush what exists
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
        if prev_term is not None:
            signal.signal(signal.SIGTERM, prev_term)
    print(json.dumps({
        "devices": n, "mesh": plan.axes, "steps": args.steps,
        "resumed_from": resumed_from, "final_step": int(state.step),
        "preempted": preempted["flag"],
        "first_loss": round(losses[0], 4), "last_loss": round(losses[-1], 4),
    }))
    if preempted["flag"]:
        # With a checkpoint saved, exit 0 so the Job controller counts the
        # pod done rather than retry-looping a node the scheduler is
        # draining; the resumed replacement carries the convergence check
        # forward.  WITHOUT --ckpt-dir nothing was preserved — exit
        # nonzero so the work is retried, not silently recorded as done.
        return 0 if args.ckpt_dir else 1
    if batch_for is not None:
        # Fresh corpus batches each step need not reduce loss monotonically
        # (the memorization check is for the fixed synthetic batch).
        return 0 if all(math.isfinite(l) for l in losses) else 1
    return 0 if losses[-1] < losses[0] or resumed_from else 1


def _maybe_quantize(params, plan, int8: bool, int4: bool = False):
    """Weight-only quantization for the serving CLIs: quantize ON device
    under the mesh so GSPMD propagates the weight shardings onto the
    quantized/scale pair (no hand-written spec tree for the quantized
    layout).  --int4 stacks on the int8 KV cache: weights stream grouped
    s4 (half of int8's bytes again), the cache stays int8."""
    if not (int8 or int4):
        return params
    import functools

    import jax

    from tputopo.workloads.quant import quantize_params

    fn = (functools.partial(quantize_params, bits=4) if int4
          else quantize_params)
    with plan.mesh:
        return jax.jit(fn)(params)


def cmd_decode(args) -> int:
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tputopo.workloads import sharding as shardlib
    from tputopo.workloads.decode import generate_jit
    from tputopo.workloads.model import ModelConfig, init_params
    from tputopo.workloads.sharding import mesh_for_slice

    cfg = ModelConfig(vocab_size=2048, d_model=256, n_layers=4, n_heads=8,
                      n_kv_heads=4, d_ff=512,
                      max_seq=args.prompt_len + args.max_new,
                      kv_dtype="int8" if args.int8 or args.int4 else "bf16")
    # Serving mesh: batch over dp, KV heads over tp (the cache's tp axis),
    # mirroring cmd_train — a multi-chip serving pod actually shards the
    # cache and weights (ADVICE r2; on one chip everything is a no-op).
    n = jax.device_count()
    plan = mesh_for_slice((n,), heads=cfg.n_kv_heads)
    dp = max(1, plan.axes["dp"])
    batch = max(dp, args.batch // dp * dp)
    params = init_params(cfg, jax.random.key(0))
    params = jax.device_put(params, shardlib.param_shardings(plan, cfg))
    params = _maybe_quantize(params, plan, args.int8, args.int4)
    prompt = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, args.prompt_len))
    prompt = jax.device_put(jnp.asarray(prompt), plan.sharding("dp", None))
    with shardlib.activate(plan):
        out = generate_jit(params, prompt, cfg, max_new=args.max_new)
        out.block_until_ready()  # compile
        t0 = time.perf_counter()
        out = generate_jit(params, prompt, cfg, max_new=args.max_new)
        out.block_until_ready()
        dt = time.perf_counter() - t0
    print(json.dumps({
        "batch": batch, "prompt_len": args.prompt_len,
        "max_new": args.max_new, "mesh": plan.axes,
        "decode_tokens_per_s": round(batch * args.max_new / dt, 1),
        "wall_s": round(dt, 4),
    }))
    return 0


def cmd_serve(args) -> int:
    """Continuous-batching serving demo: mixed-length prompts stream
    through a slotted engine (ragged prefill, EOS off, slot reuse)."""
    import time

    import jax
    import numpy as np

    from tputopo.workloads import sharding as shardlib
    from tputopo.workloads.model import ModelConfig, init_params
    from tputopo.workloads.serving import ServingEngine
    from tputopo.workloads.sharding import mesh_for_slice

    cfg = ModelConfig(vocab_size=2048, d_model=256, n_layers=4, n_heads=8,
                      n_kv_heads=4, d_ff=512,
                      max_seq=args.prompt_len + args.max_new,
                      kv_dtype="int8" if args.int8 or args.int4 else "bf16")
    # Flag validation BEFORE any device work (init/device_put/quantize).
    if args.spec_draft_layers:
        if not 0 < args.spec_draft_layers < cfg.n_layers:
            print(f"error: --spec-draft-layers must be in "
                  f"(0, {cfg.n_layers})", file=sys.stderr)
            return 2
        if args.spec_gamma < 1:
            print("error: --spec-gamma must be >= 1", file=sys.stderr)
            return 2
        incompatible = [f for f, v in (("--prefix-len", args.prefix_len),
                                       ("--prefill-chunk", args.prefill_chunk))
                        if v]
        if args.steps_per_tick != 8:  # non-default: would be silently ignored
            incompatible.append("--steps-per-tick")
        if incompatible:
            print(f"error: --spec-draft-layers is incompatible with "
                  f"{', '.join(incompatible)} (a speculative tick is one "
                  "verify stream; draft-cache mirroring for prefix/chunked "
                  "admission is future work)", file=sys.stderr)
            return 2
    n = jax.device_count()
    plan = mesh_for_slice((n,), heads=cfg.n_kv_heads)
    params = init_params(cfg, jax.random.key(0))
    params = jax.device_put(params, shardlib.param_shardings(plan, cfg))
    params = _maybe_quantize(params, plan, args.int8, args.int4)
    rng = np.random.default_rng(0)
    lens = rng.integers(max(1, args.prompt_len // 4), args.prompt_len + 1,
                        args.requests)
    max_len = args.prefix_len + args.prompt_len + args.max_new
    on_tokens = None
    if getattr(args, "stream", False):
        # JSONL stream ahead of the final summary line: one record per
        # engine tick per request with its newly committed tokens.
        def on_tokens(rid, toks):
            print(json.dumps({"rid": rid, "tokens": toks}), flush=True)
    with shardlib.activate(plan):
        if args.spec_draft_layers:
            from tputopo.workloads.speculative import SpecServingEngine

            eng = SpecServingEngine(params, cfg, slots=args.slots,
                                    max_len=max_len,
                                    prompt_pad=args.prompt_len,
                                    draft_layers=args.spec_draft_layers,
                                    gamma=args.spec_gamma,
                                    on_tokens=on_tokens)
        else:
            eng = ServingEngine(params, cfg, slots=args.slots,
                                max_len=max_len,
                                prompt_pad=args.prompt_len,
                                steps_per_tick=args.steps_per_tick,
                                prefill_chunk=args.prefill_chunk,
                                on_tokens=on_tokens)
        pid = None
        if args.prefix_len:
            # Shared system-prompt demo: its KV computes once, every
            # request below reuses it by copy.
            pid = eng.register_prefix(
                rng.integers(0, cfg.vocab_size, (args.prefix_len,)).tolist())
        ids = [eng.submit(rng.integers(0, cfg.vocab_size, (L,)).tolist(),
                          max_new=args.max_new, prefix=pid) for L in lens]
        t0 = time.perf_counter()
        results = eng.run()
        dt = time.perf_counter() - t0
    base = args.prefix_len + np.asarray(lens)
    generated = sum(len(results[i]) - int(b) for i, b in zip(ids, base))
    out = {
        "requests": args.requests, "slots": args.slots, "mesh": plan.axes,
        "prompt_lens": f"{lens.min()}..{lens.max()}",
        "prefix_len": args.prefix_len,
        "generated_tokens": int(generated),
        "decode_steps": eng.metrics["decode_steps"],
        "prefix_admits": eng.metrics["prefix_admits"],
        "tokens_per_s": round(generated / dt, 1),
        "wall_s": round(dt, 3),
    }
    if getattr(args, "stream", False):
        # The timed window includes the stream's host I/O: mark the
        # record so throughput is not compared across flag sets.
        out["stream"] = True
    if args.spec_draft_layers:
        out["drafted_accepted"] = eng.metrics["drafted_accepted"]
    print(json.dumps(out))
    return 0 if len(results) == args.requests else 1


def cmd_train_vision(args) -> int:
    import jax

    from tputopo.workloads.sharding import mesh_for_slice
    from tputopo.workloads.vision import VisionConfig, train_vision

    n = jax.device_count()
    plan = mesh_for_slice((n,), tp=1)  # pure data parallel, the Exp.6 shape
    batch = max(plan.axes["dp"], args.batch // plan.axes["dp"]
                * plan.axes["dp"])
    losses = train_vision(plan, VisionConfig(), steps=args.steps, batch=batch)
    print(json.dumps({
        "devices": n, "mesh": plan.axes, "steps": args.steps,
        "first_loss": round(losses[0], 4), "last_loss": round(losses[-1], 4),
    }))
    return 0 if losses[-1] < losses[0] else 1


def main() -> int:
    ap = argparse.ArgumentParser(prog="tputopo-workload")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("allreduce", help="measure vs predicted all-reduce")
    p.add_argument("--topology", help="slice spec, e.g. v5p:2x2x4 "
                                      "(default: injected env)")
    p.add_argument("--payload-mb", type=float, default=16.0)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--min-efficiency", type=float, default=0.0)
    p.set_defaults(fn=cmd_allreduce)

    p = sub.add_parser("train", help="sharded LM training steps")
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--tp", type=int, default=None,
                   help="tensor-parallel degree (default: policy)")
    p.add_argument("--sp", type=int, default=None,
                   help="sequence-parallel degree (context parallelism)")
    p.add_argument("--sp-impl", choices=("ring", "a2a"), default="ring",
                   help="context-parallel strategy: 'ring' rotates K/V "
                        "over ICI neighbors (max context length); 'a2a' "
                        "re-shards seq->heads with one all_to_all each "
                        "way (full-sequence flash locally; needs sp to "
                        "divide the per-tp-shard head counts)")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline stages (SPMD GPipe)")
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel degree (MoE; needs --experts)")
    p.add_argument("--experts", type=int, default=0,
                   help="MoE experts per layer (0 = dense FFN)")
    p.add_argument("--ckpt-dir", default=None,
                   help="orbax checkpoint dir: resume if present, save at end "
                        "(and every --save-every steps)")
    p.add_argument("--save-every", type=int, default=0)
    p.add_argument("--accum", type=int, default=1,
                   help="gradient-accumulation microbatches per optimizer "
                        "step: activation memory drops to one microbatch's "
                        "worth while the update sees the full-batch "
                        "gradient")
    p.add_argument("--data", default=None, metavar="TOKENS.bin",
                   help="train on a flat binary token-id corpus "
                        "(np.memmap'd; deterministic disjoint shards per "
                        "step/process, resumable) instead of the fixed "
                        "synthetic batch")
    p.add_argument("--data-dtype", default="uint16",
                   help="stored token dtype of --data (uint16 default)")
    p.add_argument("--lora-rank", type=int, default=0,
                   help="train only LoRA adapters of this rank on the "
                        "attention q/v projections (base frozen; adapter "
                        "checkpoints via --ckpt-dir)")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the steady-state "
                        "steps into DIR (open with XProf/TensorBoard; "
                        "step 0 is excluded as the compile step, so "
                        "--steps must be >= 2 for a trace to appear)")
    p.set_defaults(fn=cmd_train)

    p = sub.add_parser("decode", help="KV-cache greedy decode throughput")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--prompt-len", type=int, default=64)
    p.add_argument("--max-new", type=int, default=64)
    p.add_argument("--int8", action="store_true",
                   help="full int8 serving stack: weight-only int8 + int8 "
                        "KV cache (decode is HBM-bound; bytes are the lever)")
    p.add_argument("--int4", action="store_true",
                   help="grouped int4 weights (half of int8's stream "
                        "again) over the int8 KV cache")
    p.set_defaults(fn=cmd_decode)

    p = sub.add_parser("serve", help="continuous-batching serving engine "
                                     "(ragged prompts, slot reuse)")
    p.add_argument("--requests", type=int, default=16)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--prompt-len", type=int, default=64,
                   help="prefill bucket; prompts sample 1/4..1x of it")
    p.add_argument("--max-new", type=int, default=32)
    p.add_argument("--steps-per-tick", type=int, default=8)
    p.add_argument("--prefill-chunk", type=int, default=None,
                   help="chunked prefill: long prompts prefill this many "
                        "tokens per tick, interleaved with decode (bounds "
                        "head-of-line blocking); must divide --prompt-len")
    p.add_argument("--prefix-len", type=int, default=0,
                   help="shared system-prompt length: its KV computes once "
                        "(register_prefix) and every request reuses it")
    p.add_argument("--stream", action="store_true",
                   help="emit a JSONL token stream ({rid, tokens} per "
                        "engine tick) ahead of the final summary line; "
                        "the summary's tokens_per_s then includes the "
                        "stream's host I/O (it carries stream:true so "
                        "numbers are not compared across flag sets)")
    p.add_argument("--int8", action="store_true",
                   help="full int8 serving stack: weights + KV cache")
    p.add_argument("--int4", action="store_true",
                   help="grouped int4 weights (half of int8's stream "
                        "again) over the int8 KV cache")
    p.add_argument("--spec-draft-layers", type=int, default=0,
                   help="speculative continuous batching: draft with this "
                        "many leading layers, verify per tick (greedy; "
                        "lossless at f32 — at bf16/int8 a near-tie argmax "
                        "can flip within a ulp between the width-1 and "
                        "width-gamma+1 blocks; reports drafted_accepted)")
    p.add_argument("--spec-gamma", type=int, default=4,
                   help="draft tokens per speculative tick")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser("train-vision",
                       help="conv classifier, data parallel (Gaia Exp.6 analog)")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch", type=int, default=64)
    p.set_defaults(fn=cmd_train_vision)

    args = ap.parse_args()
    # Multi-host gangs rendezvous BEFORE the first jax backend touch so
    # jax.devices() spans the scheduled slice (no-op for single-process
    # jobs) — workloads/distributed.py documents the env contract the
    # gang Job template wires.
    from tputopo.workloads.distributed import initialize_from_env

    try:
        group = initialize_from_env()
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if not group.single:
        print(f"jax.distributed: rank {group.process_id}/"
              f"{group.num_processes} via {group.coordinator}",
              file=sys.stderr)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
