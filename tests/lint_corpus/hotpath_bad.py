# lint-corpus-relpath: tputopo/corpus/hotpath_bad.py
"""KNOWN-BAD hot-path-scan corpus: a registered root reaching a scan."""


class Engine:
    def __init__(self, api):
        self.api = api

    # hot-path-root: corpus event loop (one call per event)
    def run_events(self):
        while self.step():
            pass

    def step(self):
        return self.scan()

    def scan(self):
        # BAD: full-store read, two hops from the declared hot root
        return self.api.list_nocopy("pods")
