"""Priority tiers, admission ordering and targeted preemption.

The production extension the Gaia evaluation (PAPER.md §IV) lacks:
latency-sensitive serving pods coexist with long training gangs under an
explicit ``tpu.dev/priority`` tier model (tputopo.k8s.objects).  Three
rules, all riding existing substrate:

- **admission order** (:func:`admission_order`): pending high-tier gangs
  sort before lower tiers, FIFO within a tier;
- **targeted preemption** (:func:`plan_preemption`): a high-tier gang
  that cannot place may evict the cheapest strictly-lower-tier victim
  set — the defrag planner's mask-native cheapest-eviction search with a
  priority victim filter (gang atomicity, net-gain and budget rules all
  kept); evictions flow through the existing delete -> requeue ->
  recover path, so the chaos invariants keep holding;
- **backfill** (:func:`backfill_ok`): while a higher-tier job is blocked,
  only short trace-known-duration lower-tier jobs may jump it.
"""

from tputopo.priority.preempt import (plan_preemption,  # noqa: F401
                                      victim_priorities)
from tputopo.priority.tiers import (admission_key, admission_order,  # noqa: F401
                                    backfill_ok)
