"""Token-stream data loading for the training workloads.

The acceptance workloads train on synthetic tokens (memorization is the
convergence check); a real job trains on a tokenized corpus.  The TPU
shape of that problem: the input pipeline must never stall the MXU, and
every data-parallel rank must read a DISJOINT shard without coordination.
This loader keeps it correspondingly simple and fast:

- **One flat binary file of token ids** (the format GPT-2/nanoGPT-style
  preprocessors emit): ``np.memmap`` — no parsing, no copies, the OS page
  cache is the prefetcher.
- **Deterministic disjoint sharding**: sequence windows are a pure
  function of (epoch seed, step, rank), so ``dp_size`` ranks — or the
  per-process shards of a multi-host gang (``jax.process_index`` over
  the :mod:`tputopo.workloads.distributed` rendezvous) — draw disjoint
  batches with zero cross-host traffic and exact resumability from a
  checkpointed step.
- **Static shapes**: every batch is ``[batch, seq+0]`` int32, so the
  jitted train step never re-traces.

The reference's workload layer feeds MNIST through framework-native
loaders inside its containers (Gaia PDF §IV Exp.6); this is the analog
for the flagship LM (SURVEY §1 L5).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class TokenDataset:
    """A memory-mapped corpus of token ids.

    Args:
        path: flat binary file of token ids.
        dtype: stored integer dtype (``uint16`` for vocab < 65536, the
            common preprocessor choice; any int dtype works).
    """

    path: str
    dtype: str = "uint16"

    def __post_init__(self):
        # Mutable caches on a frozen dataclass: the memmap is opened once,
        # and one epoch's permutation stays resident (regenerating an
        # O(n_windows) shuffle per batch would be exactly the input-
        # pipeline stall this module exists to avoid).
        object.__setattr__(self, "_tokens", None)
        object.__setattr__(self, "_perm_cache", {})

    @property
    def tokens(self) -> np.memmap:
        if self._tokens is None:
            object.__setattr__(
                self, "_tokens",
                np.memmap(self.path, dtype=self.dtype, mode="r"))
        return self._tokens

    def _perm(self, n: int, seed: int, epoch: int) -> np.ndarray:
        key = (n, seed, epoch)
        if key not in self._perm_cache:
            self._perm_cache.clear()  # one epoch resident at a time
            # SeedSequence folds (seed, epoch) independently: the old
            # ``key=seed + epoch`` collided (seed=1, epoch=0) with
            # (seed=0, epoch=1), so nominally independent runs replayed
            # each other's epoch permutations shifted by one.
            self._perm_cache[key] = np.random.Generator(
                np.random.Philox(
                    seed=np.random.SeedSequence(entropy=(seed, epoch)))
            ).permutation(n)
        return self._perm_cache[key]

    def __len__(self) -> int:
        return len(self.tokens)

    def n_windows(self, seq: int) -> int:
        """Distinct non-overlapping ``seq``-token windows available."""
        return len(self) // seq

    def batch(self, step: int, batch: int, seq: int, *, rank: int = 0,
              world: int = 1, seed: int = 0) -> np.ndarray:
        """The ``[batch, seq]`` int32 batch for (step, rank).

        Windows are drawn from a per-epoch pseudorandom permutation of
        the non-overlapping window index space, striped
        ``world * batch`` wide per global step — rank r takes stripe
        slot r, so ranks are disjoint within a step BY CONSTRUCTION and
        the whole schedule replays from any checkpointed step.
        """
        if not 0 <= rank < world:
            raise ValueError(f"rank {rank} out of range for world {world}")
        n = self.n_windows(seq)
        need = world * batch
        if n < need:
            raise ValueError(
                f"corpus has {n} windows of {seq} tokens; need >= {need} "
                f"(world {world} x batch {batch})")
        steps_per_epoch = n // need
        epoch, estep = divmod(step, steps_per_epoch)
        # Deterministic per-epoch Philox permutation, cached — built once
        # per epoch, sliced per batch.
        order = self._perm(n, seed, epoch)
        base = estep * need + rank * batch
        idx = order[base:base + batch]
        toks = self.tokens
        out = np.empty((batch, seq), np.int32)
        for row, w in enumerate(idx):
            out[row] = toks[w * seq:(w + 1) * seq]
        return out

    def max_token(self, sample: int | None = None) -> int:
        """Max token id — the vocab gate before handing ids to an
        embedding table (JAX's out-of-bounds gather CLAMPS silently, so
        an unchecked corpus trains on wrong data, not a crash).  Scans
        the whole corpus by default in one chunked sequential pass; pass
        ``sample`` to bound the check to a prefix explicitly."""
        toks = self.tokens if sample is None else self.tokens[:sample]
        hi = 0
        for start in range(0, len(toks), 1 << 24):
            hi = max(hi, int(toks[start:start + (1 << 24)].max()))
        return hi


def write_tokens(path: str, ids, dtype: str = "uint16") -> None:
    """Write a token id sequence as the flat binary this loader reads
    (test fixtures and small corpora; real corpora come pre-tokenized)."""
    arr = np.asarray(ids)
    if arr.min() < 0 or arr.max() > np.iinfo(dtype).max:
        raise ValueError(
            f"token ids [{arr.min()}, {arr.max()}] do not fit {dtype}")
    arr.astype(dtype).tofile(path)


def steps_per_epoch(ds: TokenDataset, batch: int, seq: int,
                    world: int = 1) -> int:
    return max(1, ds.n_windows(seq) // (world * batch))


def batch_iterator(ds: TokenDataset, batch: int, seq: int, *,
                   start_step: int = 0, rank: int = 0, world: int = 1,
                   seed: int = 0):
    """Infinite iterator of ``[batch, seq]`` int32 arrays from
    ``start_step`` (resume by passing the checkpointed step)."""
    step = start_step
    while True:
        yield ds.batch(step, batch, seq, rank=rank, world=world, seed=seed)
        step += 1
