"""The ``counter-drift`` checker: increments round-trip with the registry.

Counter names are contracts three ways: ``/metrics`` exports them as
Prometheus series, the sim report's ``scheduler`` block filters them
through ``SCHEDULER_COUNTER_KEEP``, and the defrag block is pre-zeroed
from ``DefragController.COUNTER_KEYS``.  None of those could see a typo'd
increment (a fresh series forks silently) or a dead registration (the
name outlives its last increment site).  This rule closes the loop:

- every **literal** name incremented via ``Metrics.inc`` / ``inc_chaos``
  (and the plain ``inc(...)`` hook in ``count_retries``) must be
  registered in :data:`tputopo.obs.counters.COUNTERS`;
- **f-string** increments must carry a literal prefix matching a
  :data:`~tputopo.obs.counters.COUNTER_PREFIXES` family;
- defrag ``_count`` literals must be in ``DefragController.
  COUNTER_KEYS`` or :data:`~tputopo.obs.counters.DEFRAG_LAZY_COUNTERS`;
- **dead registrations** are findings too: every registry name, prefix
  family, lazy key, keep-list entry, and ``COUNTER_KEYS`` entry must
  still have at least one increment site, and ``SCHEDULER_COUNTER_KEEP``
  must be a subset of the registry.

Fully dynamic sinks (a bare variable — the engine's ``inc_chaos`` relay,
the ici policy's counter bridge) are conservatively skipped; they only
forward names that originate at literal sites elsewhere, which this rule
already covers.  All canonical vocabularies are read from their defining
modules' own ASTs — the checker holds no second copy of any name.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tputopo.lint.core import Checker, Finding, Module
from tputopo.lint.drift import _module_constants

#: Canonical vocabularies: (module, constant name) read from the AST.
REGISTRY_MODULE = "tputopo/obs/counters.py"
KEEP_MODULE = "tputopo/sim/report.py"
DEFRAG_MODULE = "tputopo/defrag/controller.py"

#: Attribute sink names whose first argument is a counter name.
_ATTR_SINKS = frozenset({"inc", "inc_chaos"})
_DEFRAG_SINK = "_count"
#: Bare-name sink: ``count_retries`` calls its injected ``inc(...)``.
_NAME_SINK = "inc"


def _literal_names(arg: ast.AST) -> list[str]:
    """Constant-string counter names an argument can evaluate to
    (IfExp / BoolOp branches included)."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return [arg.value]
    if isinstance(arg, ast.IfExp):
        return _literal_names(arg.body) + _literal_names(arg.orelse)
    if isinstance(arg, ast.BoolOp):
        out = []
        for v in arg.values:
            out.extend(_literal_names(v))
        return out
    return []


def _fstring_prefix(arg: ast.AST) -> str | None:
    if isinstance(arg, ast.JoinedStr) and arg.values \
            and isinstance(arg.values[0], ast.Constant) \
            and isinstance(arg.values[0].value, str):
        return arg.values[0].value
    return None


class CounterDriftChecker(Checker):
    rule = "counter-drift"
    description = ("counter names incremented via Metrics.inc/inc_chaos/"
                   "defrag _count must round-trip with the registry "
                   "(obs/counters.py), SCHEDULER_COUNTER_KEEP, and "
                   "DefragController.COUNTER_KEYS — unregistered "
                   "increments and dead registrations both flagged")

    def __init__(self) -> None:
        self._mods: list[Module] = []

    def applies_to(self, relpath: str) -> bool:
        # Package code only: tests increment ad-hoc fakes on purpose.
        return relpath.startswith("tputopo/")

    def check_module(self, mod: Module) -> Iterable[Finding]:
        self._mods.append(mod)
        return ()

    def finalize(self) -> Iterable[Finding]:
        mods, self._mods = self._mods, []
        by_path = {m.relpath: m for m in mods}
        reg_mod = by_path.get(REGISTRY_MODULE)
        if reg_mod is None:
            return  # partial run without the registry — nothing to check
        reg = _module_constants(reg_mod.tree,
                                ("COUNTERS", "COUNTER_PREFIXES",
                                 "DEFRAG_LAZY_COUNTERS"))
        counters = set(reg.get("COUNTERS", ()))
        prefixes = tuple(reg.get("COUNTER_PREFIXES", ()))
        lazy = set(reg.get("DEFRAG_LAZY_COUNTERS", ()))
        keep: set[str] = set()
        if (m := by_path.get(KEEP_MODULE)) is not None:
            keep = set(_module_constants(
                m.tree, ("SCHEDULER_COUNTER_KEEP",)).get(
                    "SCHEDULER_COUNTER_KEEP", ()))
        defrag_keys: set[str] = set()
        if (m := by_path.get(DEFRAG_MODULE)) is not None:
            defrag_keys = set(_module_constants(
                m.tree, ("COUNTER_KEYS",)).get("COUNTER_KEYS", ()))

        inc_names: set[str] = set()        # literal inc/inc_chaos names
        fstr_prefixes_seen: set[str] = set()
        defrag_names: set[str] = set()     # literal _count names
        findings: list[Finding] = []

        for mod in mods:
            if mod.relpath == REGISTRY_MODULE:
                continue
            for node in mod.nodes():
                if not (isinstance(node, ast.Call) and node.args):
                    continue
                sink = None
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _ATTR_SINKS | {_DEFRAG_SINK}:
                    sink = node.func.attr
                elif isinstance(node.func, ast.Name) \
                        and node.func.id == _NAME_SINK:
                    sink = _NAME_SINK
                if sink is None:
                    continue
                arg = node.args[0]
                names = _literal_names(arg)
                prefix = _fstring_prefix(arg)
                if sink == _DEFRAG_SINK:
                    for name in names:
                        defrag_names.add(name)
                        if name not in defrag_keys | lazy:
                            findings.append(Finding(
                                mod.relpath, node.lineno, node.col_offset,
                                self.rule,
                                f"defrag counter {name!r} is not in "
                                "DefragController.COUNTER_KEYS or "
                                "DEFRAG_LAZY_COUNTERS — register it or "
                                "fix the name"))
                    continue
                for name in names:
                    inc_names.add(name)
                    if name not in counters \
                            and not name.startswith(prefixes):
                        findings.append(Finding(
                            mod.relpath, node.lineno, node.col_offset,
                            self.rule,
                            f"counter {name!r} is not registered in "
                            f"{REGISTRY_MODULE} COUNTERS — register it "
                            "or fix the name"))
                if prefix is not None:
                    fstr_prefixes_seen.add(prefix)
                    if not prefix.startswith(prefixes):
                        findings.append(Finding(
                            mod.relpath, node.lineno, node.col_offset,
                            self.rule,
                            f"dynamic counter family {prefix!r}... has no "
                            f"registered prefix in {REGISTRY_MODULE} "
                            "COUNTER_PREFIXES"))
                # Anything else (a forwarding variable, an expression we
                # cannot see through) is conservatively skipped — such
                # relays only forward names that originate at literal
                # sites, which this rule already covers.

        yield from findings
        yield from self._dead_findings(
            reg_mod, by_path, counters, prefixes, lazy, keep, defrag_keys,
            inc_names, fstr_prefixes_seen, defrag_names)

    def _dead_findings(self, reg_mod, by_path, counters, prefixes, lazy,
                       keep, defrag_keys, inc_names, fstr_seen,
                       defrag_names) -> Iterable[Finding]:
        def const_line(mod: Module, const: str, member: str) -> int:
            """Line of ``member`` inside the ``const`` literal (falling
            back to the assignment line) — so a dead entry's finding
            points at the entry itself."""
            for node in mod.nodes():
                if isinstance(node, ast.Assign) \
                        and any(isinstance(t, ast.Name) and t.id == const
                                for t in node.targets):
                    for sub in ast.walk(node.value):
                        if isinstance(sub, ast.Constant) \
                                and sub.value == member:
                            return sub.lineno
                    return node.lineno
            return 1

        for name in sorted(counters - inc_names):
            yield Finding(
                reg_mod.relpath, const_line(reg_mod, "COUNTERS", name), 0,
                self.rule,
                f"dead registered counter {name!r}: no inc/inc_chaos "
                "site increments it — remove it or restore the "
                "increment")
        for prefix in sorted(set(prefixes)):
            if not any(seen.startswith(prefix) or prefix.startswith(seen)
                       for seen in fstr_seen):
                yield Finding(
                    reg_mod.relpath,
                    const_line(reg_mod, "COUNTER_PREFIXES", prefix), 0,
                    self.rule,
                    f"dead counter-family prefix {prefix!r}: no f-string "
                    "increment uses it")
        for name in sorted(lazy - defrag_names):
            yield Finding(
                reg_mod.relpath,
                const_line(reg_mod, "DEFRAG_LAZY_COUNTERS", name), 0,
                self.rule,
                f"dead lazy defrag counter {name!r}: no _count site "
                "increments it")
        keep_mod = by_path.get(KEEP_MODULE)
        if keep_mod is not None:
            for name in sorted(keep - inc_names):
                yield Finding(
                    keep_mod.relpath,
                    const_line(keep_mod, "SCHEDULER_COUNTER_KEEP", name),
                    0, self.rule,
                    f"SCHEDULER_COUNTER_KEEP entry {name!r} is never "
                    "incremented — the report would carry a dead key")
            for name in sorted(keep - counters):
                yield Finding(
                    keep_mod.relpath,
                    const_line(keep_mod, "SCHEDULER_COUNTER_KEEP", name),
                    0, self.rule,
                    f"SCHEDULER_COUNTER_KEEP entry {name!r} is not in "
                    f"the registry ({REGISTRY_MODULE})")
        defrag_mod = by_path.get(DEFRAG_MODULE)
        if defrag_mod is not None:
            for name in sorted(defrag_keys - defrag_names):
                yield Finding(
                    defrag_mod.relpath,
                    const_line(defrag_mod, "COUNTER_KEYS", name), 0,
                    self.rule,
                    f"DefragController.COUNTER_KEYS entry {name!r} is "
                    "never incremented — dead report key")
