"""tputopo.obs — scheduler flight recorder.

Phase-span tracing (:class:`Tracer` / :class:`Span`), per-decision
explain records, the no-op :class:`NullTracer` the hot path runs with
by default, and the bounded fleet-gauge timeline
(:class:`TimelineRecorder` / :class:`TimelineSampler`).  See
:mod:`tputopo.obs.tracer` and :mod:`tputopo.obs.timeline` for the
design notes.
"""

from tputopo.obs.timeline import (POINT_BUDGET, TimelineRecorder,
                                  TimelineSampler, bucket_at)
from tputopo.obs.tracer import (NULL_TRACER, NullTracer, Span, Trace,
                                Tracer)

__all__ = ["Tracer", "Span", "Trace", "NullTracer", "NULL_TRACER",
           "TimelineRecorder", "TimelineSampler", "POINT_BUDGET",
           "bucket_at"]
