"""Link taxonomy and cost model — the TPU analog of the reference's link
classes and affinity marks.

The reference orders NVLink/PCIe link classes SYS < NODE < PHB < PXB < PIX <
PSB < NV1-4 and assigns each an affinity mark 1-6 (design.md:31-47, 194-203),
leaving actual bandwidth weights as an unresolved TODO (design.md:47).  On
TPU the taxonomy collapses to three physically distinct classes:

=============  ======================================  =========================
TPU class      meaning                                 GPU-design analog
=============  ======================================  =========================
ICI_NEIGHBOR   direct ICI link (1 hop)                 NV1-4 (direct NVLink)
ICI_MESH       same ICI domain, >1 hop                 PIX/PXB/PHB (via switches)
DCN            different ICI domain (cross-slice /     SYS ("Cross CPU socket",
               cross-pod, data-center network)         design.md:33-36)
=============  ======================================  =========================

Unlike the reference's abstract 1-6 marks (and its inverted score formula —
see SURVEY.md §5 "Score-direction bug"), costs here are expressed directly
in physical units (GB/s per link, hop counts), so *higher score == better
placement* by construction and the TODO weight table becomes explicit,
overridable config (:mod:`tputopo.extender.config`).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

from tputopo.topology.model import ChipTopology, Coord


class LinkType(enum.IntEnum):
    """Pairwise chip-to-chip link classification, worst-to-best ordered
    (same ordering convention as the reference's mark table, design.md:196-203,
    but with the score direction fixed: bigger enum value == faster path)."""

    DCN = 1           # cross-ICI-domain, rides the data-center network
    ICI_MESH = 2      # same torus, multi-hop
    ICI_NEIGHBOR = 3  # direct ICI link

    def describe(self) -> str:
        return {
            LinkType.DCN: "Cross ICI domain (data-center network)",
            LinkType.ICI_MESH: "Same ICI torus, multi-hop",
            LinkType.ICI_NEIGHBOR: "Direct ICI link",
        }[self]


def classify_link(topo: ChipTopology, a: Coord, b: Coord) -> LinkType:
    """Classify the path between two chips of one topology.

    Chips in *different* topologies (different slices/pods) are always DCN;
    callers with multi-slice state handle that case themselves (see
    :func:`tputopo.topology.score.score_chip_set`).
    """
    if a == b:
        raise ValueError("a chip has no link to itself")
    return LinkType.ICI_NEIGHBOR if topo.hop_distance(a, b) == 1 else LinkType.ICI_MESH


@dataclass(frozen=True)
class LinkCostModel:
    """Bandwidth/latency figures the scorer consumes.

    Defaults derive from the generation spec; deployments override via config
    with measured numbers (closing the reference's design.md:47 TODO).

    Attributes:
        ici_link_gbps: one-way GB/s of a single ICI link.
        dcn_host_gbps: per-host DCN GB/s.
        host_dma_gbps: bandwidth between chips on the *same host* that are
            not ICI-connected within an allocation (traffic staged through
            host memory / PCIe — the analog of the reference's PHB class,
            design.md:38-40).  ICI-contiguous placements strictly dominate
            any split; among splits, single-host splits score this staging
            bandwidth while cross-host splits score their (narrowest) DCN
            attachment — a many-host split can legitimately aggregate
            enough NICs to out-score one host's PCIe, so the guaranteed
            ordering is contiguous > split, not a total order over splits.
        ici_hop_latency_us: per-hop ICI latency (tiebreak only; ICI is ~1us).
        dcn_latency_us: DCN round-trip latency.
        hbm_gbps: per-chip HBM stream bandwidth.  Not a *link* cost (the
            placement scorer never reads it) but part of the one
            calibratable weight table: workload heuristics (the decode
            serving ceiling, roofline accounting) consume it, and
            :func:`tputopo.workloads.validate.calibrate_cost_model` backs
            it out of a measured stream benchmark alongside the ICI
            figure — closing the reference's design.md:47 TODO for the
            memory axis too (VERDICT r3 #4).  0.0 == unset (direct
            constructions that never asked for a generation default).
    """

    ici_link_gbps: float
    dcn_host_gbps: float
    host_dma_gbps: float = 64.0  # PCIe Gen5 x16-class; must exceed dcn_host_gbps
    ici_hop_latency_us: float = 1.0
    dcn_latency_us: float = 25.0
    hbm_gbps: float = 0.0

    @staticmethod
    def for_generation(gen_name: str, **overrides) -> "LinkCostModel":
        from tputopo.topology.generations import get_generation

        g = get_generation(gen_name)
        return LinkCostModel(
            ici_link_gbps=float(overrides.pop("ici_link_gbps", g.ici_link_gbps)),
            dcn_host_gbps=float(overrides.pop("dcn_host_gbps", g.dcn_host_gbps)),
            hbm_gbps=float(overrides.pop("hbm_gbps", g.hbm_gbps)),
            **overrides,
        )

    def link_gbps(self, link: LinkType) -> float:
        """Point-to-point bandwidth for one link of the given class."""
        if link is LinkType.DCN:
            return self.dcn_host_gbps
        return self.ici_link_gbps
