"""ctypes bindings for libtputopo.so plus a pure-Python fallback probe.

Layering mirrors the reference (design.md:51-53: Go device plugin → cgo →
NVML C library): Python device plugin → ctypes → libtputopo C++ shim.  The
pure-Python fallback implements identical semantics so dev boxes without a
compiler still work; tests assert native and fallback agree bit-for-bit
(the SURVEY.md §4.2 "fake discovery backend" requirement).

Backend selection (both implementations):
- ``TPUTOPO_FAKE="<gen>:<AxBxC>[@worker]"`` -> fabricated topology (the
  CPU-emulated twin, BASELINE config 1).
- else the real TPU runtime environment (``TPU_ACCELERATOR_TYPE``,
  ``TPU_CHIPS_PER_HOST_BOUNDS``, ``TPU_HOST_BOUNDS``, ``TPU_WORKER_ID``)
  plus a /dev scan for accelerator device files.
"""

from __future__ import annotations

import ctypes
import json
import math
import os
import re
import subprocess
from dataclasses import dataclass
from pathlib import Path

from tputopo.topology.generations import GENERATIONS, get_generation
from tputopo.topology.model import ChipTopology

_NATIVE_DIR = Path(__file__).resolve().parent.parent / "native"
_SO_PATH = _NATIVE_DIR / "libtputopo.so"

# TPU_ACCELERATOR_TYPE prefix -> generation name (sync with tputopo.cc).
_TYPE_PREFIXES = [
    ("v5litepod", "v5e"),
    ("v5p", "v5p"),
    ("v5e", "v5e"),
    ("v6e", "v6e"),
    ("v4", "v4"),
]


@dataclass(frozen=True)
class HostProbe:
    """One host's discovered place in the slice — the analog of the
    reference's per-node ``gpuTopology`` matrix (design.md:61-74)."""

    backend: str
    generation: str
    slice_dims: tuple[int, ...]
    host_bounds: tuple[int, ...]
    worker_id: int
    host_coord: tuple[int, ...]
    chips: tuple[dict, ...]  # {"local_id": int, "coords": [..], "device_path": str?}
    error: str | None = None

    @property
    def ok(self) -> bool:
        return self.error is None

    def topology(self) -> ChipTopology:
        """The global slice topology this host belongs to."""
        return ChipTopology.build(self.generation, self.slice_dims)

    def local_chip_coords(self) -> list[tuple[int, ...]]:
        return [tuple(c["coords"]) for c in self.chips]


def ensure_native_built(force: bool = False) -> Path | None:
    """Build libtputopo.so if a toolchain is available; returns the path or
    None when no compiler exists (the pure-Python fallback then serves)."""
    if _SO_PATH.exists() and not force:
        return _SO_PATH
    try:
        subprocess.run(
            ["make", "-s", "libtputopo.so"],
            cwd=_NATIVE_DIR,
            check=True,
            capture_output=True,
        )
    except (OSError, subprocess.CalledProcessError):
        return None
    return _SO_PATH if _SO_PATH.exists() else None


_lib_cache: ctypes.CDLL | None = None


def _load_native() -> ctypes.CDLL | None:
    global _lib_cache
    if _lib_cache is not None:
        return _lib_cache
    if not _SO_PATH.exists():
        return None
    try:
        lib = ctypes.CDLL(str(_SO_PATH))
    except OSError:
        return None
    lib.tputopo_probe.restype = ctypes.c_int
    lib.tputopo_probe.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.tputopo_version.restype = ctypes.c_char_p
    _lib_cache = lib
    return lib


def _probe_native(lib: ctypes.CDLL) -> dict:
    cap = 1 << 16
    while True:
        buf = ctypes.create_string_buffer(cap)
        need = lib.tputopo_probe(buf, cap)
        if need < cap:
            return json.loads(buf.value.decode())
        cap = need + 1


# ---- pure-Python twin of the C++ probe --------------------------------------


def _parse_dims(s: str) -> tuple[int, ...] | None:
    if not re.fullmatch(r"\d+([x,X]\d+)*", s):
        return None
    return tuple(int(x) for x in re.split(r"[x,X]", s))


def _host_coord(worker_id: int, slice_dims, host_bounds) -> tuple[int, ...]:
    grid = [max(1, s // b) for s, b in zip(slice_dims, host_bounds)]
    out = [0] * len(grid)
    rem = worker_id
    for i in range(len(grid) - 1, -1, -1):
        out[i] = rem % grid[i]
        rem //= grid[i]
    return tuple(out)


def _chips_for_host(host_coord, host_bounds, device_paths) -> tuple[dict, ...]:
    per_host = math.prod(host_bounds)
    chips = []
    for idx in range(per_host):
        local = [0] * len(host_bounds)
        rem = idx
        for i in range(len(host_bounds) - 1, -1, -1):
            local[i] = rem % host_bounds[i]
            rem //= host_bounds[i]
        entry = {
            "local_id": idx,
            "coords": [h * b + l for h, b, l in zip(host_coord, host_bounds, local)],
        }
        if idx < len(device_paths):
            entry["device_path"] = device_paths[idx]
        chips.append(entry)
    return tuple(chips)


def _probe_python(env: dict[str, str] | None = None) -> dict:
    env = dict(os.environ if env is None else env)

    fake = env.get("TPUTOPO_FAKE", "")
    if fake:
        worker = 0
        body = fake
        if "@" in fake:
            body, _, wid = fake.partition("@")
            worker = int(wid) if (wid.isascii() and wid.isdigit()) else 0
        if ":" not in body:
            return {"backend": "fake",
                    "error": f"TPUTOPO_FAKE wants '<gen>:<AxBxC>[@worker]', got '{fake}'"}
        gen_name, _, dim_s = body.partition(":")
        if gen_name not in GENERATIONS:
            return {"backend": "fake",
                    "error": f"unknown generation '{gen_name}' in TPUTOPO_FAKE"}
        g = get_generation(gen_name)
        dims = _parse_dims(dim_s)
        if dims is None or len(dims) != g.ndims:
            return {"backend": "fake",
                    "error": f"bad dims for {gen_name} in TPUTOPO_FAKE (want {g.ndims}-D)"}
        host_bounds = tuple(min(b, d) for b, d in zip(g.host_bounds, dims))
        hc = _host_coord(worker, dims, host_bounds)
        paths = [f"/dev/accel{i}" for i in range(math.prod(host_bounds))]
        return {
            "backend": "fake",
            "generation": g.name,
            "ndims": g.ndims,
            "cores_per_chip": g.cores_per_chip,
            "slice_dims": list(dims),
            "host_bounds": list(host_bounds),
            "worker_id": worker,
            "host_coord": list(hc),
            "chips": list(_chips_for_host(hc, host_bounds, paths)),
        }

    accel_type = env.get("TPU_ACCELERATOR_TYPE", "")
    if not accel_type:
        return {"backend": "real",
                "error": "no TPU runtime detected: TPU_ACCELERATOR_TYPE unset "
                         "and TPUTOPO_FAKE not provided"}
    gen_name = None
    for prefix, name in sorted(_TYPE_PREFIXES, key=lambda p: -len(p[0])):
        if accel_type.startswith(prefix):
            gen_name = name
            break
    if gen_name is None:
        return {"backend": "real",
                "error": f"unrecognized TPU_ACCELERATOR_TYPE '{accel_type}'"}
    g = get_generation(gen_name)
    host_bounds = list(g.host_bounds)
    hb = _parse_dims(env.get("TPU_CHIPS_PER_HOST_BOUNDS", ""))
    if hb and len(hb) == g.ndims:
        host_bounds = list(hb)

    cores = 0
    if "-" in accel_type:
        try:
            cores = int(accel_type.rsplit("-", 1)[1])
        except ValueError:
            cores = 0
    chips = cores // g.cores_per_chip if g.cores_per_chip else cores

    slice_dims = [1] * g.ndims
    hosts = _parse_dims(env.get("TPU_HOST_BOUNDS", ""))
    if hosts and len(hosts) == g.ndims:
        slice_dims = [h * b for h, b in zip(hosts, host_bounds)]
    elif chips > 0:
        per_host = math.prod(host_bounds)
        if chips <= per_host:
            slice_dims = [1] * g.ndims
            slice_dims[0] = chips
        else:
            slice_dims = list(host_bounds)
            slice_dims[-1] *= chips // per_host

    wid_s = env.get("TPU_WORKER_ID", "") or env.get("CLOUD_TPU_TASK_ID", "")
    worker = int(wid_s) if (wid_s.isascii() and wid_s.isdigit()) else 0

    paths = sorted(
        f"/dev/{n}" for n in os.listdir("/dev")
        if n.startswith("accel") or n.startswith("vfio")
    ) if os.path.isdir("/dev") else []

    hc = _host_coord(worker, slice_dims, host_bounds)
    return {
        "backend": "real",
        "generation": g.name,
        "ndims": g.ndims,
        "cores_per_chip": g.cores_per_chip,
        "slice_dims": slice_dims,
        "host_bounds": host_bounds,
        "worker_id": worker,
        "host_coord": list(hc),
        "chips": list(_chips_for_host(hc, host_bounds, paths)),
    }


def _to_host_probe(d: dict) -> HostProbe:
    if "error" in d:
        return HostProbe(
            backend=d.get("backend", "?"), generation="", slice_dims=(),
            host_bounds=(), worker_id=0, host_coord=(), chips=(),
            error=d["error"],
        )
    return HostProbe(
        backend=d["backend"],
        generation=d["generation"],
        slice_dims=tuple(d["slice_dims"]),
        host_bounds=tuple(d["host_bounds"]),
        worker_id=d["worker_id"],
        host_coord=tuple(d["host_coord"]),
        chips=tuple(d["chips"]),
    )


def probe_host(prefer_native: bool = True, build: bool = False) -> HostProbe:
    """Probe this host's TPU topology.

    Uses the native shim when present (``build=True`` compiles it on demand),
    else the pure-Python twin.  Both honor ``TPUTOPO_FAKE``.
    """
    if build:
        ensure_native_built()
    if prefer_native:
        lib = _load_native()
        if lib is not None:
            return _to_host_probe(_probe_native(lib))
    return _to_host_probe(_probe_python())
