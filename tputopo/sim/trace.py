"""Synthetic workload traces for the cluster simulator.

A trace is the *input* half of the Gaia evidence base (PDF §IV: repeated
allocations against staged occupancy states), generalized to sustained
load: a time-ordered stream of gang arrivals (Poisson or bursty), each
with a slice shape drawn from the BASELINE request vocabulary (singles,
ICI pairs, host quads, multi-host gangs), a lognormal service duration,
plus node failure/repair events and a small fraction of "ghost" jobs that
bind but never confirm (the TTL-GC path).

Everything is a pure function of :class:`TraceConfig` via one Philox
stream — the same trace replays byte-identically for every policy in an
A/B run, and across processes (the sim determinism contract,
tests/test_sim.py).
"""

from __future__ import annotations

import math
from dataclasses import asdict, dataclass, field, replace

import numpy as np

from tputopo.topology.generations import get_generation


@dataclass(frozen=True)
class JobSpec:
    """One job: ``replicas`` pods of ``chips`` chips each (replicas > 1 is
    a gang; every pod lands on its own host)."""

    name: str
    arrival_s: float
    chips: int
    replicas: int
    duration_s: float
    multislice: bool = False  # gang may split across ICI domains
    ghost: bool = False       # binds but never confirms -> TTL GC reclaims
    # Priority tier (tputopo.priority): stamped onto the pods as
    # tpu.dev/priority when nonzero.  0 == the batch tier == the whole
    # pre-priority trace vocabulary, byte-for-byte.
    priority: int = 0
    # Queue-wait SLO, virtual seconds (0 = none): a scheduled job meets
    # its SLO when wait <= slo_wait_s — the per-tier attainment figure.
    slo_wait_s: float = 0.0
    # ---- checkpoint + elasticity declaration (tputopo.elastic) --------
    # checkpoint_period_s: the job writes a full checkpoint every this
    # many wall seconds of running; an eviction destroys only the work
    # since the last one (plus restore_cost_s on resume).  None == never
    # checkpoints == the whole run is lost on eviction — the pre-elastic
    # accounting, byte-for-byte, which pins all prior trace bytes.
    checkpoint_period_s: float | None = None
    restore_cost_s: float | None = None
    # Elastic width bounds: a gang with min_replicas >= 1 may shrink to
    # that width under pressure (freeing whole members instead of being
    # evicted) and grow back toward max_replicas on release events.
    # 0/0 (the default) == rigid — the entire pre-elastic vocabulary.
    min_replicas: int = 0
    max_replicas: int = 0

    @property
    def total_chips(self) -> int:
        return self.chips * self.replicas


@dataclass(frozen=True)
class TraceConfig:
    """Knobs of the synthetic trace.  ``job_mix`` weights the request
    vocabulary: ("single", "pair", "quad", "gang"); gang replica counts
    come from ``gang_sizes``."""

    seed: int = 0
    nodes: int = 64
    spec: str = "v5p:4x4x4"        # per-ICI-domain torus; nodes are split
                                   # into ceil(nodes / hosts_per_domain) domains
    arrivals: int = 500
    process: str = "poisson"       # "poisson" | "bursty"
    rate_per_s: float = 0.1        # mean arrival rate, jobs per virtual second
    burst_factor: float = 6.0      # bursty: high-phase rate multiplier
    burst_len_s: float = 120.0     # bursty: mean phase length (exp-distributed)
    job_mix: tuple[float, float, float, float] = (0.35, 0.2, 0.2, 0.25)
    gang_sizes: tuple[int, ...] = (2, 4, 8)
    p_multislice: float = 0.15     # fraction of gangs labeled allow-multislice
    # Mean offered load at the defaults: ~6.2 chips/job x 0.1 jobs/s x
    # 300 s / 256 chips (--nodes 64 of v5p:4x4x4 = 4 x 64-chip domains)
    # ~= 0.73 of capacity — busy enough for queueing and fragmentation to
    # matter, below the collapse regime where backlog drain would drown
    # the placement-quality signal.
    duration_mean_s: float = 300.0
    duration_sigma: float = 0.8    # lognormal shape
    ghost_prob: float = 0.02       # jobs that never confirm (GC exercise)
    node_failures: int = 2         # fail events spread over the arrival window
    repair_mean_s: float = 900.0   # exp-distributed time-to-repair
    # ---- mixed serving+training workload (tputopo.priority) ------------
    # "standard" keeps the original single-tenant batch vocabulary (and
    # its exact report bytes — the knobs below are dropped from
    # describe() at the defaults).  "mixed" interleaves latency-sensitive
    # serving work (serving tier, tight queue-wait SLO, diurnal/bursty
    # arrivals) with long training gangs (prod/batch tiers, Poisson).
    workload: str = "standard"
    # ---- fleet-scale knob ------------------------------------------------
    # Offered load as a fraction of fleet capacity (None = use rate_per_s
    # as given).  The default rate (0.1 jobs/s) was tuned for the 64-node
    # standard fleet; replayed at 1024 nodes it offers ~4% of capacity —
    # an idle-cluster benchmark.  offered_load derives the arrival rate
    # from the fleet itself (rate = load * total_chips / (mean_job_chips
    # * duration_mean_s)), so one load figure scales from the 64-node
    # standing trace to the 1024-node fleet trace without retuning.
    # Standard workload only; a pure function of the config, so traces
    # stay byte-deterministic.  Dropped from describe() when None —
    # every pre-existing report's bytes are pinned.
    offered_load: float | None = None
    serving_frac: float = 0.6      # fraction of arrivals that are serving
    serving_gang_frac: float = 0.3  # of serving: multi-host model replicas
    serving_duration_mean_s: float = 120.0
    # Serving queue-wait SLO (virtual s): a *provisioning* SLO — how long
    # a serving pod may pend before holding chips — not request latency.
    # One minute is tight against training gangs whose mean duration is
    # ~10x that, yet long enough that misses measure real contention,
    # not same-instant placement jitter.
    slo_wait_s: float = 60.0
    diurnal_period_s: float = 1200.0  # serving arrival-rate cycle
    diurnal_amp: float = 0.6          # peak-to-mean modulation (0..1)
    train_duration_factor: float = 2.0  # training mean = factor x duration_mean_s
    prod_train_frac: float = 0.25  # training jobs at the prod (50) tier
    # ---- checkpointed workload (tputopo.elastic) -----------------------
    # "checkpointed" is the mixed stream with checkpoint/elasticity
    # declarations stamped onto the training gangs (serving stays rigid
    # and un-checkpointed): ckpt_frac of training jobs checkpoint every
    # ~ckpt_period_mean_s with a ~ckpt_restore_mean_s restore bill, and
    # elastic_frac of THOSE are resizable down to half width.  The knobs
    # are dropped from describe() on other workloads so every prior
    # report's bytes stay pinned.
    ckpt_frac: float = 0.8
    ckpt_period_mean_s: float = 120.0
    ckpt_restore_mean_s: float = 15.0
    elastic_frac: float = 0.5

    def __post_init__(self) -> None:
        if self.offered_load is not None:
            if self.workload != "standard":
                raise ValueError(
                    "offered_load derives its rate from the standard "
                    "job-mix vocabulary; tune the mixed workload via "
                    "rate_per_s")
            if not 0.0 < self.offered_load:
                raise ValueError(f"offered_load must be > 0, "
                                 f"got {self.offered_load}")
            rate = (self.offered_load * self.total_chips
                    / (self.mean_job_chips * self.duration_mean_s))
            object.__setattr__(self, "rate_per_s", rate)

    def rng(self) -> np.random.Generator:
        # SeedSequence folds the seed on its own axis (the same collision
        # lesson as workloads/data.py's epoch permutation).
        return np.random.Generator(
            np.random.Philox(seed=np.random.SeedSequence(
                entropy=(0x7097090, self.seed))))

    # ---- cluster geometry --------------------------------------------------

    @property
    def generation(self) -> str:
        return self.spec.split(":", 1)[0]

    @property
    def domain_dims(self) -> tuple[int, ...]:
        return tuple(int(x) for x in self.spec.split(":", 1)[1].split("x"))

    @property
    def hosts_per_domain(self) -> int:
        gen = get_generation(self.generation)
        hb = tuple(min(b, d) for b, d in zip(gen.host_bounds, self.domain_dims))
        return math.prod(self.domain_dims) // math.prod(hb)

    @property
    def chips_per_host(self) -> int:
        return math.prod(self.domain_dims) // self.hosts_per_domain

    @property
    def n_domains(self) -> int:
        return max(1, math.ceil(self.nodes / self.hosts_per_domain))

    @property
    def total_chips(self) -> int:
        return self.n_domains * math.prod(self.domain_dims)

    @property
    def mean_job_chips(self) -> float:
        """Expected chips per job under the standard request vocabulary
        (the job_mix weights over single / pair / host-quad / gang) —
        the offered-load denominator, computed from the same knobs the
        generator draws from so the two can never drift."""
        w = [x / sum(self.job_mix) for x in self.job_mix]
        cph = self.chips_per_host
        gang = cph * (sum(self.gang_sizes) / len(self.gang_sizes))
        return w[0] * 1 + w[1] * min(2, cph) + w[2] * cph + w[3] * gang

    #: The mixed-workload knobs, dropped from describe() on a standard
    #: trace so every pre-priority report stays byte-identical (same rule
    #: as the engine's defrag/chaos records: absent when off).
    _MIXED_KNOBS = ("workload", "serving_frac", "serving_gang_frac",
                    "serving_duration_mean_s", "slo_wait_s",
                    "diurnal_period_s", "diurnal_amp",
                    "train_duration_factor", "prod_train_frac")

    #: The checkpointed-workload knobs, present in describe() only when
    #: workload == "checkpointed" (same absent-when-off rule).
    _CKPT_KNOBS = ("ckpt_frac", "ckpt_period_mean_s",
                   "ckpt_restore_mean_s", "elastic_frac")

    def describe(self) -> dict:
        d = asdict(self)
        if self.workload == "standard":
            for k in self._MIXED_KNOBS:
                d.pop(k, None)
        if self.workload != "checkpointed":
            for k in self._CKPT_KNOBS:
                d.pop(k, None)
        if self.offered_load is None:
            # Absent when unset (same rule as the mixed knobs): every
            # pre-fleet report's bytes stay pinned.  When set, both the
            # load figure and the derived rate_per_s are recorded.
            d.pop("offered_load", None)
        d.update(n_domains=self.n_domains, hosts_per_domain=self.hosts_per_domain,
                 chips=self.total_chips)
        return d


@dataclass(frozen=True)
class Trace:
    config: TraceConfig
    jobs: tuple[JobSpec, ...]
    # (fail_s, repair_s, node_index) — node_index over the staged node list.
    node_events: tuple[tuple[float, float, int], ...] = field(default=())


def _arrival_times(cfg: TraceConfig, rng: np.random.Generator) -> np.ndarray:
    if cfg.process == "poisson":
        gaps = rng.exponential(1.0 / cfg.rate_per_s, cfg.arrivals)
        return np.cumsum(gaps)
    if cfg.process == "bursty":
        # Two-phase Markov-modulated Poisson: burst phases arrive at
        # burst_factor * rate, quiet phases at rate / burst_factor, and
        # burst phases last 1/burst_factor as long as quiet ones — which
        # makes the time-averaged rate exactly rate_per_s for any factor
        # ((f*r * L/f + r/f * L) / (L/f + L) = r), so a bursty-vs-poisson
        # A/B measures burstiness, not a hidden load change.
        f = max(1.0, cfg.burst_factor)
        times: list[float] = []
        t, hot = 0.0, False
        phase_end = rng.exponential(cfg.burst_len_s)
        while len(times) < cfg.arrivals:
            rate = cfg.rate_per_s * (f if hot else 1.0 / f)
            nxt = t + rng.exponential(1.0 / rate)
            if nxt < phase_end:
                t = nxt
                times.append(t)
            else:
                # Exponential gaps are memoryless: truncate at the phase
                # boundary and redraw at the new phase's rate.  (Letting a
                # long quiet-rate gap jump whole burst phases would censor
                # exactly the arrivals burstiness exists to model.)
                t = phase_end
                hot = not hot
                phase_end = t + rng.exponential(
                    cfg.burst_len_s / f if hot else cfg.burst_len_s)
        return np.asarray(times)
    raise ValueError(f"unknown arrival process {cfg.process!r} "
                     "(want 'poisson' or 'bursty')")


def _diurnal_times(cfg: TraceConfig, rng: np.random.Generator,
                   n: int, base_rate: float) -> list[float]:
    """``n`` arrival times from a non-homogeneous Poisson process whose
    rate swings sinusoidally around ``base_rate`` (period
    ``diurnal_period_s``, amplitude ``diurnal_amp``) — the serving
    traffic shape.  Standard thinning: candidates at the peak rate, each
    accepted with probability rate(t)/peak; one rng, fixed draw order,
    so the stream is deterministic per config."""
    amp = min(max(cfg.diurnal_amp, 0.0), 1.0)
    peak = base_rate * (1.0 + amp)
    times: list[float] = []
    t = 0.0
    while len(times) < n:
        t += float(rng.exponential(1.0 / peak))
        rate = base_rate * (1.0 + amp * math.sin(
            2.0 * math.pi * t / cfg.diurnal_period_s))
        if float(rng.random()) * peak <= rate:
            times.append(t)
    return times


def _generate_mixed(cfg: TraceConfig, rng: np.random.Generator) -> list[JobSpec]:
    """The ``mixed`` serving+training job stream (tputopo.priority).

    Serving work (``serving_frac`` of arrivals, diurnal arrival rate,
    short lognormal durations, tier ``serving`` with the ``slo_wait_s``
    queue-wait SLO): mostly single small-k inference pods, plus
    ``serving_gang_frac`` multi-host model-replica gangs.  Training work
    (the rest, Poisson, ``train_duration_factor`` x longer durations):
    the standard gang vocabulary at the ``prod``/``batch`` tiers —
    ``prod_train_frac`` of them prod, so tier strictness (prod may evict
    batch, nothing evicts serving) is exercised, not just asserted.
    Job names are merged-arrival-order indexed, exactly like the
    standard stream."""
    from tputopo.k8s.objects import PRIORITY_TIERS

    n = cfg.arrivals
    n_serv = int(round(n * min(max(cfg.serving_frac, 0.0), 1.0)))
    n_train = n - n_serv
    cph = cfg.chips_per_host
    serv_rate = cfg.rate_per_s * (n_serv / n) if n else cfg.rate_per_s
    train_rate = cfg.rate_per_s * (n_train / n) if n else cfg.rate_per_s

    # Draw order is FIXED (serving block, then training block): the
    # determinism contract is per (seed, config), same as _arrival_times.
    serv_times = _diurnal_times(cfg, rng, n_serv, max(serv_rate, 1e-9))
    serv_gang = rng.random(n_serv) < cfg.serving_gang_frac
    serv_small_k = rng.choice([1, min(2, cph)], size=max(n_serv, 1),
                              p=[0.7, 0.3])
    serv_gang_reps = rng.choice([2, 4], size=max(n_serv, 1))
    serv_dur = rng.lognormal(math.log(cfg.serving_duration_mean_s), 0.6,
                             max(n_serv, 1))

    train_gaps = rng.exponential(1.0 / max(train_rate, 1e-9),
                                 max(n_train, 1))
    train_times = np.cumsum(train_gaps)[:n_train]
    train_reps = rng.choice(list(cfg.gang_sizes), size=max(n_train, 1))
    train_dur = rng.lognormal(
        math.log(cfg.duration_mean_s * cfg.train_duration_factor),
        cfg.duration_sigma, max(n_train, 1))
    train_prod = rng.random(max(n_train, 1)) < cfg.prod_train_frac
    train_multi = rng.random(max(n_train, 1)) < cfg.p_multislice
    train_ghost = rng.random(max(n_train, 1)) < cfg.ghost_prob

    serving_tier = PRIORITY_TIERS["serving"]
    prod_tier = PRIORITY_TIERS["prod"]
    arrivals: list[tuple[float, int, int]] = []  # (t, stream, idx)
    arrivals += [(t, 0, i) for i, t in enumerate(serv_times)]
    arrivals += [(float(t), 1, i) for i, t in enumerate(train_times)]
    arrivals.sort()

    jobs: list[JobSpec] = []
    for j, (t, stream, i) in enumerate(arrivals):
        if stream == 0:  # serving
            if serv_gang[i]:
                chips, replicas = cph, int(serv_gang_reps[i])
            else:
                chips, replicas = int(serv_small_k[i]), 1
            jobs.append(JobSpec(
                name=f"job-{j:05d}", arrival_s=round(float(t), 6),
                chips=chips, replicas=replicas,
                duration_s=round(float(serv_dur[i]), 6),
                priority=serving_tier, slo_wait_s=cfg.slo_wait_s))
        else:  # training gang
            jobs.append(JobSpec(
                name=f"job-{j:05d}", arrival_s=round(float(t), 6),
                chips=cph, replicas=int(train_reps[i]),
                duration_s=round(float(train_dur[i]), 6),
                multislice=bool(train_multi[i]), ghost=bool(train_ghost[i]),
                priority=prod_tier if train_prod[i] else 0))
    return jobs


def _decorate_checkpointed(cfg: TraceConfig, rng: np.random.Generator,
                           jobs: list[JobSpec]) -> list[JobSpec]:
    """Stamp checkpoint/elasticity declarations onto the mixed stream's
    training gangs (the ``checkpointed`` workload).  Serving jobs stay
    rigid and un-checkpointed — a latency tier neither checkpoints nor
    shrinks.  Draw order is fixed (one block of four arrays AFTER the
    mixed draws), so the stream stays byte-deterministic per config."""
    from tputopo.k8s.objects import PRIORITY_TIERS

    n = max(len(jobs), 1)
    ckpt = rng.random(n) < min(max(cfg.ckpt_frac, 0.0), 1.0)
    periods = rng.lognormal(math.log(max(cfg.ckpt_period_mean_s, 1e-9)),
                            0.5, n)
    restores = rng.lognormal(math.log(max(cfg.ckpt_restore_mean_s, 1e-9)),
                             0.5, n)
    elastic = rng.random(n) < min(max(cfg.elastic_frac, 0.0), 1.0)
    serving_tier = PRIORITY_TIERS["serving"]
    out: list[JobSpec] = []
    for i, job in enumerate(jobs):
        if job.priority == serving_tier or not ckpt[i]:
            out.append(job)
            continue
        kw: dict = {
            "checkpoint_period_s": round(float(periods[i]), 6),
            "restore_cost_s": round(float(restores[i]), 6),
        }
        if elastic[i] and job.replicas > 1:
            kw["min_replicas"] = max(1, job.replicas // 2)
            kw["max_replicas"] = job.replicas
        out.append(replace(job, **kw))
    return out


def generate_trace(cfg: TraceConfig) -> Trace:
    """The deterministic trace for ``cfg`` — one Philox stream, consumed in
    a fixed order, so equal configs give byte-equal traces."""
    rng = cfg.rng()
    if cfg.workload in ("mixed", "checkpointed"):
        jobs_mixed = _generate_mixed(cfg, rng)
        if cfg.workload == "checkpointed":
            jobs_mixed = _decorate_checkpointed(cfg, rng, jobs_mixed)
        horizon = jobs_mixed[-1].arrival_s if jobs_mixed else 0.0
        return Trace(config=cfg, jobs=tuple(jobs_mixed),
                     node_events=tuple(_node_events(cfg, rng, horizon)))
    if cfg.workload != "standard":
        raise ValueError(f"unknown workload {cfg.workload!r} "
                         "(want 'standard', 'mixed' or 'checkpointed')")
    times = _arrival_times(cfg, rng)
    kinds = rng.choice(4, size=cfg.arrivals,
                       p=np.asarray(cfg.job_mix) / sum(cfg.job_mix))
    durations = rng.lognormal(math.log(cfg.duration_mean_s),
                              cfg.duration_sigma, cfg.arrivals)
    gang_sizes = rng.choice(list(cfg.gang_sizes), size=cfg.arrivals)
    multi = rng.random(cfg.arrivals) < cfg.p_multislice
    ghosts = rng.random(cfg.arrivals) < cfg.ghost_prob

    cph = cfg.chips_per_host
    jobs = []
    for i in range(cfg.arrivals):
        kind = int(kinds[i])
        if kind == 0:
            chips, replicas = 1, 1
        elif kind == 1:
            chips, replicas = min(2, cph), 1
        elif kind == 2:
            chips, replicas = cph, 1
        else:
            chips, replicas = cph, int(gang_sizes[i])
        jobs.append(JobSpec(
            name=f"job-{i:05d}",
            arrival_s=round(float(times[i]), 6),
            chips=chips,
            replicas=replicas,
            duration_s=round(float(durations[i]), 6),
            multislice=bool(kind == 3 and multi[i]),
            ghost=bool(ghosts[i]),
        ))

    horizon = float(times[-1]) if cfg.arrivals else 0.0
    return Trace(config=cfg, jobs=tuple(jobs),
                 node_events=tuple(_node_events(cfg, rng, horizon)))


def _node_events(cfg: TraceConfig, rng: np.random.Generator,
                 horizon: float) -> list[tuple[float, float, int]]:
    """Fail/repair events over the arrival window — the shared tail of
    both workload generators (same draw order as the original standard
    path, so standard traces stay byte-identical)."""
    node_events: list[tuple[float, float, int]] = []
    if cfg.node_failures > 0 and cfg.nodes > 1:
        fail_ts = np.sort(rng.uniform(0.0, max(horizon, 1.0),
                                      cfg.node_failures))
        victims = rng.integers(0, cfg.nodes, cfg.node_failures)
        repairs = rng.exponential(cfg.repair_mean_s, cfg.node_failures)
        for ft, victim, rep in zip(fail_ts, victims, repairs):
            node_events.append((round(float(ft), 6),
                                round(float(ft + rep), 6), int(victim)))
    return node_events
