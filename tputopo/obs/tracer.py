"""Flight recorder: phase-span tracing and decision explain records.

The scheduler's verbs (``sort``/``bind``) are multi-stage pipelines —
state build/fold, generation gate, score loop, gang composition search,
CAS patch, delta publish.  This module answers the *per-decision*
questions about them: a :class:`Tracer` records, per verb invocation, a
tree of timed phase spans with deterministic counters plus an optional
**explain record** (the per-node score breakdown and structured
rejection reasons the verbs attach), into a bounded ring buffer served
by ``/debug/traces``.  It is one of three observability layers in
:mod:`tputopo.obs`: flat counters and p50/p95 gauges
(:mod:`tputopo.obs.counters` names the registry), these traces, and the
bounded fleet-gauge timeline (:mod:`tputopo.obs.timeline`) that records
the *trajectory* — utilization, fragmentation, queue depth over time —
which spans and counters cannot reconstruct after the fact.

Two design constraints shape the API:

- **The disabled path is branch-cheap.**  The default scheduler tracer
  is the :data:`NULL_TRACER` singleton; its spans are one shared no-op
  object, so a hot loop pays attribute lookups and no-op calls only —
  no dict, no list, no clock read.  Explain assembly is additionally
  gated on ``span.enabled`` so the disabled path never allocates.
- **Wall clock is telemetry, never truth.**  Span durations come from a
  wall clock (``perf_counter``); everything else a trace carries — its
  timestamp, phase counts, span counters, the explain record — comes
  from the caller's (possibly *virtual*) clock and deterministic control
  flow.  That split is what lets the simulator run with tracing on and
  still pin explain records and phase counts byte-for-byte across runs,
  quarantining wall-ms in the report's documented non-deterministic
  blocks (``throughput`` / ``phase_wall``).
"""

from __future__ import annotations

import threading
import time
from collections import deque


class Span:
    """One timed phase of a verb.  Use as a context manager; nest via
    :meth:`child`.  ``counters`` hold deterministic integers (items
    scored, memo hits) — never wall-clock values."""

    __slots__ = ("tracer", "name", "wall_ms", "counters", "children", "_t0")

    enabled = True

    def __init__(self, tracer: "Tracer", name: str) -> None:
        self.tracer = tracer
        self.name = name
        self.wall_ms = 0.0
        self.counters: dict[str, int] = {}
        self.children: list[Span] = []
        self._t0 = 0.0

    def child(self, name: str) -> "Span":
        s = Span(self.tracer, name)
        self.children.append(s)
        return s

    # Alias: a verb's direct children are its phases.
    phase = child

    def count(self, name: str, by: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + by

    def __enter__(self) -> "Span":
        self._t0 = self.tracer.wall()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.wall_ms = (self.tracer.wall() - self._t0) * 1e3
        return False

    def to_dict(self) -> dict:
        d: dict = {"name": self.name, "wall_ms": round(self.wall_ms, 3)}
        if self.counters:
            d["counters"] = dict(self.counters)
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d


class Trace(Span):
    """A verb invocation's root span.  Exiting the context records the
    finished trace into the tracer's ring buffer (including on error —
    a failed bind's trace carries the failure reason)."""

    __slots__ = ("verb", "attrs", "t", "explain_record", "error")

    def __init__(self, tracer: "Tracer", verb: str, attrs: dict) -> None:
        super().__init__(tracer, verb)
        self.verb = verb
        self.attrs = attrs
        self.t = tracer.clock()  # caller clock: virtual in the sim
        self.explain_record: dict | None = None
        self.error: str | None = None

    def explain(self, record: dict) -> None:
        self.explain_record = record

    def fail(self, reason: str) -> None:
        self.error = reason

    def __exit__(self, exc_type, exc, tb) -> bool:
        super().__exit__(exc_type, exc, tb)
        if exc_type is not None and self.error is None:
            self.error = f"{exc_type.__name__}: {exc}"
        self.tracer.record(self)
        return False  # never swallow the verb's exception

    def to_dict(self) -> dict:
        d = {"verb": self.verb, "t": round(self.t, 6),
             "wall_ms": round(self.wall_ms, 3)}
        if self.attrs:
            d.update(self.attrs)
        if self.counters:
            d["counters"] = dict(self.counters)
        d["phases"] = [c.to_dict() for c in self.children]
        if self.explain_record is not None:
            d["explain"] = self.explain_record
        if self.error is not None:
            d["error"] = self.error
        return d


class Tracer:
    """Records verb traces into a bounded ring buffer and aggregates
    per-phase totals (deterministic counts; wall-ms kept separately).

    ``clock`` stamps trace timestamps — inject the sim's virtual clock
    for deterministic explain records; ``wall`` times span durations
    (telemetry).  Thread-safe: the extender's HTTP server runs verbs
    concurrently, so recording and reading take an internal lock."""

    enabled = True

    def __init__(self, capacity: int = 256, clock=time.time,
                 wall=time.perf_counter) -> None:
        self.clock = clock
        self.wall = wall
        self._buf: deque[dict] = deque(maxlen=max(1, capacity))
        self._lock = threading.Lock()
        self.recorded = 0  # total traces ever recorded (gauge-able)
        # Aggregates keyed "verb" / "verb/phase" / "verb/phase/child":
        # counts + summed span counters are deterministic (the sim report's
        # ``phases`` block); wall-ms is telemetry (the ``phase_wall`` block).
        self.phase_counts: dict[str, int] = {}
        self.phase_counters: dict[str, dict[str, int]] = {}
        self.phase_wall_ms: dict[str, float] = {}
        self.last: dict | None = None  # most recent trace (as a dict)

    def start(self, verb: str, **attrs) -> Trace:
        return Trace(self, verb, attrs)

    def record(self, trace: Trace) -> None:
        d = trace.to_dict()
        with self._lock:
            self._buf.append(d)
            self.last = d
            self.recorded += 1
            self._aggregate(trace.verb, trace)
            for child in trace.children:
                self._aggregate_tree(trace.verb, child)

    def _aggregate(self, key: str, span: Span) -> None:
        self.phase_counts[key] = self.phase_counts.get(key, 0) + 1
        self.phase_wall_ms[key] = (self.phase_wall_ms.get(key, 0.0)
                                   + span.wall_ms)
        if span.counters:
            agg = self.phase_counters.setdefault(key, {})
            for name, v in span.counters.items():
                agg[name] = agg.get(name, 0) + v

    def _aggregate_tree(self, prefix: str, span: Span) -> None:
        key = f"{prefix}/{span.name}"
        self._aggregate(key, span)
        for child in span.children:
            self._aggregate_tree(key, child)

    def traces(self, n: int = 20) -> list[dict]:
        """The ``n`` most recent traces, oldest first (n <= 0: none —
        NOT the whole buffer, which ``buf[-0:]`` would mean)."""
        if n <= 0:
            return []
        with self._lock:
            buf = list(self._buf)
        return buf[-n:]

    @property
    def last_explain(self) -> dict | None:
        last = self.last
        return last.get("explain") if last is not None else None

    def phases_snapshot(self) -> dict:
        """Deterministic per-phase aggregate: ``{key: {"count": n,
        "counters": {...}}}`` — the sim report's ``phases`` block."""
        with self._lock:
            out = {}
            for key in sorted(self.phase_counts):
                entry: dict = {"count": self.phase_counts[key]}
                counters = self.phase_counters.get(key)
                if counters:
                    entry["counters"] = dict(sorted(counters.items()))
                out[key] = entry
            return out

    def phase_wall_snapshot(self) -> dict:
        """Wall-ms per phase key (telemetry; excluded from determinism)."""
        with self._lock:
            return {k: round(v, 3)
                    for k, v in sorted(self.phase_wall_ms.items())}


class _NullSpan:
    """Shared no-op span: every method returns self or does nothing, so
    the disabled hot path costs attribute lookups only."""

    __slots__ = ()

    enabled = False

    def child(self, name: str) -> "_NullSpan":
        return self

    phase = child

    def count(self, name: str, by: int = 1) -> None:
        pass

    def explain(self, record: dict) -> None:
        pass

    def fail(self, reason: str) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class NullTracer:
    """Tracing disabled: ``start`` hands back one shared no-op span and
    nothing is ever recorded.  Read surface matches :class:`Tracer` so
    consumers (the /debug endpoint, the sim report) need no branches."""

    enabled = False
    recorded = 0
    last = None
    last_explain = None

    def start(self, verb: str, **attrs) -> _NullSpan:
        return _NULL_SPAN

    def traces(self, n: int = 20) -> list[dict]:
        return []

    def phases_snapshot(self) -> dict:
        return {}

    def phase_wall_snapshot(self) -> dict:
        return {}


#: Shared disabled tracer — the default for every scheduler not
#: explicitly wired for tracing.
NULL_TRACER = NullTracer()
