"""Extender tests: cluster-state rebuild, sort/bind verbs, gang
all-or-nothing, stale-assumption GC — driving the same flows as the
reference's §3.2/§3.3 call stacks against staged fixtures."""

import pytest

from tests.cluster import build_cluster
from tputopo.extender import AssumptionGC, ClusterState, ExtenderConfig, ExtenderScheduler
from tputopo.extender.scheduler import (
    BindError,
    LABEL_GANG_ID,
    LABEL_GANG_SIZE,
    MAX_PRIORITY,
)
from tputopo.k8s import make_pod
from tputopo.k8s import objects as ko


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def make_scheduler(api, clock=None, **cfg):
    config = ExtenderConfig(**cfg)
    return ExtenderScheduler(api, config, clock=clock or Clock())


def all_nodes(api):
    return [n["metadata"]["name"] for n in api.list("nodes")]


# ---- cluster state ----------------------------------------------------------

def test_state_rebuild_from_annotations():
    api, _ = build_cluster()
    state = ClusterState(api).sync()
    assert set(state.domains) == {"slice-a"}
    dom = state.domains["slice-a"]
    assert dom.topology.num_chips == 16
    assert len(dom.node_by_host) == 4
    assert len(dom.allocator.free) == 16
    assert state.free_chips_on_node("node-2") == [(0, 0, 2), (0, 1, 2), (1, 0, 2), (1, 1, 2)]


def test_state_counts_confirmed_and_fresh_assumptions():
    clock = Clock(1000.0)
    api, _ = build_cluster(clock=clock)
    api.create("pods", make_pod("p1", chips=2, node_name="node-0", annotations={
        ko.ANN_GROUP: "0,0,0;0,1,0", ko.ANN_ASSUME_TIME: "990", ko.ANN_ASSIGNED: "false"}))
    api.create("pods", make_pod("p2", chips=1, node_name="node-1", annotations={
        ko.ANN_GROUP: "0,0,1", ko.ANN_ASSUME_TIME: "1", ko.ANN_ASSIGNED: "true"}))
    api.create("pods", make_pod("p3", chips=1, node_name="node-1", annotations={
        ko.ANN_GROUP: "0,1,1", ko.ANN_ASSUME_TIME: "1", ko.ANN_ASSIGNED: "false"}))
    state = ClusterState(api, assume_ttl_s=60, clock=clock).sync()
    dom = state.domains["slice-a"]
    # p1 fresh assumption + p2 confirmed occupy; p3's expired does not.
    assert len(dom.allocator.used) == 3
    assert [pa.pod_name for pa in state.expired] == ["p3"]


def test_state_rejects_topology_disagreement():
    api, _ = build_cluster()
    api.patch_annotations("nodes", "node-3", {ko.ANN_TOPOLOGY: "v5p:2x2x2:wrap=000"})
    with pytest.raises(ValueError, match="disagree"):
        ClusterState(api).sync()


# ---- sort -------------------------------------------------------------------

def test_sort_scores_all_nodes_equal_when_empty():
    api, _ = build_cluster()
    sched = make_scheduler(api)
    pod = make_pod("p", chips=2)
    api.create("pods", pod)
    scores = sched.sort(pod, all_nodes(api))
    assert len(scores) == 4
    assert len({s["Score"] for s in scores}) == 1
    assert scores[0]["Score"] == MAX_PRIORITY  # adjacent pair == ideal for k=2


def test_sort_prefers_tight_node_for_single_chip():
    api, _ = build_cluster()
    # node-1 has 3 chips taken -> its last chip is the tight spot.
    api.create("pods", make_pod("busy", chips=3, node_name="node-1", annotations={
        ko.ANN_GROUP: "0,0,1;0,1,1;1,0,1", ko.ANN_ASSUME_TIME: "999",
        ko.ANN_ASSIGNED: "true"}))
    sched = make_scheduler(api)
    pod = make_pod("p", chips=1)
    scores = {s["Host"]: s["Score"] for s in sched.sort(pod, all_nodes(api))}
    assert scores["node-1"] > scores["node-0"]
    assert scores["node-1"] > scores["node-2"]


def test_sort_zero_when_infeasible():
    api, _ = build_cluster()
    sched = make_scheduler(api)
    pod = make_pod("p", chips=5)  # > 4 chips/host, no gang
    scores = sched.sort(pod, all_nodes(api))
    assert all(s["Score"] == 0 for s in scores)
    nochip = make_pod("p0", chips=0)
    assert all(s["Score"] == 0 for s in sched.sort(nochip, all_nodes(api)))


def test_sort_unknown_node_scores_zero():
    api, _ = build_cluster()
    sched = make_scheduler(api)
    pod = make_pod("p", chips=1)
    scores = {s["Host"]: s["Score"] for s in sched.sort(pod, ["node-0", "ghost"])}
    assert scores["ghost"] == 0
    assert scores["node-0"] > 0


# ---- bind -------------------------------------------------------------------

def test_bind_full_handshake():
    clock = Clock(2000.0)
    api, _ = build_cluster()
    sched = make_scheduler(api, clock=clock)
    api.create("pods", make_pod("train", chips=4))
    decision = sched.bind("train", "default", "node-2")
    assert decision["contiguous"] is True
    assert decision["predicted_allreduce_gbps"] == 400.0
    pod = api.get("pods", "train", "default")
    anns = pod["metadata"]["annotations"]
    assert anns[ko.ANN_GROUP] == "0,0,2;0,1,2;1,0,2;1,1,2"
    assert anns[ko.ANN_ASSIGNED] == "false"
    assert anns[ko.ANN_ASSUME_TIME] == "2000.0"
    assert float(anns[ko.ANN_PREDICTED_GBPS]) == 400.0
    assert pod["spec"]["nodeName"] == "node-2"


def test_bind_respects_existing_occupancy():
    api, _ = build_cluster()
    sched = make_scheduler(api)
    api.create("pods", make_pod("first", chips=3))
    sched.bind("first", "default", "node-0")
    api.create("pods", make_pod("second", chips=2))
    with pytest.raises(BindError, match="no feasible"):
        sched.bind("second", "default", "node-0")  # only 1 chip left there
    sched.bind("second", "default", "node-1")  # fine elsewhere


def test_bind_errors_are_counted():
    api, _ = build_cluster()
    sched = make_scheduler(api)
    with pytest.raises(BindError, match="not found"):
        sched.bind("ghost", "default", "node-0")
    api.create("pods", make_pod("p", chips=1))
    with pytest.raises(BindError, match="not part of any TPU slice"):
        sched.bind("p", "default", "ghost-node")
    assert sched.metrics.counters["bind_errors"] == 2


# ---- gang scheduling --------------------------------------------------------

def gang_pod(name, gang_id, size, chips):
    return make_pod(name, chips=chips, labels={
        LABEL_GANG_ID: gang_id, LABEL_GANG_SIZE: str(size)})


def test_gang_4x4_binds_all_members_contiguously():
    # BASELINE config 4: 4 x 4-chip DP replicas on v5p-32.
    clock = Clock(1000.0)
    api, _ = build_cluster(clock=clock)
    sched = make_scheduler(api, clock=clock)
    for i in range(4):
        api.create("pods", gang_pod(f"dp-{i}", "job-a", 4, 4))
    bound_nodes = []
    for i in range(4):
        pod = api.get("pods", f"dp-{i}", "default")
        scores = sched.sort(pod, all_nodes(api))
        best = max(scores, key=lambda s: s["Score"])
        assert best["Score"] > 0
        decision = sched.bind(f"dp-{i}", "default", best["Host"])
        bound_nodes.append(best["Host"])
        assert decision["gang"] == "job-a"
        assert decision["contiguous"]
    assert sorted(bound_nodes) == ["node-0", "node-1", "node-2", "node-3"]
    # All 16 chips assigned, disjoint.
    state = ClusterState(api, clock=clock).sync()
    assert len(state.domains["slice-a"].allocator.used) == 16


def test_gang_8chip_2x2x2_slice():
    # BASELINE config 3: an 8-chip 2x2x2 slice == gang of 2 hosts on v5p.
    clock = Clock(1000.0)
    api, _ = build_cluster(clock=clock)
    sched = make_scheduler(api, clock=clock)
    for i in range(2):
        api.create("pods", gang_pod(f"bench-{i}", "bench", 2, 4))
    for i in range(2):
        pod = api.get("pods", f"bench-{i}", "default")
        scores = sched.sort(pod, all_nodes(api))
        best = max(scores, key=lambda s: s["Score"])
        sched.bind(f"bench-{i}", "default", best["Host"])
    state = ClusterState(api, clock=clock).sync()
    used = state.domains["slice-a"].allocator.used
    assert len(used) == 8
    # The union must be a contiguous 2x2x2 box (adjacent hosts chosen).
    from tputopo.topology.score import score_chip_set
    dom = state.domains["slice-a"]
    score = score_chip_set(dom.topology, used, dom.allocator.cost)
    assert score == pytest.approx(
        sum([200.0, 200.0, 200.0]), rel=1e-6)  # 2x2x2: three wrapless axes of 2


def test_gang_all_or_nothing_binds_nothing_when_infeasible():
    api, _ = build_cluster()
    sched = make_scheduler(api)
    # Occupy one full host: only 3 hosts left for a 4-host gang.
    api.create("pods", make_pod("squatter", chips=4, node_name="node-0",
               annotations={ko.ANN_GROUP: "0,0,0;0,1,0;1,0,0;1,1,0",
                            ko.ANN_ASSUME_TIME: "999", ko.ANN_ASSIGNED: "true"}))
    for i in range(4):
        api.create("pods", gang_pod(f"dp-{i}", "job-b", 4, 4))
    pod = api.get("pods", "dp-0", "default")
    scores = sched.sort(pod, all_nodes(api))
    assert all(s["Score"] == 0 for s in scores)
    with pytest.raises(BindError, match="all-or-nothing"):
        sched.bind("dp-0", "default", "node-1")
    # Nothing got annotated.
    for i in range(4):
        anns = api.get("pods", f"dp-{i}", "default")["metadata"]["annotations"]
        assert ko.ANN_GROUP not in anns


def test_gang_plan_carries_across_bind_sequence():
    """An N-member gang plans ONCE: every later sort/bind revalidates and
    reuses the carried plan instead of re-searching (VERDICT r2 #5 — the
    per-state memo alone never hit across binds, which re-sync state)."""
    clock = Clock(1000.0)
    api, _ = build_cluster(clock=clock)
    sched = make_scheduler(api, clock=clock)
    for i in range(4):
        api.create("pods", gang_pod(f"dp-{i}", "job-a", 4, 4))
    for i in range(4):
        pod = api.get("pods", f"dp-{i}", "default")
        scores = sched.sort(pod, all_nodes(api))
        best = max(scores, key=lambda s: s["Score"])
        sched.bind(f"dp-{i}", "default", best["Host"])
    # Bind-heavy trace: all four binds and the last three sorts reuse.
    assert sched.metrics.counters.get("gang_plan_reuse_hits", 0) >= 4
    state = ClusterState(api, clock=clock).sync()
    assert len(state.domains["slice-a"].allocator.used) == 16


def test_gang_plan_reuse_invalidated_when_chips_taken():
    """A carried plan whose chips got taken by someone else must NOT be
    reused — the validation replans instead of double-booking."""
    clock = Clock(1000.0)
    api, _ = build_cluster(clock=clock)
    sched = make_scheduler(api, clock=clock)
    for i in range(2):
        api.create("pods", gang_pod(f"g-{i}", "job-c", 2, 2))
    pod = api.get("pods", "g-0", "default")
    sched.sort(pod, all_nodes(api))  # plans and caches
    planned = set(sched._gang_plan_cache[("default", "job-c")]["plan"])
    # A rival pod confirms onto ALL chips of one planned node.
    victim = sorted(planned)[0]
    state = ClusterState(api, clock=clock).sync()
    chips = state.domains["slice-a"].chips_by_node[victim]
    api.create("pods", make_pod("rival", chips=4, node_name=victim,
               annotations={ko.ANN_GROUP: ";".join(",".join(map(str, c))
                                                   for c in chips),
                            ko.ANN_ASSUME_TIME: "1000",
                            ko.ANN_ASSIGNED: "true"}))
    hits_before = sched.metrics.counters.get("gang_plan_reuse_hits", 0)
    decision = sched.bind("g-0", "default", sorted(
        n for n in all_nodes(api) if n != victim)[0])
    assert sched.metrics.counters.get("gang_plan_reuse_hits", 0) == hits_before
    assert decision["node"] != victim
    # And the replanned gang completes on the remaining hosts.
    nxt = [n for n in all_nodes(api)
           if n not in (victim, decision["node"])][0]
    sched.bind("g-1", "default", nxt)


def test_infeasible_gang_releases_members_immediately():
    """All-or-nothing with prompt cleanup (VERDICT r2 #5): when a gang bind
    turns infeasible mid-sequence, the already-bound unconfirmed members'
    assumptions are cleared by the failing bind itself — chips come free
    within that very call, no 60 s TTL GC wait."""
    clock = Clock(1000.0)
    api, _ = build_cluster(clock=clock)
    sched = make_scheduler(api, clock=clock)
    for i in range(3):
        api.create("pods", gang_pod(f"m-{i}", "job-d", 3, 4))
    bound = []
    for i in range(2):
        pod = api.get("pods", f"m-{i}", "default")
        best = max(sched.sort(pod, all_nodes(api)), key=lambda s: s["Score"])
        sched.bind(f"m-{i}", "default", best["Host"])
        bound.append(best["Host"])
    # Squatters confirm onto every remaining host -> member 3 cannot fit.
    state = ClusterState(api, clock=clock).sync()
    for n in all_nodes(api):
        if n in bound:
            continue
        chips = state.domains["slice-a"].chips_by_node[n]
        api.create("pods", make_pod(f"squat-{n}", chips=4, node_name=n,
                   annotations={ko.ANN_GROUP: ";".join(
                       ",".join(map(str, c)) for c in chips),
                       ko.ANN_ASSUME_TIME: "1000", ko.ANN_ASSIGNED: "true"}))
    with pytest.raises(BindError, match="released 2 unconfirmed"):
        sched.bind("m-2", "default", bound[0])
    # Released IMMEDIATELY (clock never advanced past any TTL):
    for i in range(2):
        anns = api.get("pods", f"m-{i}", "default")["metadata"]["annotations"]
        assert ko.ANN_GROUP not in anns and ko.ANN_ASSIGNED not in anns
    state = ClusterState(api, clock=clock).sync()
    # Only the squatters' chips remain used — the gang's 8 came back.
    assert len(state.domains["slice-a"].allocator.used) == 8
    assert sched.metrics.counters["gang_assumptions_released"] == 2


def test_gang_size_label_required():
    api, _ = build_cluster()
    sched = make_scheduler(api)
    bad = make_pod("p", chips=4, labels={LABEL_GANG_ID: "g"})
    api.create("pods", bad)
    with pytest.raises(ValueError, match="gang-size"):
        sched.sort(bad, all_nodes(api))


# ---- GC ---------------------------------------------------------------------

def test_gc_releases_expired_assumption_and_frees_chips():
    clock = Clock(1000.0)
    api, _ = build_cluster(clock=clock)
    sched = make_scheduler(api, clock=clock)
    api.create("pods", make_pod("stuck", chips=4))
    sched.bind("stuck", "default", "node-0")
    # Occupied while fresh:
    assert len(ClusterState(api, clock=clock).sync().domains["slice-a"].allocator.used) == 4
    clock.t += 120  # beyond the 60 s TTL, never confirmed
    gc = AssumptionGC(api, assume_ttl_s=60, clock=clock)
    released = gc.sweep()
    assert released == ["default/stuck"]
    anns = api.get("pods", "stuck", "default")["metadata"]["annotations"]
    assert ko.ANN_GROUP not in anns and ko.ANN_ASSIGNED not in anns
    assert len(ClusterState(api, clock=clock).sync().domains["slice-a"].allocator.used) == 0


def test_gc_releases_whole_gang_together():
    clock = Clock(1000.0)
    api, _ = build_cluster(clock=clock)
    sched = make_scheduler(api, clock=clock)
    for i in range(4):
        api.create("pods", gang_pod(f"dp-{i}", "job-c", 4, 4))
    # Two members bind, then the job stalls (members 2,3 never arrive).
    sched.bind("dp-0", "default", "node-0")
    sched.bind("dp-1", "default", "node-1")
    clock.t += 120
    released = AssumptionGC(api, assume_ttl_s=60, clock=clock).sweep()
    assert sorted(released) == ["default/dp-0", "default/dp-1"]


def test_gc_keeps_confirmed_assignments():
    clock = Clock(1000.0)
    api, plugins = build_cluster(clock=clock)
    sched = make_scheduler(api, clock=clock)
    api.create("pods", make_pod("ok", chips=2))
    sched.bind("ok", "default", "node-1")
    # Device plugin confirms (flow ⑥): Allocate flips ASSIGNED.
    plugins["node-1"].kubelet.allocate(ko.RESOURCE_CHIPS, ["0,0,1", "0,1,1"])
    clock.t += 9999
    assert AssumptionGC(api, assume_ttl_s=60, clock=clock).sweep() == []
    assert len(ClusterState(api, clock=clock).sync().domains["slice-a"].allocator.used) == 2


# ---- config -----------------------------------------------------------------

def test_config_roundtrip_and_policy(tmp_path):
    cfg = ExtenderConfig(assume_ttl_s=30, cost_overrides={"v5p": {"ici_link_gbps": 95.0}})
    path = tmp_path / "cfg.json"
    cfg.save(path)
    loaded = ExtenderConfig.load(path)
    assert loaded == cfg
    assert loaded.cost_model("v5p").ici_link_gbps == 95.0
    assert loaded.cost_model("v5e").ici_link_gbps == 50.0  # defaults intact
    policy = cfg.policy_json()
    ext = policy["extenders"][0]
    assert ext["prioritizeVerb"] == "sort" and ext["bindVerb"] == "bind"
    assert "filterVerb" not in ext  # deliberately no Filter (design.md:115-117)
    assert ext["ignorable"] is False
    with pytest.raises(ValueError, match="unknown config keys"):
        path2 = tmp_path / "bad.json"
        path2.write_text('{"bogus": 1}')
        ExtenderConfig.load(path2)


# ---- code-review regressions: overlap tolerance & namespace-scoped gangs ----

def test_state_tolerates_overlapping_chip_groups():
    """Two pods claiming the same chips must not wedge sync(): first claimant
    keeps them, the second lands in state.conflicts, and every verb (and the
    GC, which also syncs) stays serviceable."""
    clock = Clock(1000.0)
    api, _ = build_cluster(clock=clock)
    # Older assignment wins the chips (sync processes in assume-time order).
    for name, t in (("first", "980"), ("dupe", "990")):
        api.create("pods", make_pod(name, chips=2, node_name="node-0", annotations={
            ko.ANN_GROUP: "0,0,0;0,1,0", ko.ANN_ASSUME_TIME: t,
            ko.ANN_ASSIGNED: "true"}))
    state = ClusterState(api, clock=clock).sync()
    dom = state.domains["slice-a"]
    assert len(dom.allocator.used) == 2
    assert [pa.pod_name for pa in state.conflicts] == ["dupe"]
    report = state.fragmentation_report()["slice-a"]
    assert report["conflicting_assignments"] == ["default/dupe"]
    # Verbs still work on the poisoned cluster.
    sched = make_scheduler(api, clock=clock)
    api.create("pods", make_pod("next", chips=1))
    scores = sched.sort(api.get("pods", "next", "default"), all_nodes(api))
    assert any(s["Score"] > 0 for s in scores)


def test_state_tolerates_out_of_slice_chips():
    clock = Clock(1000.0)
    api, _ = build_cluster(clock=clock)
    api.create("pods", make_pod("bogus", chips=1, node_name="node-0", annotations={
        ko.ANN_GROUP: "9,9,9", ko.ANN_ASSUME_TIME: "990", ko.ANN_ASSIGNED: "true"}))
    state = ClusterState(api, clock=clock).sync()
    assert [pa.pod_name for pa in state.conflicts] == ["bogus"]
    assert len(state.domains["slice-a"].allocator.used) == 0


def test_gangs_are_namespace_scoped():
    """Same gang id in two namespaces = two independent gangs (a fully bound
    gang 'train' in ns A must not block ns B's gang 'train')."""
    clock = Clock(1000.0)
    api, _ = build_cluster(clock=clock)
    sched = make_scheduler(api, clock=clock)
    for i in range(2):
        api.create("pods", gang_pod(f"a-{i}", "train", 2, 4))
    for i in range(2):
        pod = api.get("pods", f"a-{i}", "default")
        scores = sched.sort(pod, all_nodes(api))
        best = max(scores, key=lambda s: (s["Score"], s["Host"]))
        assert best["Score"] > 0
        sched.bind(f"a-{i}", "default", best["Host"])
    # Namespace team-b reuses the gang id; it must schedule independently.
    for i in range(2):
        p = gang_pod(f"b-{i}", "train", 2, 4)
        p["metadata"]["namespace"] = "team-b"
        api.create("pods", p)
    for i in range(2):
        pod = api.get("pods", f"b-{i}", "team-b")
        scores = sched.sort(pod, all_nodes(api))
        best = max(scores, key=lambda s: (s["Score"], s["Host"]))
        assert best["Score"] > 0, f"ns-b gang blocked by ns-a: {scores}"
        sched.bind(f"b-{i}", "team-b", best["Host"])
    state = ClusterState(api, clock=clock).sync()
    assert len(state.domains["slice-a"].allocator.used) == 16


def test_state_tolerates_malformed_assume_time():
    """A hand-written bad assume-time must not crash sync — it reads as 0
    (long expired) and the pod's assumption simply doesn't count."""
    clock = Clock(1000.0)
    api, _ = build_cluster(clock=clock)
    api.create("pods", make_pod("badtime", chips=1, node_name="node-0", annotations={
        ko.ANN_GROUP: "0,0,0", ko.ANN_ASSUME_TIME: "not-a-number",
        ko.ANN_ASSIGNED: "false"}))
    # Also a pod with a bad time and NO group/node: must not break the sort.
    api.create("pods", make_pod("unbound", chips=1, annotations={
        ko.ANN_ASSUME_TIME: "garbage"}))
    state = ClusterState(api, clock=clock).sync()
    assert len(state.domains["slice-a"].allocator.used) == 0
    assert [pa.pod_name for pa in state.expired] == ["badtime"]


def test_state_nonfinite_assume_time_reads_as_expired():
    """'nan'/'inf' assume-times must not occupy chips forever: they parse
    as 0 (long expired) so the GC can release them."""
    clock = Clock(1000.0)
    api, _ = build_cluster(clock=clock)
    for name, t in (("nanpod", "nan"), ("infpod", "inf")):
        api.create("pods", make_pod(name, chips=1, node_name="node-0", annotations={
            ko.ANN_GROUP: "0,0,0" if name == "nanpod" else "0,1,0",
            ko.ANN_ASSUME_TIME: t, ko.ANN_ASSIGNED: "false"}))
    state = ClusterState(api, clock=clock).sync()
    assert len(state.domains["slice-a"].allocator.used) == 0
    assert sorted(pa.pod_name for pa in state.expired) == ["infpod", "nanpod"]
    gc = AssumptionGC(api, assume_ttl_s=60, clock=clock)
    assert sorted(gc.sweep()) == ["default/infpod", "default/nanpod"]


def test_generation_quota_pinning():
    """Gaia heterogeneous-quota analog: a pod pinning tpu.dev/generation
    must only score/bind on nodes of that generation (mixed v5p + v5e
    cluster)."""
    clock = Clock(1000.0)
    api, _ = build_cluster(spec="v5p:2x2x4", workers=4, slice_id="slice-p",
                           clock=clock)
    api, _ = build_cluster(spec="v5e:4x4", workers=2, slice_id="slice-e",
                           api=api, clock=clock, node_prefix="enode")
    sched = make_scheduler(api, clock=clock)

    api.create("pods", make_pod("pinned", chips=2,
                                labels={ko.ANN_GENERATION_LABEL: "v5e"}))
    pod = api.get("pods", "pinned", "default")
    scores = {s["Host"]: s["Score"] for s in sched.sort(pod, all_nodes(api))}
    assert all(scores[n] == 0 for n in scores if n.startswith("node-"))
    assert any(scores[n] > 0 for n in scores if n.startswith("enode-"))

    with pytest.raises(BindError, match="quota classing"):
        sched.bind("pinned", "default", "node-0")
    decision = sched.bind("pinned", "default", "enode-0")
    assert decision["slice"] == "slice-e"

    # Unpinned pods still use both pools.
    api.create("pods", make_pod("free", chips=2))
    free_scores = {s["Host"]: s["Score"]
                   for s in sched.sort(api.get("pods", "free", "default"),
                                       all_nodes(api))}
    assert any(free_scores[n] > 0 for n in free_scores if n.startswith("node-"))


def test_gang_generation_pinning():
    clock = Clock(1000.0)
    api, _ = build_cluster(spec="v5p:2x2x4", workers=4, slice_id="slice-p",
                           clock=clock)
    api, _ = build_cluster(spec="v5e:4x4", workers=2, slice_id="slice-e",
                           api=api, clock=clock, node_prefix="enode")
    sched = make_scheduler(api, clock=clock)
    for i in range(2):
        p = gang_pod(f"g-{i}", "pinned-gang", 2, 4)
        p["metadata"]["labels"][ko.ANN_GENERATION_LABEL] = "v5e"
        api.create("pods", p)
    pod = api.get("pods", "g-0", "default")
    scores = {s["Host"]: s["Score"] for s in sched.sort(pod, all_nodes(api))}
    assert all(scores[n] == 0 for n in scores if n.startswith("node-"))
    assert any(scores[n] > 0 for n in scores if n.startswith("enode-"))


# ---- multislice gangs -------------------------------------------------------

def two_slice_cluster(clock):
    """Two v5p 2x2x2 domains (2 hosts each = 8 chips per slice)."""
    api, _ = build_cluster(spec="v5p:2x2x2", workers=2, slice_id="slice-a",
                           clock=clock)
    api, _ = build_cluster(spec="v5p:2x2x2", workers=2, slice_id="slice-b",
                           api=api, clock=clock, node_prefix="bnode")
    return api


def test_gang_without_multislice_label_refuses_split():
    """A 4-replica gang needing 4 hosts cannot fit either 2-host domain;
    without the opt-in it must not schedule at all (all-or-nothing)."""
    clock = Clock(1000.0)
    api = two_slice_cluster(clock)
    sched = make_scheduler(api, clock=clock)
    for i in range(4):
        api.create("pods", gang_pod(f"g-{i}", "big", 4, 4))
    pod = api.get("pods", "g-0", "default")
    scores = sched.sort(pod, all_nodes(api))
    assert all(s["Score"] == 0 for s in scores)
    with pytest.raises(BindError, match="cannot fit"):
        sched.bind("g-0", "default", "node-0")


def test_gang_multislice_opt_in_splits_across_domains():
    """With tpu.dev/allow-multislice=true the same gang splits 2+2 across
    the two slices, each sub-gang contiguous within its domain."""
    clock = Clock(1000.0)
    api = two_slice_cluster(clock)
    sched = make_scheduler(api, clock=clock)
    for i in range(4):
        p = gang_pod(f"m-{i}", "big", 4, 4)
        p["metadata"]["labels"]["tpu.dev/allow-multislice"] = "true"
        api.create("pods", p)
    decisions = []
    for i in range(4):
        pod = api.get("pods", f"m-{i}", "default")
        scores = sched.sort(pod, all_nodes(api))
        best = max(scores, key=lambda s: (s["Score"], s["Host"]))
        assert best["Score"] > 0, scores
        decisions.append(sched.bind(f"m-{i}", "default", best["Host"]))
    slices = {d["slice"] for d in decisions}
    assert slices == {"slice-a", "slice-b"}
    assert all(d["contiguous"] for d in decisions)
    # Every chip of both slices used, each sub-gang disjoint.
    state = ClusterState(api, clock=clock).sync()
    assert len(state.domains["slice-a"].allocator.used) == 8
    assert len(state.domains["slice-b"].allocator.used) == 8


def test_gang_multislice_prefers_single_domain_when_it_fits():
    """The opt-in must not cause gratuitous splitting: a 2-replica gang
    fits in one domain and must land there."""
    clock = Clock(1000.0)
    api = two_slice_cluster(clock)
    sched = make_scheduler(api, clock=clock)
    for i in range(2):
        p = gang_pod(f"s-{i}", "small", 2, 4)
        p["metadata"]["labels"]["tpu.dev/allow-multislice"] = "true"
        api.create("pods", p)
    decisions = []
    for i in range(2):
        pod = api.get("pods", f"s-{i}", "default")
        scores = sched.sort(pod, all_nodes(api))
        best = max(scores, key=lambda s: (s["Score"], s["Host"]))
        decisions.append(sched.bind(f"s-{i}", "default", best["Host"]))
    assert len({d["slice"] for d in decisions}) == 1


def test_gang_multislice_never_mixes_generations():
    """Phase-2 split must stay within one generation even without a pin:
    a 4x4 gang with 2 free v5p hosts and 2 free v5e hosts must NOT split
    across the pools (quota classing)."""
    clock = Clock(1000.0)
    api, _ = build_cluster(spec="v5p:2x2x2", workers=2, slice_id="slice-p",
                           clock=clock)
    api, _ = build_cluster(spec="v5e:4x4", workers=2, slice_id="slice-e",
                           api=api, clock=clock, node_prefix="enode")
    sched = make_scheduler(api, clock=clock)
    for i in range(4):
        p = gang_pod(f"x-{i}", "mixed", 4, 4)
        p["metadata"]["labels"]["tpu.dev/allow-multislice"] = "true"
        api.create("pods", p)
    pod = api.get("pods", "x-0", "default")
    scores = sched.sort(pod, all_nodes(api))
    assert all(s["Score"] == 0 for s in scores), scores


def test_gang_multislice_prefers_fewest_domains():
    """Three same-generation domains with capacities 1/1/2 hosts: a
    2-replica-split gang of 3 must use the 2-host domain plus ONE 1-host
    domain (largest-first fill = shortest DCN ring), never all three."""
    clock = Clock(1000.0)
    api, _ = build_cluster(spec="v5p:2x2x1", workers=1, slice_id="s-one",
                           clock=clock)
    api, _ = build_cluster(spec="v5p:2x2x1", workers=1, slice_id="s-two",
                           api=api, clock=clock, node_prefix="tnode")
    api, _ = build_cluster(spec="v5p:2x2x2", workers=2, slice_id="s-big",
                           api=api, clock=clock, node_prefix="bnode")
    sched = make_scheduler(api, clock=clock)
    for i in range(3):
        p = gang_pod(f"f-{i}", "fewest", 3, 4)
        p["metadata"]["labels"]["tpu.dev/allow-multislice"] = "true"
        api.create("pods", p)
    decisions = []
    for i in range(3):
        pod = api.get("pods", f"f-{i}", "default")
        scores = sched.sort(pod, all_nodes(api))
        best = max(scores, key=lambda s: (s["Score"], s["Host"]))
        assert best["Score"] > 0
        decisions.append(sched.bind(f"f-{i}", "default", best["Host"]))
    used_slices = {d["slice"] for d in decisions}
    assert "s-big" in used_slices
    assert len(used_slices) == 2, used_slices


# ---- round-2 regressions: gang-order scaling & scored multislice splits ----

def test_gang_16_members_no_rank_saturation():
    """VERDICT r1 #7: a 16-pod gang must keep a strict front-runner at every
    bind step (the old max(1, 10-rank) clamp tied all ranks >= 9, so the
    host-box marching order degraded exactly at the scale it served)."""
    clock = Clock(1000.0)
    api, _ = build_cluster(spec="v5p:4x4x4", workers=16, clock=clock)
    sched = make_scheduler(api, clock=clock)
    for i in range(16):
        api.create("pods", gang_pod(f"big-{i}", "sixteen", 16, 4))
    for i in range(16):
        pod = api.get("pods", f"big-{i}", "default")
        scores = sorted(sched.sort(pod, all_nodes(api)),
                        key=lambda s: -s["Score"])
        # Strict front-runner: the planned next host outranks every other.
        assert scores[0]["Score"] > scores[1]["Score"], (i, scores[:4])
        sched.bind(f"big-{i}", "default", scores[0]["Host"])
    state = ClusterState(api, clock=clock).sync()
    assert len(state.domains["slice-a"].allocator.used) == 64


def test_gang_multislice_split_is_scored_not_greedy():
    """VERDICT r1 #8: with DCN wide enough that the narrowest sub-gang's ICI
    bandwidth binds the multidomain score, a balanced 2+2 split (each a
    2x2x2 box, 600 GB/s) must beat greedy largest-first (3+1: the 1-host
    2x2x1 box scores 400)."""
    clock = Clock(1000.0)
    api, _ = build_cluster(spec="v5p:2x2x3", workers=3, slice_id="s-three",
                           clock=clock)
    api, _ = build_cluster(spec="v5p:2x2x2", workers=2, slice_id="s-two",
                           api=api, clock=clock, node_prefix="tnode")
    # Fat DCN: per-chip DCN share (10000 * 1/4 per chip) no longer binds,
    # exposing the ICI term the greedy order ignored.
    sched = make_scheduler(
        api, clock=clock,
        cost_overrides={"v5p": {"dcn_host_gbps": 10000.0}})
    for i in range(4):
        p = gang_pod(f"b-{i}", "balanced", 4, 4)
        p["metadata"]["labels"]["tpu.dev/allow-multislice"] = "true"
        api.create("pods", p)
    decisions = []
    for i in range(4):
        pod = api.get("pods", f"b-{i}", "default")
        scores = sched.sort(pod, all_nodes(api))
        best = max(scores, key=lambda s: (s["Score"], s["Host"]))
        assert best["Score"] > 0, scores
        decisions.append(sched.bind(f"b-{i}", "default", best["Host"]))
    per_slice = {}
    for d in decisions:
        per_slice[d["slice"]] = per_slice.get(d["slice"], 0) + 1
    assert per_slice == {"s-three": 2, "s-two": 2}, per_slice
    # Both sub-gangs contiguous 2x2x2 boxes.
    assert all(d["contiguous"] for d in decisions)


def test_scheduler_configuration_v1_shape():
    """VERDICT r1 #5: the modern KubeSchedulerConfiguration artifact."""
    cfg = ExtenderConfig()
    sc = cfg.scheduler_configuration(host="tputopo-extender.kube-system.svc")
    assert sc["apiVersion"] == "kubescheduler.config.k8s.io/v1"
    assert sc["kind"] == "KubeSchedulerConfiguration"
    ext = sc["extenders"][0]
    assert ext["urlPrefix"] == (
        "http://tputopo-extender.kube-system.svc:32743/tputopo-scheduler")
    assert ext["prioritizeVerb"] == "sort" and ext["bindVerb"] == "bind"
    assert "filterVerb" not in ext
    assert ext["weight"] == 1 and ext["enableHTTPS"] is False
    assert ext["nodeCacheCapable"] is True and ext["ignorable"] is False
    assert ext["managedResources"] == [
        {"name": "tpu.dev/chips", "ignoredByScheduler": True}]


def test_gang_rank_scaling_no_tie_at_any_size():
    """Code-review r2: round() re-tied rank 1 with rank 0 from n=19 up
    (banker's rounding); rank 0 must be the unique max at every gang size."""
    from tputopo.extender.scheduler import ExtenderScheduler

    for n in (2, 3, 10, 16, 19, 32, 64, 128):
        ctx = {"plan": {f"n{i}": None for i in range(n)},
               "order": [f"n{i}" for i in range(n)]}
        scores = [ExtenderScheduler._score_gang_node(None, ctx, f"n{i}")
                  for i in range(n)]
        assert scores[0] == MAX_PRIORITY
        assert all(s < scores[0] for s in scores[1:]), (n, scores[:4])
        assert all(a >= b for a, b in zip(scores, scores[1:])), (n, scores)
        assert min(scores) >= 1


def test_duplicate_bind_of_full_gang_does_not_wipe_members():
    """Retried/duplicate binds against a fully bound gang (ADVICE r3):

    - a retry for a member on ITS OWN node is idempotent — it returns the
      recorded decision (a kube-scheduler retry after a timed-out-but-
      successful bind must not surface a spurious failure);
    - a retry naming a DIFFERENT node raises without re-placing;
    - an EXTRA pod wearing the gang label raises "nothing left to bind";
    - none of these release the members' live assumptions (they are
      healthy — only genuinely infeasible gangs get the prompt wipe)."""
    clock = Clock(1000.0)
    api, _ = build_cluster(clock=clock)
    sched = make_scheduler(api, clock=clock)
    for i in range(2):
        api.create("pods", gang_pod(f"d-{i}", "job-e", 2, 4))
    decisions = {}
    for i in range(2):
        pod = api.get("pods", f"d-{i}", "default")
        best = max(sched.sort(pod, all_nodes(api)), key=lambda s: s["Score"])
        decisions[f"d-{i}"] = sched.bind(f"d-{i}", "default", best["Host"])
    # Same node -> idempotent replay of the recorded decision.
    own_node = decisions["d-0"]["node"]
    replay = sched.bind("d-0", "default", own_node)
    assert replay["replayed"] is True
    assert replay["chips"] == decisions["d-0"]["chips"]
    assert sched.metrics.counters["bind_idempotent_replays"] == 1
    # Different node -> error, no re-placement, annotations untouched.
    other = next(n for n in all_nodes(api) if n != own_node)
    with pytest.raises(BindError, match="already bound"):
        sched.bind("d-0", "default", other)
    # Extra pod wearing the label of a full gang -> nothing left to bind.
    api.create("pods", gang_pod("d-extra", "job-e", 2, 4))
    with pytest.raises(BindError, match="nothing left to bind"):
        sched.bind("d-extra", "default", own_node)
    for i in range(2):
        anns = api.get("pods", f"d-{i}", "default")["metadata"]["annotations"]
        assert ko.ANN_GROUP in anns, "duplicate bind wiped a live assumption"
        assert anns[ko.ANN_GROUP] == ko.coords_to_ann(
            [tuple(c) for c in decisions[f"d-{i}"]["chips"]]), \
            "a retried bind re-placed a healthy member"
    assert "gang_assumptions_released" not in sched.metrics.counters
    assert sched.metrics.counters["bind_gang_already_bound"] == 1


def test_retried_single_pod_bind_is_idempotent():
    """ADVICE r3: a bind replayed after a timed-out-but-successful earlier
    bind (kube-scheduler retry) returns the recorded decision verbatim —
    it must NOT re-run selection, which could overwrite the GROUP
    annotation with different chips while the kubelet is already
    allocating the original group."""
    api, _ = build_cluster()
    sched = make_scheduler(api)
    api.create("pods", make_pod("solo", chips=2))
    first = sched.bind("solo", "default", "node-1")
    anns_before = api.get("pods", "solo", "default")["metadata"]["annotations"]
    replay = sched.bind("solo", "default", "node-1")
    assert replay["replayed"] is True
    assert replay["chips"] == first["chips"]
    assert replay["node"] == first["node"]
    assert replay["contiguous"] == first["contiguous"]
    anns_after = api.get("pods", "solo", "default")["metadata"]["annotations"]
    assert anns_after == anns_before, "replay mutated the recorded handshake"
    # Naming the wrong node is an error, still without mutation.
    with pytest.raises(BindError, match="already bound"):
        sched.bind("solo", "default", "node-2")
    assert api.get("pods", "solo", "default")["metadata"]["annotations"] == anns_before


def test_bogus_node_chip_annotation_does_not_wedge_sort():
    """Code-review r4: a hand-written node chips annotation naming a coord
    outside the topology must not crash the verb — the bogus coord simply
    cannot be placed on (the same tolerance sync applies to UNHEALTHY)."""
    api, _ = build_cluster()
    import json as _json
    chips = _json.loads(
        api.get("nodes", "node-1")["metadata"]["annotations"][ko.ANN_CHIPS])
    chips.append({"id": "9,9,9", "path": "/dev/bogus"})
    api.patch_annotations("nodes", "node-1",
                          {ko.ANN_CHIPS: _json.dumps(chips)})
    sched = make_scheduler(api)
    pod = make_pod("p", chips=2)
    api.create("pods", pod)
    scores = {s["Host"]: s["Score"] for s in sched.sort(pod, all_nodes(api))}
    assert scores["node-1"] > 0  # real chips still schedulable
    decision = sched.bind("p", "default", "node-1")
    assert all(tuple(c) != (9, 9, 9) for c in decision["chips"])
