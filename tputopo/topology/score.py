"""All-reduce bandwidth scorer — the TPU-native combo scorer.

Replaces the reference's affinity-mark formula ``10 - 10*sum(marks)/(6*n)``
(design.md:205-217).  That formula has a documented direction bug (it ranks
the *worst* node highest — SURVEY.md §5); this scorer fixes it by
construction: the score *is* the predicted ring all-reduce algorithm
bandwidth in GB/s of the candidate chip set, so higher is strictly better
and the number is physically checkable against a measured JAX collective
(the BASELINE.md north-star metric).

Model (documented so deployments can calibrate it):

- A contiguous axis-aligned box does one bidirectional-ring reduce-scatter /
  all-gather per axis, with the payload split across axes.  Per-axis
  algorithm bandwidth for extent ``d``:

      algbw_axis = link_gbps * n_dirs * d / (2 * (d - 1))

  where ``n_dirs`` is 2 when the axis wraps (torus) or ``d == 2`` (both
  directions of the single link are usable), else 1 (open mesh — the
  classic "non-wrapped axis halves all-reduce bandwidth" rule).
  Box score = sum of algbw_axis over axes with d > 1.

- A connected but non-box ("blob") set is injection-limited by its most
  weakly attached chip: ``link_gbps * min_internal_degree * N / (2*(N-1))``.

- A set spanning several ICI components (or several nodes/slices) must cross
  DCN; its score is the narrowest component's aggregate DCN pipe — orders of
  magnitude below ICI, which yields the same strict preference ordering the
  reference encodes with SYS-vs-NVLink marks (design.md:33-44).
"""

from __future__ import annotations

from functools import lru_cache

from tputopo.topology.cost import LinkCostModel
from tputopo.topology.model import ChipTopology, Coord


def _ring_factor(d: int) -> float:
    return d / (2.0 * (d - 1)) if d > 1 else 0.0


def _axis_algbw(link_gbps: float, d: int, wrapped: bool) -> float:
    if d <= 1:
        return 0.0
    n_dirs = 2.0 if (wrapped or d == 2) else 1.0
    return link_gbps * n_dirs * _ring_factor(d)


@lru_cache(maxsize=8192)
def predict_allreduce_gbps(topo: ChipTopology, dims: tuple[int, ...],
                           cost: LinkCostModel | None = None,
                           wrap: tuple[bool, ...] | None = None) -> float:
    """Predicted all-reduce algorithm bandwidth of an axis-aligned box slice.

    ``wrap`` marks which axes of the *box* have wraparound links; by default
    an axis wraps iff the box spans the host topology's full wrapped extent.

    Memoized on its (hashable, frozen) arguments: the box search asks for
    the same handful of (topology, shape) scores tens of thousands of times
    per fleet-scale scheduling cycle.
    """
    cost = cost or LinkCostModel.for_generation(topo.generation.name)
    if wrap is None:
        wrap = tuple(
            topo.wrap[i] and dims[i] == topo.dims[i] for i in range(len(dims))
        )
    return sum(
        _axis_algbw(cost.ici_link_gbps, d, w) for d, w in zip(dims, wrap)
    )


def _components(topo: ChipTopology, chips: frozenset[Coord]) -> list[set[Coord]]:
    todo = set(chips)
    comps: list[set[Coord]] = []
    while todo:
        seed = todo.pop()
        comp = {seed}
        frontier = [seed]
        while frontier:
            c = frontier.pop()
            for n in topo.neighbors(c):
                if n in todo:
                    todo.discard(n)
                    comp.add(n)
                    frontier.append(n)
        comps.append(comp)
    return comps


def _circular_extent(vals: list[int], dim: int, wrapped: bool) -> tuple[int, int]:
    """Minimal covering extent of coordinate values along one axis.

    Returns (start, length).  On a wrapped axis the covering arc may cross
    the boundary (e.g. values {7, 0} on a wrapped axis of 8 -> start 7, len 2).
    """
    uniq = sorted(set(vals))
    span = uniq[-1] - uniq[0] + 1
    if not wrapped or len(uniq) == dim:
        return uniq[0], span
    # Largest gap between consecutive occupied values (circularly); the
    # minimal covering arc is everything outside that gap.
    best_gap, best_start = 0, uniq[0]
    for i, v in enumerate(uniq):
        nxt = uniq[(i + 1) % len(uniq)]
        gap = (nxt - v - 1) % dim
        if gap > best_gap:
            best_gap, best_start = gap, nxt
    return best_start, dim - best_gap


def _box_of(topo: ChipTopology, chips: frozenset[Coord]) -> tuple[tuple[int, ...], tuple[int, ...]] | None:
    """If ``chips`` is exactly an axis-aligned (possibly wrap-crossing) box,
    return (origin, dims); else None."""
    nd = len(topo.dims)
    origin, dims = [], []
    vol = 1
    for ax in range(nd):
        start, length = _circular_extent([c[ax] for c in chips], topo.dims[ax], topo.wrap[ax])
        origin.append(start)
        dims.append(length)
        vol *= length
    if vol != len(chips):
        return None
    # Verify every cell of the box is present.
    for c in chips:
        for ax in range(nd):
            off = (c[ax] - origin[ax]) % topo.dims[ax] if topo.wrap[ax] else c[ax] - origin[ax]
            if not (0 <= off < dims[ax]):
                return None
    return tuple(origin), tuple(dims)


def _internal_degree(topo: ChipTopology, chips: frozenset[Coord], c: Coord) -> int:
    return sum(1 for n in topo.neighbors(c) if n in chips)


@lru_cache(maxsize=16384)
def _host_count(topo: ChipTopology, chips: frozenset[Coord]) -> int:
    """Distinct hosts a chip set touches — the DCN attachment width the
    multislice scorer reads per candidate split (memoized: the composition
    search re-asks for the same sets)."""
    return len({topo.host_of(c) for c in chips})


def score_chip_set(topo: ChipTopology, chips: frozenset[Coord] | set[Coord],
                   cost: LinkCostModel | None = None) -> float:
    """Score an arbitrary chip set within one ICI domain: predicted all-reduce
    GB/s (higher is better).  A single chip scores 0.0 — no collective runs,
    and k=1 placement is decided by the anti-fragmentation policy instead
    (the analog of Gaia's Singular scheduler, Gaia PDF Alg. 3).

    Memoized (a pure function of frozen arguments): the blob fallback and
    the multislice composition search re-score the same candidate sets many
    times per scheduling cycle."""
    chips = frozenset(chips)
    cost = cost or LinkCostModel.for_generation(topo.generation.name)
    if len(chips) == 0:
        raise ValueError("empty chip set")
    return _score_chip_set_cached(topo, chips, cost)


@lru_cache(maxsize=16384)
def _score_chip_set_cached(topo: ChipTopology, chips: frozenset[Coord],
                           cost: LinkCostModel) -> float:
    n = len(chips)
    if n == 1:
        return 0.0

    comps = _components(topo, chips)
    if len(comps) > 1:
        # Disconnected within the allocation: chips outside the set do not
        # forward its traffic, so the collective stages through host memory
        # when every component shares one host (the reference's PHB-class
        # path, design.md:38-40), else rides DCN between hosts.  Either way
        # it is far below ICI, preserving the strict preference ordering.
        hosts = {topo.host_of(c) for c in chips}
        if len(hosts) == 1:
            return cost.host_dma_gbps * _ring_factor(n) * 2.0 / n
        narrowest = min(
            len({topo.host_of(c) for c in comp}) for comp in comps
        )
        return cost.dcn_host_gbps * narrowest * _ring_factor(n) * 2.0 / n

    box = _box_of(topo, chips)
    if box is not None:
        return predict_allreduce_gbps(topo, box[1], cost)

    min_deg = min(_internal_degree(topo, chips, c) for c in chips)
    return cost.ici_link_gbps * max(min_deg, 1) * _ring_factor(n)


def predict_multidomain_allreduce_gbps(
    domains: list[tuple[ChipTopology, frozenset[Coord]]],
    cost: LinkCostModel,
) -> float:
    """Score a chip set spanning several ICI domains (nodes/slices).

    Units match :func:`score_chip_set`: *per-chip* all-reduce algorithm
    bandwidth.  Cross-domain traffic rides DCN; during the inter-domain
    phase the whole payload crosses the narrowest domain's aggregate DCN
    attachment, shared by that domain's chips — so the per-chip DCN share
    is ``dcn_host_gbps * hosts / chips`` of the narrowest domain, scaled by
    the D-domain ring factor.  This keeps DCN-spanning placements strictly
    below any ICI-contiguous placement (the SYS-vs-NVLink ordering the
    reference encodes with marks, design.md:33-44).
    """
    if not domains:
        raise ValueError("no domains")
    if len(domains) == 1:
        topo, chips = domains[0]
        return score_chip_set(topo, chips, cost)
    d = len(domains)
    per_chip_dcn = min(
        cost.dcn_host_gbps * _host_count(t, chips) / len(chips)
        for t, chips in domains
        if chips
    )
    ici_bound = min(
        score_chip_set(t, chips, cost) if len(chips) > 1 else float("inf")
        for t, chips in domains
    )
    return min(per_chip_dcn * d / (2.0 * (d - 1)), ici_bound)


def explain_chip_set(topo: ChipTopology, chips: frozenset[Coord] | set[Coord],
                     cost: LinkCostModel | None = None) -> dict:
    """Human-readable decision record — the analog of the reference's worked
    scoring example (design.md:213-217) and its annotation-as-observability
    posture (SURVEY.md §5.5)."""
    chips = frozenset(chips)
    cost = cost or LinkCostModel.for_generation(topo.generation.name)
    box = _box_of(topo, chips) if len(chips) > 1 else None
    return {
        "chips": sorted(chips),
        "num_chips": len(chips),
        "hosts": sorted({topo.host_of(c) for c in chips}),
        "contiguous_box": list(box[1]) if box else None,
        "predicted_allreduce_gbps": round(score_chip_set(topo, chips, cost), 3),
        "ici_link_gbps": cost.ici_link_gbps,
    }
