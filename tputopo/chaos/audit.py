"""Invariant auditor: the correctness contract a chaos trace must hold.

Faults are only interesting if we can say what "survived" means.  The
auditor checks the control plane's hard invariants against **API truth**
(a fresh authoritative :class:`ClusterState` sync of the raw server —
never through the chaos wrapper):

1. **No chip double-booked** — no two live assignments claim one chip
   (``ClusterState.conflicts`` empty), and the engine's independent chip
   ledger agrees exactly with the API's occupancy records.
2. **Gang atomicity** — every gang is all-or-none bound; no gang sits
   with a strict subset of members bound between events.
3. **No orphaned assumptions after GC** (final audit) — one sweep later,
   no expired unconfirmed assumption still claims chips.
4. **No lost jobs** (final audit) — every arrived job is terminal
   (completed / ghost-reclaimed) or still queued with its pods intact;
   arithmetic AND identity are both checked.

``audit_engine(engine)`` runs the suite against a finished (or
mid-trace) :class:`~tputopo.sim.engine.SimEngine`; the result dict is
deterministic (sorted violations, stable counts) and lands in the chaos
report block.  Per-event auditing (``SimEngine(audit_every=N)``) runs
the occupancy/atomicity subset every N events — the test-tier dial; a
violation there raises at the exact event that broke the invariant
instead of a post-mortem at the end of the trace.
"""

from __future__ import annotations

from tputopo.extender.gc import AssumptionGC
from tputopo.extender.scheduler import _gang_of
from tputopo.extender.state import ClusterState

#: Violations kept verbatim in the report; the rest collapse to a count
#: (a broken run must not emit an O(pods) report).
_MAX_VIOLATIONS = 50


class InvariantAuditor:
    """Audits one sim engine's world.  Stateless between calls — every
    audit re-reads API truth."""

    def __init__(self, engine) -> None:
        self.engine = engine

    def _state(self) -> ClusterState:
        return ClusterState(self.engine.api,
                            assume_ttl_s=self.engine.assume_ttl_s,
                            clock=self.engine.clock).sync()

    # ---- individual invariants --------------------------------------------

    def check_no_double_booking(self, state: ClusterState,
                                violations: list[str]) -> int:
        for pa in state.conflicts:
            violations.append(
                f"double_booked: {pa.namespace}/{pa.pod_name} overlaps an "
                f"earlier claim on {pa.node_name}")
        return sum(len(d.assignments) for d in state.domains.values())

    def check_ledger_matches_api(self, state: ClusterState,
                                 violations: list[str]) -> int:
        """The engine's independent chip ledger vs API occupancy — equal
        as maps, modulo ghosts already past their TTL (the API side has
        expired them; the engine reaps them lazily at the next wake)."""
        eng = self.engine
        now = eng.clock()
        stale_ghosts = {name for name, exp in eng.ghosts.items()
                        if exp <= now}
        api_claims: dict[tuple, str] = {}
        for ns, pod, sid, held, _gang, _assigned in state.occupancy_records():
            job = pod.rsplit("-", 1)[0]
            for chip in held:
                api_claims[(sid, tuple(chip))] = job
        ledger = {key: job for key, job in eng.ledger.items()
                  if job not in stale_ghosts}
        for key in sorted(set(ledger) | set(api_claims)):
            lj, aj = ledger.get(key), api_claims.get(key)
            if lj != aj:
                violations.append(
                    f"ledger_mismatch: chip {key} ledger={lj} api={aj}")
        return len(api_claims)

    def check_gang_atomicity(self, violations: list[str]) -> int:
        """All-or-none: no gang may end a trace partially bound.

        Deliberately re-derives gang grouping and the partial-gang
        predicate from raw API objects instead of sharing the scheduler's
        ``recover()`` helpers: the auditor exists to catch bugs in exactly
        that code, and an invariant checked with the checked code's own
        predicate can never see the predicate go wrong.  Keep this
        implementation independent."""
        pods = self.engine.api.list("pods")
        gangs: dict[tuple[str, str], dict] = {}
        for p in pods:
            g = _gang_of(p)
            if g is None:
                continue
            info = gangs.setdefault((g[0], g[1]), {"size": g[2], "bound": 0})
            if p["spec"].get("nodeName"):
                info["bound"] += 1
        for (ns, gid), info in sorted(gangs.items()):
            if 0 < info["bound"] < info["size"]:
                violations.append(
                    f"gang_partial: {ns}/{gid} has {info['bound']} of "
                    f"{info['size']} members bound")
        return len(gangs)

    def check_no_orphaned_assumptions(self, violations: list[str]) -> int:
        """One sweep, then: nothing expired may remain.  Uses the raw API
        and the engine clock — GC on virtual time, like the sim's own."""
        gc = AssumptionGC(self.engine.api,
                          assume_ttl_s=self.engine.assume_ttl_s,
                          clock=self.engine.clock)
        released = gc.sweep()
        state = self._state()
        for pa in state.expired:
            violations.append(
                f"orphaned_assumption: {pa.namespace}/{pa.pod_name} expired "
                "but still annotated after a GC sweep")
        return len(released)

    def check_no_lost_jobs(self, violations: list[str]) -> int:
        eng = self.engine
        counts = eng.metrics.counts
        arrived = counts["arrived"]
        terminal = counts["completed"] + counts["ghost_reclaimed"]
        queued = len(eng.queue)
        if arrived != terminal + queued:
            violations.append(
                f"jobs_lost: arrived={arrived} != completed+reclaimed="
                f"{terminal} + queued={queued}")
        queued_names = {r.spec.name for r in eng.queue}
        live_names = set(eng.jobs)
        for name in sorted(live_names - queued_names):
            violations.append(f"job_limbo: {name} tracked but neither "
                              "queued nor terminal")
        for run in eng.queue:
            for m in range(run.spec.replicas):
                pod_name = f"{run.spec.name}-{m}"
                try:
                    pod = eng.api.get("pods", pod_name, "default")
                except Exception:
                    violations.append(
                        f"job_pod_missing: queued {pod_name} has no pod")
                    continue
                if pod["spec"].get("nodeName"):
                    violations.append(
                        f"queued_but_bound: {pod_name} is bound while its "
                        "job waits in queue")
        return arrived

    # ---- suites ------------------------------------------------------------

    def audit(self, final: bool = True) -> dict:
        """The full audit.  ``final=False`` (the per-event form) skips the
        GC-dependent and end-of-trace accounting checks, which only hold
        once the event loop has drained."""
        violations: list[str] = []
        checks: dict[str, int] = {}
        state = self._state()
        checks["assignments"] = self.check_no_double_booking(state, violations)
        checks["api_chips_claimed"] = self.check_ledger_matches_api(
            state, violations)
        checks["gangs"] = self.check_gang_atomicity(violations)
        if final:
            checks["jobs_arrived"] = self.check_no_lost_jobs(violations)
            checks["gc_final_released"] = self.check_no_orphaned_assumptions(
                violations)
        violations.sort()
        out = {"ok": not violations,
               "checks": dict(sorted(checks.items())),
               "violations": violations[:_MAX_VIOLATIONS]}
        if len(violations) > _MAX_VIOLATIONS:
            out["violations_omitted"] = len(violations) - _MAX_VIOLATIONS
        return out


def audit_engine(engine, final: bool = True) -> dict:
    """Run the invariant suite against a sim engine (see class docs)."""
    return InvariantAuditor(engine).audit(final=final)
