"""Framework core for :mod:`tputopo.lint` — the project-contract linter.

The codebase carries load-bearing guarantees that ordinary tooling cannot
see: byte-deterministic sim reports (no wall clock / ambient entropy in
deterministic modules), injected-clock discipline, the ``list_nocopy`` /
``get_nocopy`` no-mutation contract, lock-guarded shared attributes in the
threaded extender, and single-definition contract literals (report schema
versions, the Prometheus name prefix, the report counter keep-list).  Each
of those is enforced here as an AST checker over the repository's own
source — machine-checked at CI time, the way the nocopy digest guard made
aliasing checkable at run time.

Vocabulary:

- A :class:`Module` is one parsed source file (AST + token-level comments).
- A :class:`Checker` contributes :class:`Finding`\\ s for one rule id.
- A **waiver** is an inline comment ``# tpulint: disable=<rule>[,<rule>]
  -- <reason>`` suppressing that rule on its own line (trailing form) or
  on the next line (standalone-comment form).  The reason is mandatory —
  a waiver without one is itself a finding — and waivers that suppress
  nothing are findings too, so stale escapes cannot accumulate.

Stdlib-only by design (the same constraint as the scheduler core): the
whole suite must run anywhere the package imports, in well under ~5 s.
"""

from __future__ import annotations

import ast
import io
import re
import time
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Sequence

#: Rule id of the waiver-syntax meta rule (missing reason, unknown rule,
#: unused waiver).  Meta findings cannot themselves be waived.
WAIVER_RULE = "waiver"

#: Rule id reported for files that fail to parse/tokenize.
PARSE_RULE = "parse"

_WAIVER_RE = re.compile(
    r"#\s*tpulint:\s*disable=(?P<rules>[A-Za-z0-9_,\- ]+?)"
    r"(?:\s*--\s*(?P<reason>.*\S))?\s*$")


@dataclass(frozen=True)
class Finding:
    """One structured lint finding: ``path:line:col: rule: message``."""

    path: str
    line: int
    col: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}"


@dataclass
class Waiver:
    """A parsed ``# tpulint: disable=...`` comment."""

    line: int             # line the comment sits on
    applies_to: int       # line whose findings it suppresses
    rules: tuple[str, ...]
    reason: str | None
    used: bool = False


@dataclass
class Module:
    """One source file, parsed once and shared by every checker.

    Comment extraction is LAZY: tokenizing every file cost ~1.5 s of a
    whole-repo run, yet only modules carrying waiver/annotation markers
    ever need their comments — the first touch of :attr:`comments`
    tokenizes, everything else never pays."""

    relpath: str                       # repo-relative, posix separators
    source: str
    tree: ast.AST = field(repr=False, default=None)
    lines: list[str] = field(repr=False, default_factory=list)
    waivers: list[Waiver] = field(default_factory=list)
    parse_error: Finding | None = None
    _nodes: list = field(repr=False, default=None)
    _comments: dict = field(repr=False, default=None)

    def nodes(self) -> list:
        """Every AST node of the module, walked once and cached — the
        checkers share this instead of re-walking the tree apiece."""
        if self._nodes is None:
            self._nodes = list(ast.walk(self.tree))
        return self._nodes

    @property
    def comments(self) -> dict[int, str]:
        """{line: comment text}, tokenized on first access."""
        if self._comments is None:
            self._comments = {}
            try:
                for tok in tokenize.generate_tokens(
                        io.StringIO(self.source).readline):
                    if tok.type == tokenize.COMMENT:
                        self._comments[tok.start[0]] = tok.string
            except (tokenize.TokenError, IndentationError):
                pass  # AST parsed; comments are best-effort beyond that
        return self._comments

    @classmethod
    def parse(cls, relpath: str, source: str) -> "Module":
        mod = cls(relpath=relpath, source=source,
                  lines=source.splitlines())
        try:
            mod.tree = ast.parse(source)
        except SyntaxError as e:
            mod.parse_error = Finding(relpath, e.lineno or 1, e.offset or 0,
                                      PARSE_RULE, f"syntax error: {e.msg}")
            mod.tree = ast.Module(body=[], type_ignores=[])
            return mod
        if "tpulint:" in source:  # only waiver-bearing files tokenize here
            mod._parse_waivers()
        return mod

    def _parse_waivers(self) -> None:
        for line_no, text in sorted(self.comments.items()):
            m = _WAIVER_RE.search(text)
            if m is None:
                continue
            rules = tuple(r.strip() for r in m.group("rules").split(",")
                          if r.strip())
            src_line = (self.lines[line_no - 1]
                        if line_no - 1 < len(self.lines) else "")
            standalone = src_line.lstrip().startswith("#")
            self.waivers.append(Waiver(
                line=line_no,
                applies_to=line_no + 1 if standalone else line_no,
                rules=rules,
                reason=m.group("reason")))

    def comment_on_or_above(self, line: int) -> str:
        """Trailing comment on ``line`` plus a standalone comment line
        directly above — where annotation checkers look for markers."""
        parts = []
        above = self.comments.get(line - 1)
        if above is not None and line - 2 < len(self.lines) and \
                self.lines[line - 2].lstrip().startswith("#"):
            parts.append(above)
        own = self.comments.get(line)
        if own is not None:
            parts.append(own)
        return "\n".join(parts)


class Checker:
    """Base class: one contract rule.

    ``check_module`` runs per file (scoped by :meth:`applies_to`);
    ``finalize`` runs once after every file was seen — cross-module rules
    (single-definition drift) report there.  ``version`` bumps whenever a
    rule's semantics change, so CI JSON artifacts diff cleanly across
    PRs (a finding-count delta is attributable to a rule change, not a
    tree change)."""

    rule = "abstract"
    description = ""
    version = 1

    def applies_to(self, relpath: str) -> bool:
        return True

    def check_module(self, mod: Module) -> Iterable[Finding]:
        return ()

    def finalize(self) -> Iterable[Finding]:
        return ()


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for an Attribute/Name chain, else None (calls,
    subscripts and other dynamic roots cannot be a static module path)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def subscript_root(node: ast.AST) -> ast.AST:
    """The base object of a ``x[...][...].attr`` access chain."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
    return node


class LintRun:
    """Parse files once, run every checker, apply waivers, report."""

    def __init__(self, checkers: Sequence[Checker],
                 known_rules: Iterable[str] | None = None) -> None:
        self.checkers = list(checkers)
        # The full rule universe for waiver validation.  A --select run
        # executes a subset of checkers, but a waiver for a deselected
        # rule is still legitimate — it must be judged against every rule
        # that exists, not just the ones running now.
        self.known_rules = (set(known_rules) if known_rules is not None
                            else {c.rule for c in self.checkers})
        self.modules: list[Module] = []
        self._raw: list[Finding] = []
        self.waived: list[Finding] = []
        #: Per-rule finding/waived counts and wall seconds — the CI
        #: artifact's ``by_rule`` block, so a slow or noisy rule is
        #: attributable from the JSON alone.
        self.rule_stats: dict[str, dict] = {
            c.rule: {"findings": 0, "waived": 0, "duration_s": 0.0}
            for c in self.checkers}

    def _timed(self, checker: Checker, fn) -> list[Finding]:
        t0 = time.perf_counter()
        got = list(fn())
        stats = self.rule_stats.get(checker.rule)
        if stats is not None:
            stats["duration_s"] += time.perf_counter() - t0
        return got

    def add_module(self, mod: Module) -> None:
        self.modules.append(mod)
        if mod.parse_error is not None:
            self._raw.append(mod.parse_error)
            return
        for checker in self.checkers:
            if checker.applies_to(mod.relpath):
                self._raw.extend(
                    self._timed(checker,
                                lambda: checker.check_module(mod)))

    def add_source(self, relpath: str, source: str) -> None:
        self.add_module(Module.parse(relpath, source))

    def add_path(self, path: Path, relpath: str) -> None:
        self.add_source(relpath, path.read_text(encoding="utf-8"))

    def finish(self) -> list[Finding]:
        """Finalize cross-module checkers, apply waivers, and return the
        ACTIVE findings (waived ones land in :attr:`waived`)."""
        for checker in self.checkers:
            self._raw.extend(self._timed(checker, checker.finalize))
        by_module = {m.relpath: m for m in self.modules}
        active: list[Finding] = []
        for f in sorted(self._raw, key=lambda f: (f.path, f.line, f.col,
                                                  f.rule, f.message)):
            waiver = self._matching_waiver(by_module.get(f.path), f)
            if waiver is not None:
                waiver.used = True
                self.waived.append(f)
                if f.rule in self.rule_stats:
                    self.rule_stats[f.rule]["waived"] += 1
            else:
                active.append(f)
        active.extend(self._waiver_findings())
        active = sorted(active, key=lambda f: (f.path, f.line, f.col, f.rule))
        for f in active:
            if f.rule in self.rule_stats:
                self.rule_stats[f.rule]["findings"] += 1
        for stats in self.rule_stats.values():
            stats["duration_s"] = round(stats["duration_s"], 3)
        return active

    @staticmethod
    def _matching_waiver(mod: Module | None, f: Finding) -> Waiver | None:
        if mod is None or f.rule in (WAIVER_RULE, PARSE_RULE):
            return None
        for w in mod.waivers:
            # A reasonless waiver suppresses NOTHING: the violation stays
            # active alongside the waiver-syntax finding, so fixing the
            # comment cannot silently change what the run reports.
            if w.reason and f.line in (w.applies_to, w.line) \
                    and f.rule in w.rules:
                return w
        return None

    def _waiver_findings(self) -> list[Finding]:
        active = {c.rule for c in self.checkers}
        known = self.known_rules | active | {WAIVER_RULE, PARSE_RULE}
        out = []
        for mod in self.modules:
            for w in mod.waivers:
                if not w.reason:
                    out.append(Finding(
                        mod.relpath, w.line, 0, WAIVER_RULE,
                        "waiver must carry a reason: "
                        "`# tpulint: disable=<rule> -- <why>`"))
                    continue
                unknown = [r for r in w.rules if r not in known]
                if unknown:
                    out.append(Finding(
                        mod.relpath, w.line, 0, WAIVER_RULE,
                        f"waiver names unknown rule(s) {unknown} "
                        f"(known: {sorted(known)})"))
                elif not w.used and all(r in active for r in w.rules):
                    # Unused is only judgeable when every named rule's
                    # checker actually ran — under --select, a waiver for
                    # a deselected rule could not have been used.
                    out.append(Finding(
                        mod.relpath, w.line, 0, WAIVER_RULE,
                        f"unused waiver for {list(w.rules)} — it suppresses "
                        "nothing; remove it"))
        return out


def discover_files(root: Path, roots: Sequence[str] = ("tputopo", "tests"),
                   ) -> list[tuple[Path, str]]:
    """All ``.py`` files under ``root/<r>`` for each requested subtree,
    as (absolute path, repo-relative posix path), deterministically
    ordered.  Generated protobuf stubs are excluded (not ours to lint),
    and so is ``tests/lint_corpus/`` — the seeded KNOWN-BAD fixture
    files each rule must flag; the corpus tests feed them explicitly."""
    out: list[tuple[Path, str]] = []
    for sub in roots:
        base = root / sub
        if not base.is_dir():
            continue
        for p in sorted(base.rglob("*.py")):
            rel = p.relative_to(root).as_posix()
            if "__pycache__" in rel or rel.endswith("_pb2.py") \
                    or "tests/lint_corpus/" in rel:
                continue
            out.append((p, rel))
    return out
