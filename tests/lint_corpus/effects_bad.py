# lint-corpus-relpath: tputopo/corpus/effects_bad.py
"""KNOWN-BAD effect-purity corpus: the branch-copy launder.

The flow-insensitive nocopy rules walk statements in source order, so
the copy in the ``if`` branch hides the mutation from them — only the
per-path CFG analysis sees the uncopied path still reaching ``sort``.
"""


def thin(pods, aggressive):
    if aggressive:
        pods = [dict(p) for p in pods]  # copies on THIS path only
    pods.sort(key=len)  # BAD: mutates the stored list on the other path
    return pods


def stamp(pods):
    for p in pods:
        p["seen"] = True  # BAD: store through a view element
    return pods


def caller(api):
    thin(api.list_nocopy("pods"), False)
    stamp(api.list_nocopy("pods"))
