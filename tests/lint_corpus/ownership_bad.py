# lint-corpus-relpath: tputopo/corpus/ownership_bad.py
"""KNOWN-BAD ownership-flow corpus: in-place mutation reachable from
shared-writer contexts — a direct fold under a ReplicaSet scheduler, and
one hidden behind virtual dispatch."""


class Scheduler:
    def apply_events(self, state, events):
        # BAD: unguarded in-place fold on a scheduler ReplicaSet races
        return state.fold_inplace(events)


class FastScheduler(Scheduler):
    def apply_events(self, state, events):
        # BAD: the override reached only through virtual dispatch
        return state.bind_inplace(events)


class ReplicaSet:
    def __init__(self, schedulers: list[Scheduler]):
        self.schedulers = list(schedulers)

    def deliver(self, state, events):
        for s in self.schedulers:
            s.apply_events(state, events)


def start_replicas(make_config, api):
    cfg = make_config(shared_writers=True)
    # BAD: a shared-writer construction context handing out the
    # structural-sharing store
    server = api(nocopy_writes=True)
    return cfg, server
