"""Cluster state: the extender's in-memory world, rebuilt from the API
server on demand.

Keeps the reference's statelessness posture (SURVEY.md §5.4: "a restarted
extender rebuilds its world from the API server; no private state files"):
every sync reads node annotations (topology, component 2.5's output) and pod
annotations (assignments, component 2.9's output) and reconstructs
per-ICI-domain allocators.  An assumption older than the TTL that was never
confirmed does not count as occupancy — that is the GC semantics the
two-phase handshake needs (design.md:227-246; SURVEY.md §5.2).
"""

from __future__ import annotations

import json
import math
import time
from dataclasses import dataclass, field
from functools import lru_cache

from tputopo.k8s import objects as ko
from tputopo.k8s.fakeapi import FakeApiServer
from tputopo.topology.cost import LinkCostModel
from tputopo.topology.model import ChipTopology, Coord, parse_topology
from tputopo.topology.slices import Allocator, chips_mask


@dataclass
class PodAssignment:
    pod_name: str
    namespace: str
    node_name: str
    chips: list[Coord]
    assigned: bool
    assume_time: float
    gang_id: str | None


class _DeltaUnappliable(Exception):
    """An event the copy-on-write delta machinery cannot fold exactly
    (node-topology change, overlapping claim, conflicted base state) —
    the caller falls back to a full sync().  ``code`` is the structured
    fallback reason the scheduler's split counters attribute the rebuild
    to (``node_churn`` / ``overlap`` / ``conflict`` / ``other``; the
    ``journal_gap`` reason is raised by the informer side, not here)."""

    def __init__(self, detail: str, code: str = "other") -> None:
        super().__init__(detail)
        self.code = code


class _PodRec:
    """Per-pod derived-state record: what sync concluded about one pod,
    kept in an index so watch deltas can fold pod events without an
    O(pods) rescan.  ``held`` is the chip subset this pod actually
    occupies in the allocator (== chips unless the pod conflicted)."""

    __slots__ = ("pa", "sid", "status", "held")

    def __init__(self, pa: PodAssignment, sid: str, status: str,
                 held: tuple[Coord, ...]) -> None:
        self.pa = pa
        self.sid = sid
        self.status = status  # "active" | "expired"
        self.held = held


@lru_cache(maxsize=4096)
def _parse_chips_ann(s: str) -> tuple[Coord, ...]:
    """Node ANN_CHIPS JSON -> chip coords, memoized on the (stable)
    annotation string: every sync re-reads every node's chip list, which
    at fleet scale was ~10^5 json.loads per trace."""
    return tuple(tuple(int(x) for x in c["id"].split(","))
                 for c in json.loads(s))


def _assume_time_of(pod: dict) -> float:
    """Annotation timestamp, 0.0 when absent or malformed — a hand-written
    bad value must never crash sync (it just reads as long-expired).
    Non-finite values (nan/inf) count as malformed: nan would bypass the
    TTL comparison forever and inf would occupy chips eternally."""
    raw = pod["metadata"].get("annotations", {}).get(ko.ANN_ASSUME_TIME, "0")
    try:
        val = float(raw)
    except (TypeError, ValueError):
        return 0.0
    return val if math.isfinite(val) else 0.0


# Parsed-assignment cache (ClusterState.PA_CACHE): the fold/sync hot
# paths call _pod_assignment_of for every pod of every event batch —
# ~3.8M times per XL trace — and the api server bumps resourceVersion on
# EVERY write, so (namespace, name, resourceVersion) pins one immutable
# annotation snapshot and the parse is a pure function of it.  The key
# alone is NOT globally unique — two api servers (the sim runs one per
# policy) restart the version counter, so a hit additionally requires
# the cached entry's metadata dict to be the SAME OBJECT: under the
# nocopy read path an unchanged pod hands out one stored incarnation
# (identity holds, hits land), while a colliding key from another
# server is a different dict and recomputes.  Pods without a
# resourceVersion (hand-built test objects, foreign clients) bypass the
# cache entirely.  The cached PodAssignment is SHARED by all callers —
# safe under the repo-wide "assignments are replaced, never mutated"
# discipline (_update_assignment builds a new record; nothing writes
# PodAssignment fields in place).  Bounded FIFO like _parse_chips_ann's
# lru; hit/miss stats are module-local (state.py has no Metrics
# plumbing) for the differential test and the CI smoke.
_PA_CACHE: dict[tuple, tuple] = {}  # key -> (metadata dict, parse)
_PA_CACHE_MAX = 32768
_PA_CACHE_STATS = {"hits": 0, "misses": 0}


def _pod_assignment_of(pod: dict) -> PodAssignment | None:
    """The assignment a pod object carries, or None for a pod with no
    derived-state impact (no chip group or not bound to a node).  THE pod
    filter — shared by sync() and the event folders, so the two can never
    silently diverge on what counts as an assignment."""
    md = pod.get("metadata", {})
    key = None
    if ClusterState.PA_CACHE:
        rv = md.get("resourceVersion")
        if rv is not None:
            key = (md.get("namespace", "default"), md.get("name"), rv)
            got = _PA_CACHE.get(key)
            if got is not None and got[0] is md:
                _PA_CACHE_STATS["hits"] += 1
                return got[1]
            _PA_CACHE_STATS["misses"] += 1
    anns = md.get("annotations", {})
    group = anns.get(ko.ANN_GROUP)
    node_name = pod.get("spec", {}).get("nodeName")
    if not group or not node_name:
        pa = None
    else:
        pa = PodAssignment(
            pod_name=md["name"],
            namespace=md.get("namespace", "default"),
            node_name=node_name,
            chips=ko.ann_to_coords(group),
            assigned=anns.get(ko.ANN_ASSIGNED) == "true",
            assume_time=_assume_time_of(pod),
            gang_id=anns.get(ko.ANN_GANG_ID),
        )
    if key is not None:
        if len(_PA_CACHE) >= _PA_CACHE_MAX:
            _PA_CACHE.clear()
        _PA_CACHE[key] = (md, pa)
    return pa


def _host_coord_of(anns: dict) -> Coord:
    """Node ANN_HOST_COORD -> host-grid coordinate (shared parse)."""
    return tuple(int(x) for x in anns[ko.ANN_HOST_COORD].split(","))


def _node_unhealthy_of(anns: dict, valid: frozenset) -> frozenset[Coord]:
    """Node ANN_UNHEALTHY -> this node's dead-chip set, bogus coords
    dropped (a hand-written annotation must not wedge sync) — shared by
    sync() and the node-event folder."""
    return frozenset(
        c for c in ko.ann_to_coords(anns.get(ko.ANN_UNHEALTHY, ""))
        if c in valid)


@dataclass
class SliceDomain:
    """One ICI domain: a set of nodes sharing a torus (same slice-id)."""

    slice_id: str
    topology: ChipTopology
    allocator: Allocator
    node_by_host: dict[Coord, str] = field(default_factory=dict)   # host coord -> node name
    host_by_node: dict[str, Coord] = field(default_factory=dict)
    chips_by_node: dict[str, list[Coord]] = field(default_factory=dict)
    # Per-node chip bitmask over the topology's chip index, precomputed at
    # sync (immutable afterwards, shared across copy-on-write states):
    # free_chips_on_node is then one AND against the allocator's free_mask.
    node_masks: dict[str, int] = field(default_factory=dict)
    assignments: list[PodAssignment] = field(default_factory=list)
    conflicts: list[PodAssignment] = field(default_factory=list)
    expired: list[PodAssignment] = field(default_factory=list)
    # Dead chips (node-reported health, ANN_UNHEALTHY) and the live
    # assignments whose groups overlap them — the scheduler half of the
    # health loop: never place onto these, surface who is stranded on them.
    unhealthy: set[Coord] = field(default_factory=set)
    on_unhealthy: list[PodAssignment] = field(default_factory=list)

    def node_of_chip(self, chip: Coord) -> str | None:
        host = self.topology.host_of(chip)
        return self.node_by_host.get(host)


class ClusterState:
    #: Kill switch for the single-owner in-place fold (leg 1 of the fleet
    #: hot-path pass): False makes :meth:`fold_inplace` delegate to the
    #: copy-on-write :meth:`with_events`/:meth:`with_bind` path, byte-for-
    #: byte — the differential tests' comparator.  Class-level so a test
    #: can flip the whole process; callers still decide *eligibility*
    #: (only a provably single-owner state may fold in place).
    FOLD_INPLACE = True

    #: Kill switch for the parsed-assignment cache (XL hot-path pass):
    #: :func:`_pod_assignment_of` memoizes its result per (namespace,
    #: name, resourceVersion) — the api server bumps resourceVersion on
    #: every write and the nocopy guard forbids content drift at an
    #: unmoved version, so the key pins one immutable annotation
    #: snapshot and the parse is a pure function of it (a hit also
    #: requires metadata-dict identity, so a second api server's
    #: colliding version counter can never alias).  Pods without a
    #: resourceVersion bypass the cache, so a hit can only ever return
    #: the value the parse would recompute — fold results, sync results,
    #: and report bytes are identical under both settings.  False
    #: restores the parse-per-call path wholesale.
    PA_CACHE = True

    def __init__(self, api_server: FakeApiServer, *,
                 cost_for_generation=None, assume_ttl_s: float = 60.0,
                 clock=time.time) -> None:
        self.api = api_server
        self.assume_ttl_s = assume_ttl_s
        self.clock = clock
        self._cost_for_generation = cost_for_generation or (
            lambda gen: LinkCostModel.for_generation(gen))
        self.domains: dict[str, SliceDomain] = {}
        self.expired: list[PodAssignment] = []  # assumptions the TTL voided
        # Assignments whose chip groups overlap an earlier pod's (double-book
        # races, hand-written annotations) or name chips outside the slice.
        # Sync must tolerate them — a poisoned annotation would otherwise
        # wedge every verb AND the GC that could clean it up.
        self.conflicts: list[PodAssignment] = []
        self._dom_by_node: dict[str, SliceDomain] = {}
        # Delta-maintenance bookkeeping (populated by sync):
        self._pod_index: dict[tuple[str, str], _PodRec] = {}
        self._unhealthy_by_node: dict[str, frozenset[Coord]] = {}
        self._synced_at: float = 0.0  # clock at sync — expiry judgement time
        # Domains whose occupancy the in-place fold paths moved since the
        # owner last drained the set (ExtenderScheduler.DIRTY_FOLD memo
        # eviction).  Recorded unconditionally at every mark/release site
        # — it is a bounded set of slice_ids, and recording must not
        # depend on the scheduler-side switch so a mid-run flip never
        # sees a half-recorded fold.
        self._dirty_sids: set[str] = set()

    # ---- sync (SURVEY.md §3.2: parse annotations -> in-memory model) -------

    def _list(self, kind: str) -> list[dict]:
        """List via the reader; sync only PARSES the objects (tuples/sets
        of its own are what it keeps), so copy-free readers (the informer
        mirror) are asked not to deepcopy."""
        try:
            # tpulint: disable=nocopy-flow -- sync's documented read-only listing: it parses objects into tuples/sets of its own and keeps none of the stored dicts
            return self.api.list(kind, copy=False)
        except TypeError:  # reader without a copy kwarg (fake/REST client)
            return self.api.list(kind)

    def sync(self) -> "ClusterState":
        self.domains = {}
        self.expired = []
        self.conflicts = []
        self._dom_by_node = {}
        self._pod_index = {}
        self._unhealthy_by_node = {}
        self._dirty_sids = set()
        for node in self._list("nodes"):
            anns = node["metadata"].get("annotations", {})
            if ko.ANN_TOPOLOGY not in anns or ko.ANN_SLICE_ID not in anns:
                continue  # not a TPU node
            slice_id = anns[ko.ANN_SLICE_ID]
            topo = parse_topology(anns[ko.ANN_TOPOLOGY])
            dom = self.domains.get(slice_id)
            if dom is None:
                cost = self._cost_for_generation(topo.generation.name)
                dom = SliceDomain(
                    slice_id=slice_id, topology=topo,
                    allocator=Allocator(topo, cost),
                )
                self.domains[slice_id] = dom
            elif dom.topology != topo:
                raise ValueError(
                    f"nodes of slice {slice_id!r} disagree on topology: "
                    f"{dom.topology.describe()} vs {topo.describe()}"
                )
            name = node["metadata"]["name"]
            host = _host_coord_of(anns)
            dom.node_by_host[host] = name
            dom.host_by_node[name] = host
            self._dom_by_node[name] = dom
            dom.chips_by_node[name] = list(
                _parse_chips_ann(anns.get(ko.ANN_CHIPS, "[]")))
            dom.node_masks[name] = chips_mask(
                dom.topology, dom.chips_by_node[name], ignore_unknown=True)
            node_unhealthy = _node_unhealthy_of(anns, dom.topology.chip_set)
            if node_unhealthy:
                self._unhealthy_by_node[name] = node_unhealthy
                dom.unhealthy.update(node_unhealthy)

        now = self.clock()
        self._synced_at = now
        pods = sorted(
            self._list("pods"),
            key=lambda p: (
                _assume_time_of(p),
                p["metadata"].get("namespace", "default"),
                p["metadata"]["name"],
            ),
        )
        for pod in pods:
            pa = _pod_assignment_of(pod)
            if pa is None:
                continue
            dom = self._domain_of_node(pa.node_name)
            if dom is None:
                continue
            key = (pa.namespace, pa.pod_name)
            if not pa.assigned and now - pa.assume_time > self.assume_ttl_s:
                # Stale assumption: bind happened but Allocate never confirmed
                # within the TTL — the chips are NOT occupied (SURVEY.md §5.2).
                self.expired.append(pa)
                dom.expired.append(pa)
                self._pod_index[key] = _PodRec(pa, dom.slice_id, "expired", ())
                continue
            dom.assignments.append(pa)
            # Mask-native freshness: one bitmask accumulation instead of
            # materializing the allocator's coord-set `used` view per pod
            # (the view cache is invalidated by every mark_used, so the
            # old per-pod set membership rebuilt it O(chips) per
            # assignment — a measured sim-wall item).  Out-of-slice
            # coords, duplicates within the group, and overlaps with
            # earlier claimants all drop out of the mask; any drop flags
            # the conflict exactly as the set-based filter did.
            alloc = dom.allocator
            fresh_mask = 0
            taken = alloc.used_mask
            for c in pa.chips:
                i = alloc._index.get(c)
                if i is None:
                    continue
                b = 1 << i
                if b & (taken | fresh_mask):
                    continue
                fresh_mask |= b
            if fresh_mask.bit_count() != len(pa.chips):
                # Overlap or out-of-slice chips: first pod keeps the chips,
                # later claimants are flagged (fragmentation_report surfaces
                # them; the operator or job controller resolves).
                self.conflicts.append(pa)
                dom.conflicts.append(pa)
            fresh = alloc.chips_of_mask(fresh_mask)
            alloc.mark_used(fresh)
            self._pod_index[key] = _PodRec(pa, dom.slice_id, "active",
                                           tuple(fresh))
            if any(c in dom.unhealthy for c in pa.chips):
                # Running (or promised) on silicon the node now reports
                # dead — surfaced for the job controller; chips stay
                # accounted to the pod until it is deleted/re-placed.
                dom.on_unhealthy.append(pa)
        # Dead chips are not placeable: mark the remainder used so no
        # selector, gang plan, or k=1 pick can touch them (mask-native:
        # one AND against the free mask, no coord-set view build).
        for dom in self.domains.values():
            add = chips_mask(dom.topology, dom.unhealthy) \
                & dom.allocator.free_mask
            if add:
                dom.allocator.mark_used(dom.allocator.chips_of_mask(add))
        return self

    def _domain_of_node(self, node_name: str) -> SliceDomain | None:
        return self._dom_by_node.get(node_name)

    # ---- delta application (the watch/bind fast path) ----------------------

    def event_has_impact(self, kind: str, etype: str, obj: dict) -> bool:
        """Cheap O(1) pre-screen: could folding this watch event change
        any derived state?  False only when provably not — a pod with no
        record here and no assignment in the event object (the Pending
        ADDED every arrival emits, the DELETED of a never-bound pod).
        Screening those out before :meth:`with_events` is what keeps the
        per-arrival path from paying a copy-on-write clone for events
        that cannot move occupancy.  Conservative everywhere else: node
        events and unknown kinds always report impact."""
        if kind != "pods" or etype == "BOOKMARK":
            return kind != "pods"  # BOOKMARK: no impact; nodes: always
        md = obj.get("metadata", {})
        key = (md.get("namespace", "default"), md.get("name"))
        if key in self._pod_index:
            return True
        if etype == "DELETED":
            return False  # nothing recorded -> nothing held -> no-op
        return self._parse_pod_assignment(obj) is not None

    def note_bind(self, pa: PodAssignment, *, chips_marked: bool = False) -> None:
        """Record a bind the CALLER just committed, in place — the
        single-owner twin of :meth:`with_bind` (no copy-on-write clone:
        only valid when no other reader holds this state, e.g. the sim's
        baseline policies, which own their cached state outright).
        ``chips_marked=True`` means the caller already marked the chips
        used during planning; otherwise they are marked here (raising if
        any is taken).  The record is what later DELETED/assumption-wipe
        events fold against — without it, event folding could never
        release this bind's chips."""
        dom = self._dom_by_node[pa.node_name]
        if not chips_marked:
            dom.allocator.mark_used(pa.chips)
        dom.assignments.append(pa)
        self._dirty_sids.add(dom.slice_id)
        self._pod_index[(pa.namespace, pa.pod_name)] = _PodRec(
            pa, dom.slice_id, "active", tuple(pa.chips))

    def _cow(self) -> "ClusterState":
        """Copy-on-write clone: the receiver and its domains are never
        mutated, so concurrently running sorts holding the old state keep a
        consistent snapshot; the caller mutates the clone and atomically
        publishes it.  Topology, node maps, chip lists/masks are immutable
        after sync — shared; occupancy (an O(1) mask clone) and assignment
        lists are copied.  Per-state memos (gang plans, node scores) are
        attribute-attached by the scheduler and deliberately NOT carried
        over: the delta invalidates them."""
        new = ClusterState.__new__(ClusterState)
        new.api = self.api
        new.assume_ttl_s = self.assume_ttl_s
        new.clock = self.clock
        new._cost_for_generation = self._cost_for_generation
        new.expired = list(self.expired)
        new.conflicts = list(self.conflicts)
        new._pod_index = dict(self._pod_index)
        new._unhealthy_by_node = self._unhealthy_by_node
        new._synced_at = self._synced_at
        new._dirty_sids = set()  # fresh owner, nothing drained yet
        new.domains = {}
        new._dom_by_node = {}
        for sid, dom in self.domains.items():
            nd = SliceDomain(
                slice_id=sid, topology=dom.topology,
                allocator=dom.allocator.clone(),
                node_by_host=dom.node_by_host,
                host_by_node=dom.host_by_node,
                chips_by_node=dom.chips_by_node,
                node_masks=dom.node_masks,
                assignments=list(dom.assignments),
                conflicts=list(dom.conflicts),
                expired=list(dom.expired),
                unhealthy=dom.unhealthy,
                on_unhealthy=list(dom.on_unhealthy),
            )
            new.domains[sid] = nd
            for node in nd.host_by_node:
                new._dom_by_node[node] = nd
        return new

    def with_bind(self, pa: PodAssignment) -> "ClusterState":
        """A new state equal to this one plus one just-bound assignment —
        the extender's bind delta (VERDICT r3 #1: bind used to pay a full
        O(pods) cluster re-sync per call; applying its own delta to the
        informer-coherent derived state is O(chips)).

        Raises ValueError when the assignment's chips are not free here
        (the caller falls back to a full re-sync)."""
        new = self._cow()
        dom = new._dom_by_node.get(pa.node_name)
        if dom is None:
            raise ValueError(f"node {pa.node_name} not in any domain")
        dom.allocator.mark_used(pa.chips)  # raises if any chip is taken
        dom.assignments.append(pa)
        new._pod_index[(pa.namespace, pa.pod_name)] = _PodRec(
            pa, dom.slice_id, "active", tuple(pa.chips))
        return new

    def apply_event(self, kind: str, event: dict) -> "ClusterState | None":
        """This state plus one informer-style watch event
        (``{"type": ADDED|MODIFIED|DELETED, "object": ...}``) folded in
        copy-on-write, or None when the event cannot be applied exactly
        (node-topology change, overlapping chip claim, conflicted base
        state) and the caller must fall back to a full sync()."""
        return self.with_events([(kind, event.get("type"), event["object"])])

    def with_events(self, events,
                    reasons: list[str] | None = None) -> "ClusterState | None":
        """Fold a sequence of ``(kind, event_type, object)`` watch events
        into a copy-on-write clone — the generalization of the bind-only
        delta to the full informer event vocabulary: pod ADDED/MODIFIED/
        DELETED (binds, assumption wipes, confirms, deletions) and node
        unhealthy-chip changes apply in O(event); node add/remove or any
        topology-shaped change returns None (full sync is the only exact
        answer there).  Expiry is still judged at this state's original
        sync time — the caller's staleness bound (the scheduler's
        _INFORMER_STATE_MAX_AGE_S) governs when a real re-sync re-judges
        the TTL clock.

        ``reasons``, when given, receives the structured fallback reason
        code on a None return (``node_churn`` / ``overlap`` / ``conflict``
        / ``other``) — what the scheduler's per-reason fallback counters
        attribute the forced rebuild to."""
        if self.conflicts:
            # A conflicted base state's occupancy attribution is
            # order-dependent (first claimant wins); removing or adding
            # claims can reshuffle it in ways only a full re-sort sees.
            if reasons is not None:
                reasons.append("conflict")
            return None
        new = self._cow()
        try:
            for kind, etype, obj in events:
                if etype == "BOOKMARK":
                    continue
                if kind == "pods":
                    new._apply_pod_event(etype, obj)
                elif kind == "nodes":
                    new._apply_node_event(etype, obj)
                else:
                    raise _DeltaUnappliable(f"unknown kind {kind!r}")
        except _DeltaUnappliable as e:
            if reasons is not None:
                reasons.append(e.code)
            return None
        return new

    def fold_inplace(self, events,
                     reasons: list[str] | None = None) -> "ClusterState | None":
        """Single-owner twin of :meth:`with_events`: fold the same event
        vocabulary by MUTATING this state instead of paying the
        copy-on-write clone (``_cow``'s O(active-pods) list/dict copies
        were ~6.2k folds per fleet trace).  Only valid when the caller
        holds the ONLY reference to this state — the sim engine's
        bind-from-cache scheduler and the baseline policies' cached
        states qualify; anything published to concurrent readers (the
        extender's informer-coherent pair) must keep using
        :meth:`with_events`.

        Returns ``self`` on success.  Returns None when an event cannot
        fold exactly (same reason vocabulary as :meth:`with_events`) —
        and then this state may be PARTIALLY MUTATED and must be
        discarded for a full sync, which is precisely what every delta
        consumer already does on a None.

        With :attr:`FOLD_INPLACE` off (the kill switch) this delegates
        to the copy-on-write path byte-for-byte and returns the clone,
        leaving ``self`` untouched — so call sites can stay shape-
        agnostic (``new = state.fold_inplace(...)``) under either mode."""
        if not ClusterState.FOLD_INPLACE:
            return self.with_events(events, reasons)
        if self.conflicts:
            # Same verdict as with_events: conflicted occupancy
            # attribution is order-dependent — only a re-sort answers.
            if reasons is not None:
                reasons.append("conflict")
            return None
        try:
            for kind, etype, obj in events:
                if etype == "BOOKMARK":
                    continue
                if kind == "pods":
                    self._apply_pod_event(etype, obj)
                elif kind == "nodes":
                    self._apply_node_event(etype, obj)
                else:
                    raise _DeltaUnappliable(f"unknown kind {kind!r}")
        except _DeltaUnappliable as e:
            if reasons is not None:
                reasons.append(e.code)
            return None
        return self

    def bind_inplace(self, pa: PodAssignment) -> "ClusterState | None":
        """Single-owner twin of :meth:`with_bind`: apply one just-committed
        bind by mutating this state (an O(chips) :meth:`note_bind`) instead
        of cloning.  Same ownership contract as :meth:`fold_inplace`; the
        :attr:`FOLD_INPLACE` kill switch restores the copy-on-write clone
        byte-for-byte.  Returns ``self`` (or the clone) on success, None
        when the chips are not cleanly free here — ``mark_used`` validates
        the whole batch before mutating, so a None leaves this state
        UNCHANGED (unlike a failed fold) and the caller simply drops it."""
        if not ClusterState.FOLD_INPLACE:
            try:
                return self.with_bind(pa)
            except ValueError:
                return None
        try:
            self.note_bind(pa)
        except (ValueError, KeyError):
            return None
        return self

    # -- event folding internals (mutate a _cow clone only) ------------------

    def _parse_pod_assignment(self, obj: dict) -> PodAssignment | None:
        """The assignment a pod object carries, or None when it has no
        derived-state impact — sync()'s shared pod filter
        (:func:`_pod_assignment_of`) plus the known-node gate."""
        pa = _pod_assignment_of(obj)
        if pa is None or self._dom_by_node.get(pa.node_name) is None:
            return None
        return pa

    def _apply_pod_event(self, etype: str, obj: dict) -> None:
        md = obj.get("metadata", {})
        key = (md.get("namespace", "default"), md["name"])
        old = self._pod_index.get(key)
        new_pa = None if etype == "DELETED" else self._parse_pod_assignment(obj)
        if old is None and new_pa is None:
            return  # no derived impact before or after (e.g. a Pending pod)
        if old is not None and new_pa is not None:
            if (old.pa.node_name == new_pa.node_name
                    and list(old.pa.chips) == list(new_pa.chips)):
                self._update_assignment(key, old, new_pa)
                return
            # Chips or node moved: remove the old claim, add the new one.
        if old is not None:
            self._remove_assignment(key, old)
        if new_pa is not None:
            self._add_assignment(new_pa)

    @staticmethod
    def _replace_in(lst: list, old_pa: PodAssignment,
                    new_pa: PodAssignment) -> None:
        for i, x in enumerate(lst):
            if x is old_pa:
                lst[i] = new_pa
                return

    @staticmethod
    def _remove_from(lst: list, pa: PodAssignment) -> bool:
        for i, x in enumerate(lst):
            if x is pa:
                del lst[i]
                return True
        return False

    def _update_assignment(self, key, old: _PodRec,
                           new_pa: PodAssignment) -> None:
        """Metadata-only change (ASSIGNED confirm, assume-time restamp,
        gang label): same chips, same node — occupancy unchanged, replace
        the record.  The old PodAssignment object is shared with the parent
        state's lists, so it is replaced, never mutated."""
        dom = self.domains[old.sid]
        if old.status == "expired":
            if (new_pa.assigned == old.pa.assigned
                    and new_pa.assume_time == old.pa.assume_time):
                return  # echo — nothing moved
            # A restamp/confirm of an expired assumption changes whether a
            # fresh sync would count its chips — only a real sync answers.
            raise _DeltaUnappliable("expired assumption changed")
        self._replace_in(dom.assignments, old.pa, new_pa)
        self._replace_in(dom.on_unhealthy, old.pa, new_pa)
        self._pod_index[key] = _PodRec(new_pa, old.sid, old.status, old.held)

    def _remove_assignment(self, key, rec: _PodRec) -> None:
        del self._pod_index[key]
        dom = self.domains[rec.sid]
        if rec.status == "expired":
            self._remove_from(self.expired, rec.pa)
            self._remove_from(dom.expired, rec.pa)
            return
        if not self._remove_from(dom.assignments, rec.pa):
            raise _DeltaUnappliable("assignment record out of step")
        self._remove_from(dom.on_unhealthy, rec.pa)
        if rec.held:
            dom.allocator.release(rec.held)
            # Dead chips stay unplaceable even after their holder goes.
            back = [c for c in rec.held if c in dom.unhealthy]
            if back:
                dom.allocator.mark_used(back)
            self._dirty_sids.add(dom.slice_id)

    def _add_assignment(self, pa: PodAssignment) -> None:
        dom = self._dom_by_node[pa.node_name]
        key = (pa.namespace, pa.pod_name)
        if not pa.assigned and \
                self._synced_at - pa.assume_time > self.assume_ttl_s:
            # Already stale at this state's sync-time judgement: not
            # occupancy, exactly as sync() would have filed it.
            self.expired.append(pa)
            dom.expired.append(pa)
            self._pod_index[key] = _PodRec(pa, dom.slice_id, "expired", ())
            return
        try:
            dom.allocator.mark_used(pa.chips)
        except ValueError:
            # Overlap, out-of-slice chip, or duplicate within the group —
            # sync() files these as conflicts with order-dependent
            # attribution; only a full re-sort reproduces that.
            raise _DeltaUnappliable("chips not cleanly free",
                                     code="overlap") from None
        dom.assignments.append(pa)
        self._dirty_sids.add(dom.slice_id)
        self._pod_index[key] = _PodRec(pa, dom.slice_id, "active",
                                       tuple(pa.chips))

    def _apply_node_event(self, etype: str, obj: dict) -> None:
        md = obj.get("metadata", {})
        name = md.get("name")
        anns = md.get("annotations", {})
        known = name in self._dom_by_node
        if etype in ("ADDED", "DELETED"):
            if not known and (ko.ANN_TOPOLOGY not in anns
                              or ko.ANN_SLICE_ID not in anns):
                return  # a non-TPU node joining/leaving changes nothing derived
            raise _DeltaUnappliable("node set changed", code="node_churn")
        # MODIFIED: appliable iff the node's topology-shaped annotations are
        # untouched and only the unhealthy-chip report moved.
        if ko.ANN_TOPOLOGY not in anns or ko.ANN_SLICE_ID not in anns:
            if known:
                raise _DeltaUnappliable("node stopped being a TPU node",
                                        code="node_churn")
            return
        if not known:
            raise _DeltaUnappliable("node became a TPU node", code="node_churn")
        dom = self._dom_by_node[name]
        if (anns[ko.ANN_SLICE_ID] != dom.slice_id
                or parse_topology(anns[ko.ANN_TOPOLOGY]) != dom.topology):
            raise _DeltaUnappliable("node topology changed", code="node_churn")
        if dom.host_by_node.get(name) != _host_coord_of(anns):
            raise _DeltaUnappliable("host coordinate changed", code="node_churn")
        chips = list(_parse_chips_ann(anns.get(ko.ANN_CHIPS, "[]")))
        if chips != dom.chips_by_node.get(name):
            raise _DeltaUnappliable("node chip list changed", code="node_churn")
        node_unhealthy = _node_unhealthy_of(anns, dom.topology.chip_set)
        if node_unhealthy == self._unhealthy_by_node.get(name, frozenset()):
            return  # labels or other metadata — no derived impact
        self._fold_unhealthy(dom, name, node_unhealthy)

    def _fold_unhealthy(self, dom: SliceDomain, name: str,
                        node_unhealthy: frozenset[Coord]) -> None:
        """Apply one node's new unhealthy-chip report: dead chips enter the
        used mask unless an assignment already accounts for them; chips
        reported healthy again free up unless a live assignment holds them."""
        per_node = dict(self._unhealthy_by_node)
        if node_unhealthy:
            per_node[name] = node_unhealthy
        else:
            per_node.pop(name, None)
        self._unhealthy_by_node = per_node
        union: set[Coord] = set()
        for n in dom.host_by_node:
            union |= per_node.get(n, frozenset())
        held: set[Coord] = set()
        for rec in self._pod_index.values():
            if rec.sid == dom.slice_id and rec.status == "active":
                held.update(rec.held)
        alloc = dom.allocator
        # Mask-native batch: newly-dead chips enter the used mask unless an
        # assignment (or an overlapping prior report) already covers them;
        # recovered chips leave it unless an assignment holds them
        # (release of a not-used chip is a no-op by contract).
        add = chips_mask(dom.topology,
                         [c for c in union - dom.unhealthy
                          if c not in held]) & alloc.free_mask
        if add:
            alloc.mark_used(alloc.chips_of_mask(add))
        gone = [c for c in dom.unhealthy - union if c not in held]
        if gone:
            alloc.release(gone)
        if add or gone:
            self._dirty_sids.add(dom.slice_id)
        dom.unhealthy = union  # fresh set: the parent's is shared, not ours
        dom.on_unhealthy = [pa for pa in dom.assignments
                            if any(c in union for c in pa.chips)]

    # ---- views -------------------------------------------------------------

    def domain_of_node(self, node_name: str) -> SliceDomain | None:
        return self._domain_of_node(node_name)

    def free_chips_on_node(self, node_name: str) -> list[Coord]:
        dom = self._domain_of_node(node_name)
        if dom is None:
            return []
        # One AND against the precomputed node mask; coords come back in
        # chip-index (== ascending coordinate) order.
        return dom.allocator.chips_of_mask(
            dom.node_masks.get(node_name, 0) & dom.allocator.free_mask)

    def free_mask_on_node(self, node_name: str) -> int:
        """Free chips on a node as a bitmask over its domain's chip index —
        the mask-native form the sort hot loop feeds straight into
        :meth:`Allocator.find` (no set round-trip)."""
        dom = self._domain_of_node(node_name)
        if dom is None:
            return 0
        return dom.node_masks.get(node_name, 0) & dom.allocator.free_mask

    def occupancy_records(self):
        """Every pod currently holding chips, as ``(namespace, pod_name,
        slice_id, held_chips, gang_id, assigned)`` tuples in sorted
        (namespace, pod) order — the defrag planner's victim universe.
        ``held_chips`` is the subset the pod actually occupies in the
        allocator (conflicted claims excluded), so a plan built from
        these records frees exactly what eviction frees."""
        out = []
        for (ns, name), rec in sorted(self._pod_index.items()):
            if rec.status == "active" and rec.held:
                out.append((ns, name, rec.sid, rec.held, rec.pa.gang_id,
                            rec.pa.assigned))
        return out

    def fragmentation_report(self) -> dict:
        """Observability: per-domain free/used and largest free box — the
        analog of Gaia's fragment-node bookkeeping (PDF §III.B).  Served
        per /state hit: counts are popcounts and largest_free_box runs off
        the allocator's incremental index (clones share it, so a derived
        state inherits the last computed witness), not a fresh windowed
        scan per request."""
        out = {}
        for sid, dom in self.domains.items():
            largest = dom.allocator.largest_free_box()
            out[sid] = {
                "topology": dom.topology.describe(),
                "free_chips": dom.allocator.free_count,
                "used_chips": dom.allocator.used_count,
                "largest_free_box": list(largest[1]) if largest else None,
                "expired_assumptions": len(dom.expired),
                "conflicting_assignments": [
                    f"{pa.namespace}/{pa.pod_name}" for pa in dom.conflicts
                ],
                "unhealthy_chips": sorted(map(list, dom.unhealthy)),
                "assignments_on_unhealthy": [
                    {"pod": f"{pa.namespace}/{pa.pod_name}",
                     "gang": pa.gang_id}
                    for pa in dom.on_unhealthy
                ],
            }
        return out


def list_pods_nocopy(api) -> list[dict]:
    """Read-only pod listing, copy-free where the reader supports the
    hint (informer mirror / fake API nocopy) — the shared shim for every
    read-only whole-store consumer (defrag demand derivation,
    /debug/defrag, the GC sweep's expiry scan).  Callers parse the
    objects and keep none of them."""
    try:
        # tpulint: disable=nocopy-flow -- THE documented copy-free shim: every consumer (defrag demand derivation, /debug/defrag, the GC expiry scan) reads the listing and keeps nothing
        return api.list("pods", copy=False)
    except TypeError:  # reader without a copy kwarg (fake/REST client)
        return api.list("pods")


def full_sync(api, *, cost_for_generation=None, assume_ttl_s: float = 60.0,
              clock=time.time) -> ClusterState:
    """THE full O(pods) rebuild, as one shared call site: every consumer
    of the cached-derived-state discipline (the extender's ``_state``
    cache-miss branches, the sim baselines' delta-fallback) lands here
    when — and only when — the delta/journal-fold fast paths cannot
    answer exactly (cache miss, journal gap, node churn, conflicted base
    state).  Each caller counts its own fallback (``state_full_rebuilds``
    / ``invalidate_full_drop_*``), which is what makes the amortization
    argument below auditable from reports instead of asserted."""
    # tpulint: disable=hot-path-scan -- amortized: the ONE shared counted cache-miss/fallback rebuild behind every delta-maintained state (scheduler state_full_rebuilds, baseline invalidate_full_drop_*); steady-state paths fold deltas and never reach here
    return ClusterState(api, cost_for_generation=cost_for_generation,
                        assume_ttl_s=assume_ttl_s, clock=clock).sync()
