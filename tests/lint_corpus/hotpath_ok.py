# lint-corpus-relpath: tputopo/corpus/hotpath_ok.py
"""Clean twin of hotpath_bad: indexed reads on the hot path; the full
scan exists but only off-path (cold setup) — reachability matters."""


class Engine:
    def __init__(self, api):
        self.api = api

    # hot-path-root: corpus event loop (one call per event)
    def run_events(self):
        while self.step():
            pass

    def step(self):
        # O(result) indexed lookup — not a store scan
        return self.api.list_by_meta("pods", "gang", "g1")

    def cold_rebuild(self):
        # The same primitive OFF the hot path is fine: this is the
        # startup/recovery shape, not per-event work.
        return self.api.list_nocopy("pods")
