"""The ``clock-flow`` checker: transitive wall-clock effect analysis.

The per-function rules (``determinism``, ``clock``) see a wall-clock
call only in the body that makes it.  A helper that calls
``time.perf_counter()`` on behalf of the sim engine — or of any function
that took an injected ``clock`` — was a blind spot: the run stays green
and quietly stops being virtual-time-pure.  This rule closes it with the
call graph: compute which functions *root* a wall-clock effect, then
flag every such root that is reachable from

- any function defined in a deterministic module (``sim/``, ``chaos/``,
  ``topology/``, ``obs/``, ``defrag/planner.py``), or
- any ``clock``-taking function anywhere in the package,

via call paths whose interior hops are ordinary helpers.  Propagation
stops at ``clock``-taking functions and deterministic-module functions:
each of those re-promises virtual time and is an entry in its own right,
so its body is judged by the direct rules (and by this rule's own
treatment of it as an entry) — never double-reported through a caller.

Findings attach at the **wall-clock call site** (the root), naming one
example entry path — one fix (or one reasoned waiver) covers every path
that reaches it.  Wall sites *inside* deterministic modules or
``clock``-taking functions are the direct rules' findings and are
skipped here.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tputopo.lint.callgraph import CallGraph, FunctionInfo, graph_for
from tputopo.lint.clocks import (DETERMINISTIC_FILES, DETERMINISTIC_PREFIXES,
                                 WALL_CLOCK_CALLS)
from tputopo.lint.core import Checker, Finding, Module, dotted_name

#: Entry-path hops shown in a finding before eliding.
_PATH_HOPS = 4


def _in_deterministic_scope(relpath: str) -> bool:
    return (relpath.startswith(DETERMINISTIC_PREFIXES)
            or relpath in DETERMINISTIC_FILES)


class ClockFlowChecker(Checker):
    rule = "clock-flow"
    description = ("wall-clock calls must not be transitively reachable "
                   "from deterministic modules or clock-taking functions "
                   "through helper call chains")

    def __init__(self) -> None:
        self._mods: list[Module] = []

    def applies_to(self, relpath: str) -> bool:
        # Whole-program module set, shared with the other graph-backed
        # checkers (one cached build); findings are scoped below.
        return relpath.startswith(("tputopo/", "tests/"))

    def check_module(self, mod: Module) -> Iterable[Finding]:
        self._mods.append(mod)
        return ()

    def finalize(self) -> Iterable[Finding]:
        mods, self._mods = self._mods, []
        graph = graph_for(mods)
        # A wall site is literally a ``time.``/``datetime`` call — skip
        # whole modules that never spell either (most of the tree).
        wall_mods = {m.relpath for m in mods
                     if "time." in m.source or "datetime" in m.source}

        def is_entry(fn: FunctionInfo) -> bool:
            return (fn.takes_clock and fn.relpath.startswith("tputopo/")) \
                or _in_deterministic_scope(fn.relpath)

        for fn in sorted(graph.functions.values(), key=lambda f: f.key):
            if not fn.relpath.startswith("tputopo/") \
                    or fn.relpath not in wall_mods:
                continue  # wall clocks in tests are not the contract
            if is_entry(fn):
                continue  # direct rules own this body
            wall_sites = self._wall_sites(fn)
            if not wall_sites:
                continue
            path = self._entry_path(graph, fn, is_entry)
            if path is None:
                continue  # not reachable from virtual-time territory
            via = " -> ".join(p.display for p in path[:_PATH_HOPS])
            if len(path) > _PATH_HOPS:
                via += " -> ..."
            for node, dotted in wall_sites:
                yield Finding(
                    fn.relpath, node.lineno, node.col_offset, self.rule,
                    f"{dotted}() in {fn.qualname}() is transitively "
                    f"reachable from virtual-time code ({via}) — take an "
                    "injectable wall hook (the clock=time.time default-arg "
                    "idiom) or waive with a reason")

    @staticmethod
    def _wall_sites(fn: FunctionInfo) -> list[tuple[ast.Call, str]]:
        out = []
        stack = list(getattr(fn.node, "body", []))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # separate scope, judged on its own
            if isinstance(node, ast.Call):
                dotted = dotted_name(node.func)
                if dotted in WALL_CLOCK_CALLS:
                    out.append((node, dotted))
            stack.extend(ast.iter_child_nodes(node))
        out.sort(key=lambda pair: (pair[0].lineno, pair[0].col_offset))
        return out

    @staticmethod
    def _entry_path(graph: CallGraph, fn: FunctionInfo,
                    is_entry) -> list[FunctionInfo] | None:
        """Shortest caller chain entry -> ... -> fn whose interior hops
        are non-entries (an interior entry re-promises virtual time and
        would be its own entry), or None."""
        seen = {fn.key}
        frontier: list[list[FunctionInfo]] = [[fn]]
        while frontier:
            nxt: list[list[FunctionInfo]] = []
            for chain in frontier:
                for site in graph.callers_of(chain[0]):
                    caller = site.caller
                    if caller.key in seen:
                        continue
                    seen.add(caller.key)
                    if is_entry(caller):
                        return [caller] + chain
                    nxt.append([caller] + chain)
            frontier = nxt
        return None
