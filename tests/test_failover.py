"""End-to-end failure recovery: the whole framework story in one test.

A training job is scheduled (sort → bind → Allocate → confirm), trains and
checkpoints; a chip under it dies; the scheduler plane surfaces the
stranded assignment and refuses the dead silicon for every new placement;
the job controller deletes and resubmits; the replacement lands on healthy
chips; the workload restores its checkpoint onto the NEW slice layout and
keeps training.  This is the composition of SURVEY.md §5.3 (failure
detection), §5.4 (checkpoint-as-statelessness), and the L4/L2 planes —
none of the pieces is mocked beyond the CPU-emulated probe.
"""

import jax
import jax.numpy as jnp
import numpy as np

from tests.cluster import build_cluster
from tests.test_extender import Clock, all_nodes, make_scheduler
from tputopo.extender import ClusterState
from tputopo.k8s import make_pod
from tputopo.k8s import objects as ko
from tputopo.workloads import checkpoint as ckpt
from tputopo.workloads.model import ModelConfig
from tputopo.workloads.sharding import build_mesh
from tputopo.workloads.train import make_sharded_state, make_sharded_train_step
import pytest

CFG = ModelConfig(vocab_size=64, d_model=32, n_layers=2, n_heads=4,
                  n_kv_heads=2, d_ff=64, max_seq=32,
                  compute_dtype=jnp.float32)


def _schedule(sched, api, name):
    pod = api.get("pods", name, "default")
    scores = sched.sort(pod, all_nodes(api))
    best = max(scores, key=lambda s: (s["Score"], s["Host"]))
    assert best["Score"] > 0, f"no feasible node for {name}"
    return sched.bind(name, "default", best["Host"])


@pytest.mark.slow
def test_chip_death_replace_and_resume(tmp_path):
    clock = Clock(1000.0)
    api, plugins = build_cluster(clock=clock)  # v5p:2x2x4, 4 nodes, 16 chips
    sched = make_scheduler(api, clock=clock)

    # --- schedule the job and confirm the handshake (L4 -> L2) -----------
    api.create("pods", make_pod("job", chips=4))
    decision = _schedule(sched, api, "job")
    node = decision["node"]
    chip_ids = [",".join(str(x) for x in c) for c in decision["chips"]]
    plugins[node].kubelet.allocate(ko.RESOURCE_CHIPS, chip_ids)
    assert api.get("pods", "job", "default")[
        "metadata"]["annotations"][ko.ANN_ASSIGNED] == "true"

    # --- the workload trains on its 4-device mesh and checkpoints --------
    plan = build_mesh({"dp": 2, "tp": 2}, devices=jax.devices()[:4])
    state = make_sharded_state(plan, CFG, jax.random.key(0), lr=1e-2)
    step = make_sharded_train_step(plan, CFG, lr=1e-2)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 64, (4, 16)))
    for _ in range(3):
        state, loss_before = step(state, toks)
    assert ckpt.save(tmp_path, state) == 3

    # --- a chip under the job dies (L1/L2 -> L3) -------------------------
    dead = decision["chips"][0]
    dead_id = ",".join(str(x) for x in dead)
    plugins[node].set_health(dead_id, healthy=False)

    cs = ClusterState(api, clock=clock).sync()
    dom = cs.domain_of_node(node)
    assert tuple(dead) in dom.unhealthy
    stranded = [pa for pa in dom.on_unhealthy if pa.pod_name == "job"]
    assert stranded, "assignment on dead silicon must be surfaced"

    # No NEW placement may touch the dead chip even while the old pod
    # still holds its assignment.
    api.create("pods", make_pod("probe", chips=1))
    d_probe = _schedule(sched, api, "probe")
    assert tuple(d_probe["chips"][0]) != tuple(dead)

    # --- job controller: delete + resubmit (the reference's posture:
    # re-placement, not in-place healing) ---------------------------------
    api.delete("pods", "job", "default")
    api.create("pods", make_pod("job-r2", chips=4))
    d2 = _schedule(sched, api, "job-r2")
    new_chips = {tuple(c) for c in d2["chips"]}
    assert tuple(dead) not in new_chips, "replacement landed on dead chip"
    assert d2["contiguous"]
    plugins[d2["node"]].kubelet.allocate(
        ko.RESOURCE_CHIPS, [",".join(str(x) for x in c) for c in d2["chips"]])

    # --- the replacement pod restores onto a DIFFERENT mesh layout and
    # keeps training from step 3 ------------------------------------------
    plan2 = build_mesh({"dp": 4, "tp": 1}, devices=jax.devices()[:4])
    target = make_sharded_state(plan2, CFG, jax.random.key(9), lr=1e-2)
    restored = ckpt.restore(tmp_path, target)
    assert restored is not None and int(restored.step) == 3
    step2 = make_sharded_train_step(plan2, CFG, lr=1e-2)
    restored, loss_after = step2(restored, toks)
    assert int(restored.step) == 4
    # Same batch, one more optimizer step from the same trajectory: loss
    # keeps improving (memorization), proving real state carried over.
    assert float(loss_after) < float(loss_before)
