"""The ``single-def`` checker: contract literals live in exactly one place.

Report schema version strings (``tputopo.sim/v2..v4``), the sim report's
scheduler-counter keep-list, and the Prometheus metric-name prefix are
*contracts*: consumers diff reports and scrape metrics against them, and
a second copy of the literal is a drift bomb — edit one and the other
silently keeps emitting/asserting the old value.  This checker enforces
single definition two ways, both configured by a canon of
``(module, constant-name)`` pairs whose values are read from the
canonical module's own AST (so the checker never duplicates the literal
either — it is cross-referenced by construction):

- any *other* ``tputopo/`` module containing a string literal exactly
  equal to a canonical scalar value is a finding (import the constant
  instead);
- any *other* module assigning a module-level constant of the same NAME
  (a shadow keep-list, say) is a finding.

Tests are deliberately out of scope: a test that pins the literal value
is pinning the contract on purpose.
"""

from __future__ import annotations

import ast
from typing import Iterable, Sequence

from tputopo.lint.core import Checker, Finding, Module

#: The repository's contract constants: (canonical module, constant names).
DEFAULT_CANON: tuple[tuple[str, tuple[str, ...]], ...] = (
    ("tputopo/sim/report.py",
     ("SCHEMA", "SCHEMA_DEFRAG", "SCHEMA_CHAOS", "SCHEMA_PRIORITY",
      "SCHEMA_REPLICAS", "SCHEMA_KEY_MANIFEST",
      "SCHEDULER_COUNTER_KEEP")),
    ("tputopo/extender/server.py", ("_PREFIX",)),
)


def _module_constants(tree: ast.AST, names: Sequence[str]) -> dict[str, object]:
    """Values of ``NAME = <literal>`` assignments for the requested names
    (strings, or tuples/lists/sets of strings), at module level or as
    class attributes (the Prometheus ``_PREFIX`` lives on the HTTP
    handler class, not at module scope)."""
    out: dict[str, object] = {}
    body = list(getattr(tree, "body", []))
    while body:
        node = body.pop(0)
        if isinstance(node, ast.ClassDef):
            body.extend(node.body)
            continue
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t for t in node.targets if isinstance(t, ast.Name)]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None \
                and isinstance(node.target, ast.Name):
            targets = [node.target]
            value = node.value
        else:
            continue
        for t in targets:
            if t.id in names:
                try:
                    out[t.id] = ast.literal_eval(value)
                except (ValueError, SyntaxError):
                    pass
    return out


class SingleDefChecker(Checker):
    rule = "single-def"
    description = ("contract literals (report schema versions, counter "
                   "keep-list, Prometheus prefix) must be defined once and "
                   "imported everywhere else")

    def __init__(self, canon=DEFAULT_CANON, scope: str = "tputopo/") -> None:
        self.canon = tuple(canon)
        self.scope = scope
        self._mods: list[Module] = []

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(self.scope)

    def check_module(self, mod: Module) -> Iterable[Finding]:
        self._mods.append(mod)
        return ()

    def finalize(self) -> Iterable[Finding]:
        mods, self._mods = self._mods, []
        canon_names: dict[str, str] = {}     # constant name -> canonical mod
        scalar_values: dict[str, tuple[str, str]] = {}  # literal -> (mod, name)
        by_path = {m.relpath: m for m in mods}
        for canon_path, names in self.canon:
            canon_mod = by_path.get(canon_path)
            if canon_mod is None:
                continue  # canonical module not in this run's file set
            consts = _module_constants(canon_mod.tree, names)
            for name in names:
                canon_names[name] = canon_path
            for name, value in consts.items():
                if isinstance(value, str):
                    scalar_values[value] = (canon_path, name)
        if not canon_names and not scalar_values:
            return
        canon_paths = {path for path, _ in self.canon}
        for mod in mods:
            if mod.relpath in canon_paths:
                continue
            yield from self._check_against(mod, canon_names, scalar_values)

    def _check_against(self, mod: Module, canon_names: dict[str, str],
                       scalar_values: dict[str, tuple[str, str]],
                       ) -> Iterable[Finding]:
        # Shadow definitions of a canonical constant NAME.
        for node in getattr(mod.tree, "body", []):
            targets = []
            if isinstance(node, ast.Assign):
                targets = [t for t in node.targets
                           if isinstance(t, ast.Name)]
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.value is not None:
                targets = [node.target]
            for t in targets:
                if t.id in canon_names:
                    yield Finding(
                        mod.relpath, node.lineno, node.col_offset, self.rule,
                        f"shadow definition of contract constant {t.id} — "
                        f"the single definition lives in "
                        f"{canon_names[t.id]}; import it")
        # Duplicated scalar literals (docstrings that merely mention a
        # value inside longer prose do not match — equality is exact).
        for node in mod.nodes():
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                hit = scalar_values.get(node.value)
                if hit is not None:
                    path, name = hit
                    yield Finding(
                        mod.relpath, node.lineno, node.col_offset, self.rule,
                        f"duplicated contract literal {node.value!r} — "
                        f"import {name} from {path} instead")
