"""pjit/shard_map all-reduce microbenchmark — the acceptance workload.

The direct measurement of the north-star metric (BASELINE.md: "ICI
all-reduce GB/s of scheduled slice vs ideal"), and the rebuild's analog of
Gaia's MNIST acceptance experiment (PDF §IV Exp.6).  A container scheduled
by the extender runs this over the chips it was handed; the reported
algorithm bandwidth is directly comparable to the scorer's prediction
(:func:`tputopo.topology.score.predict_allreduce_gbps`) — closing the loop
the reference left open (its bandwidth-weight table was an unresolved TODO,
design.md:47).

Conventions match NCCL-tests so numbers are recognizable:
  algbw = payload_bytes / time          (what the user's gradient feels)
  busbw = algbw * 2 * (n - 1) / n      (per-link wire pressure)
"""

from __future__ import annotations

import statistics
import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map


@dataclass(frozen=True)
class AllReduceResult:
    n_devices: int
    payload_mb: float       # global (sharded) array size, as requested
    time_ms: float          # median of timed iterations
    algbw_gbps: float       # per-rank buffer bytes / time (NCCL-tests algbw)
    busbw_gbps: float

    def to_dict(self) -> dict:
        return {
            "n_devices": self.n_devices,
            "payload_mb": round(self.payload_mb, 3),
            "time_ms": round(self.time_ms, 4),
            "algbw_gbps": round(self.algbw_gbps, 3),
            "busbw_gbps": round(self.busbw_gbps, 3),
        }


def measure_allreduce(devices=None, payload_mb: float = 8.0,
                      iters: int = 20, warmup: int = 3,
                      dtype=jnp.float32) -> AllReduceResult:
    """Time a psum all-reduce across ``devices`` (default: all local).

    The payload lives sharded across devices (as a gradient would); one
    step is a full all-reduce returning the replicated sum.  Uses a 1-D
    mesh — on a contiguous torus slice XLA decomposes this into per-axis
    rings itself, which is exactly the behavior the scorer models.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    mesh = Mesh(np.asarray(devices), ("all",))
    itemsize = jnp.dtype(dtype).itemsize
    elems = max(n, int(payload_mb * 1e6) // itemsize // n * n)
    x = jnp.arange(elems, dtype=jnp.uint32).astype(dtype)
    x = jax.device_put(x, NamedSharding(mesh, P("all")))

    @jax.jit
    def allreduce_sum(v):
        # shard_map psum formulation — the collective cannot be elided.
        f = shard_map(lambda s: jax.lax.psum(s, "all"), mesh=mesh,
                      in_specs=P("all"), out_specs=P(None))
        return f(v)

    for _ in range(warmup):
        allreduce_sum(x).block_until_ready()
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        allreduce_sum(x).block_until_ready()
        times.append(time.perf_counter() - t0)
    t = statistics.median(times)
    # NCCL-tests convention: algbw = per-rank buffer bytes / time.  The
    # global array is sharded, so the all-reduced per-rank buffer holds
    # elems/n elements — NOT the full elems.
    algbw = (elems // n * itemsize) / t / 1e9
    return AllReduceResult(
        n_devices=n,
        payload_mb=elems * itemsize / 1e6,
        time_ms=t * 1e3,
        algbw_gbps=algbw,
        busbw_gbps=algbw * 2.0 * (n - 1) / n if n > 1 else algbw,
    )


def measure_axis_allreduce(plan, axis: str, payload_mb: float = 8.0,
                           iters: int = 10, warmup: int = 2,
                           dtype=jnp.float32) -> AllReduceResult:
    """All-reduce over ONE logical axis of a MeshPlan (e.g. the dp gradient
    ring), other axes held as independent replicas — what a DP x TP training
    step actually does each step."""
    mesh = plan.mesh
    n = plan.axes.get(axis, 1)
    itemsize = jnp.dtype(dtype).itemsize
    total = max(plan.n_devices, int(payload_mb * 1e6) // itemsize)
    total = total // plan.n_devices * plan.n_devices
    x = jnp.arange(total, dtype=jnp.uint32).astype(dtype)
    all_axes = tuple(a for a in mesh.axis_names)
    x = jax.device_put(x, NamedSharding(mesh, P(all_axes)))

    @jax.jit
    def step(v):
        f = shard_map(lambda s: jax.lax.psum(s, axis), mesh=mesh,
                      in_specs=P(all_axes), out_specs=P(all_axes))
        return f(v)

    for _ in range(warmup):
        step(x).block_until_ready()
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        step(x).block_until_ready()
        times.append(time.perf_counter() - t0)
    t = statistics.median(times)
    # Per-rank buffer within the reduced axis group (NCCL-tests algbw).
    algbw = (total // plan.n_devices * itemsize) / t / 1e9
    return AllReduceResult(
        n_devices=n, payload_mb=total * itemsize / 1e6, time_ms=t * 1e3,
        algbw_gbps=algbw,
        busbw_gbps=algbw * 2.0 * (n - 1) / n if n > 1 else algbw,
    )
