"""TPU generation specifications.

TPU-native analog of the reference's link taxonomy table (design.md:31-47):
where the GPU design enumerates NVLink/PCIe link classes (SYS/NODE/PHB/PXB/
PIX/PSB/NV1-4) discovered pairwise via NVML, a TPU fleet has a small set of
*generations*, each with a known interconnect geometry (2D or 3D ICI torus),
fixed per-link bandwidth, and a fixed chips-per-host layout.  The reference
left its bandwidth-weight table as an open TODO (design.md:47, "带宽权值"
unresolved); here the weights are first-class, explicit data — editable via
the extender config (see :mod:`tputopo.extender.config`) so deployments can
substitute measured numbers.

Bandwidth figures are public-spec derived (GB/s = one-way, per link, per
direction): v4 advertises 2400 Gbps/chip over 6 ICI links, v5e 1600 Gbps
over 4 links, v5p 4800 Gbps over 6 links, v6e 3584 Gbps over 4 links.
They are *defaults*, not ground truth — the north-star acceptance test
(BASELINE.md) validates predicted vs. measured all-reduce throughput.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field


@dataclass(frozen=True)
class TpuGeneration:
    """Static interconnect spec for one TPU generation.

    Attributes:
        name: canonical generation name, e.g. ``"v5p"``.
        ndims: dimensionality of the ICI mesh (2 for v5e/v6e, 3 for v4/v5p).
        max_dims: largest pod shape in chips along each axis.
        host_bounds: chips per host along each axis (v5p host = 2x2x1,
            v5e host = 4x2).  The analog of the reference's CPU-affinity
            grouping (design.md:145-146): chips on one host share a NUMA
            domain and a DCN attachment.
        cores_per_chip: TensorCores per chip.  v5p slice names count cores
            (v5p-32 == 16 chips, the 2x2x4 target in BASELINE.json).
        ici_link_gbps: one-way bandwidth of a single ICI link, GB/s.
        hbm_gbps: per-chip HBM bandwidth, GB/s (used by workload heuristics).
        dcn_host_gbps: per-host data-center-network bandwidth, GB/s.  DCN is
            the TPU analog of the reference's worst link class ``SYS``
            ("Cross CPU socket", design.md:33-36): traffic that leaves the
            ICI domain entirely.
        wrap_when_full: axes acquire wraparound (torus) links when a slice
            spans the full pod extent on that axis — standard TPU behavior;
            smaller sub-slices on that axis are open meshes.
    """

    name: str
    ndims: int
    max_dims: tuple[int, ...]
    host_bounds: tuple[int, ...]
    cores_per_chip: int
    ici_link_gbps: float
    hbm_gbps: float
    dcn_host_gbps: float
    wrap_when_full: bool = True
    # Slice shapes officially offered for this generation, in chips.
    # Used by the enumerator as the preferred shape vocabulary; arbitrary
    # boxes that fit the torus are still representable.
    standard_shapes: tuple[tuple[int, ...], ...] = field(default=())

    @property
    def chips_per_host(self) -> int:
        return math.prod(self.host_bounds)

    def slice_name(self, num_chips: int) -> str:
        """Public slice name, e.g. v5p counts cores: 16 chips -> 'v5p-32'."""
        return f"{self.name}-{num_chips * self.cores_per_chip}"


GENERATIONS: dict[str, TpuGeneration] = {
    g.name: g
    for g in [
        TpuGeneration(
            name="v4",
            ndims=3,
            max_dims=(8, 8, 16),
            host_bounds=(2, 2, 1),
            cores_per_chip=2,
            ici_link_gbps=50.0,
            hbm_gbps=1228.0,
            dcn_host_gbps=25.0,
            standard_shapes=((2, 2, 1), (2, 2, 2), (2, 2, 4), (4, 4, 4), (4, 4, 8)),
        ),
        TpuGeneration(
            name="v5e",
            ndims=2,
            max_dims=(16, 16),
            host_bounds=(4, 2),
            cores_per_chip=1,
            ici_link_gbps=50.0,
            hbm_gbps=819.0,
            dcn_host_gbps=25.0,
            standard_shapes=((1, 1), (2, 2), (2, 4), (4, 4), (4, 8), (8, 8), (8, 16), (16, 16)),
        ),
        TpuGeneration(
            name="v5p",
            ndims=3,
            max_dims=(16, 16, 24),
            host_bounds=(2, 2, 1),
            cores_per_chip=2,
            ici_link_gbps=100.0,
            hbm_gbps=2765.0,
            dcn_host_gbps=50.0,
            standard_shapes=((2, 2, 1), (2, 2, 2), (2, 2, 4), (4, 4, 4), (4, 4, 8), (8, 8, 8)),
        ),
        TpuGeneration(
            name="v6e",
            ndims=2,
            max_dims=(16, 16),
            host_bounds=(4, 2),
            cores_per_chip=1,
            ici_link_gbps=112.0,
            hbm_gbps=1638.0,
            dcn_host_gbps=50.0,
            standard_shapes=((1, 1), (2, 2), (2, 4), (4, 4), (4, 8), (8, 8), (8, 16), (16, 16)),
        ),
    ]
}


def get_generation(name: str) -> TpuGeneration:
    try:
        return GENERATIONS[name]
    except KeyError:
        raise KeyError(
            f"unknown TPU generation {name!r}; known: {sorted(GENERATIONS)}"
        ) from None
