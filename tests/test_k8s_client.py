"""KubeApiClient against the REST mock: the extender/device-plugin flows
must run unchanged over real API-server wire semantics (merge-patch with
resourceVersion CAS, binding subresource, 404/409 mapping)."""

import pytest

from tests.cluster import build_cluster
from tests.k8s_mock import MockKubeApi
from tputopo.extender import AssumptionGC, ExtenderConfig, ExtenderScheduler
from tputopo.k8s import make_pod
from tputopo.k8s import objects as ko
from tputopo.k8s.client import KubeApiClient
from tputopo.k8s.fakeapi import Conflict, NotFound


def make_env():
    api, _ = build_cluster()
    mock = MockKubeApi(api)
    return mock


def test_crud_roundtrip_over_rest():
    with make_env() as mock:
        client = KubeApiClient(base_url=mock.base_url)
        nodes = client.list("nodes")
        assert [n["metadata"]["name"] for n in nodes] == [
            "node-0", "node-1", "node-2", "node-3"]
        client.create("pods", make_pod("p1", chips=2))
        pod = client.get("pods", "p1", "default")
        assert ko.pod_requested_chips(pod) == 2  # spec survived the round-trip
        assert len(client.list("pods")) == 1
        client.delete("pods", "p1", "default")
        with pytest.raises(NotFound):
            client.get("pods", "p1", "default")


def test_merge_patch_cas_and_null_delete():
    with make_env() as mock:
        client = KubeApiClient(base_url=mock.base_url)
        client.create("pods", make_pod("p1", chips=1))
        pod = client.get("pods", "p1", "default")
        rv = pod["metadata"]["resourceVersion"]
        out = client.patch_annotations("pods", "p1", {"a": "1"}, "default",
                                       expect_version=rv)
        assert out["metadata"]["annotations"]["a"] == "1"
        # Stale version -> Conflict (the handshake's race signal).
        with pytest.raises(Conflict):
            client.patch_annotations("pods", "p1", {"a": "2"}, "default",
                                     expect_version=rv)
        # Null deletes the key.
        client.patch_annotations("pods", "p1", {"a": None}, "default")
        assert "a" not in client.get("pods", "p1", "default")["metadata"].get(
            "annotations", {})


def test_full_scheduling_flow_over_rest():
    """sort -> bind -> annotations -> GC, all through the REST client."""
    with make_env() as mock:
        client = KubeApiClient(base_url=mock.base_url)
        sched = ExtenderScheduler(client, ExtenderConfig())
        client.create("pods", make_pod("train", chips=4))
        pod = client.get("pods", "train", "default")
        nodes = [n["metadata"]["name"] for n in client.list("nodes")]
        scores = sched.sort(pod, nodes)
        best = max(scores, key=lambda s: (s["Score"], s["Host"]))
        assert best["Score"] > 0
        decision = sched.bind("train", "default", best["Host"])
        assert decision["contiguous"]

        fresh = client.get("pods", "train", "default")
        anns = fresh["metadata"]["annotations"]
        assert anns[ko.ANN_ASSIGNED] == "false"
        assert fresh["spec"]["nodeName"] == best["Host"]

        # Binding again -> Conflict via REST 409.
        with pytest.raises(Conflict):
            client.bind_pod("train", best["Host"], "default")

        # GC over REST: expire the assumption by forcing an old time.
        client.patch_annotations("pods", "train", {ko.ANN_ASSUME_TIME: "1"},
                                 "default")
        gc = AssumptionGC(client, assume_ttl_s=60)
        assert gc.sweep() == ["default/train"]
        anns = client.get("pods", "train", "default")["metadata"].get(
            "annotations", {})
        assert ko.ANN_GROUP not in anns


def test_labels_patch_over_rest():
    with make_env() as mock:
        client = KubeApiClient(base_url=mock.base_url)
        client.patch_labels("nodes", "node-0", {"tpu.dev/generation": "v5p"})
        node = client.get("nodes", "node-0")
        assert node["metadata"]["labels"]["tpu.dev/generation"] == "v5p"
