"""Seeded, deterministic fault injection for the control plane.

:class:`FaultPlan` is the single source of fault decisions: one Philox
stream (``SeedSequence``-folded, the same construction as the sim trace
generator) drawn in a fixed call order, so an identical (seed, profile)
injects an identical fault sequence into an identical operation stream —
chaos runs replay byte-for-byte, across processes (``--jobs 2``)
included.  :class:`ChaosApi` wraps an API-server surface and consults
the plan per intercepted verb:

- **CAS conflicts** beyond the organic ones: a compare-and-swap
  ``patch_annotations`` raises :class:`Conflict` before applying.
- **Transient 500s / timeouts**: :class:`ApiUnavailable` /
  :class:`ApiTimeout` raised before the verb applies (the retry path).
- **Ambiguous timeouts**: the verb APPLIES, then :class:`ApiTimeout` is
  raised — the nastiest real-world failure, exercising the caller's
  retry-reconciliation (idempotent bind replay, conflict-vs-own-success
  resolution).
- **Watch drops**: the stream raises :class:`Gone` mid-flight, forcing
  the informer's relist path; **delayed/reordered delivery** holds an
  event back past its successor (the mirror's newest-wins upserts must
  absorb it).
- **Node flaps** (:meth:`FaultPlan.flap_events`) and **crash-restart
  points** (:meth:`FaultPlan.crash_point`) are consumed by the sim
  engine / ici policy rather than the API wrapper.

The **consecutive-failure cap** (``max_consecutive``) is the liveness
contract: per (fault kind, operation key), at most ``max_consecutive``
injections land in a row before one is suppressed — so any caller
retrying at least ``max_consecutive + 1`` times is guaranteed to get
through, and a chaos trace can assert *zero lost jobs* rather than
"probably none".
"""

from __future__ import annotations

import numpy as np

from tputopo.k8s.fakeapi import Conflict, Gone
from tputopo.k8s.retry import ApiTimeout, ApiUnavailable

#: Named chaos profiles (the ``--chaos <profile>`` vocabulary).  Every
#: knob a profile omits falls back to :data:`DEFAULT_KNOBS`.
PROFILES: dict[str, dict] = {
    # The standing chaos trace: a flaky-but-functional API server plus a
    # restart-happy extender — every hardened path exercised, rates low
    # enough that headline axes degrade gracefully instead of collapsing.
    "api-flake": {
        "conflict_prob": 0.05,
        "unavailable_prob": 0.03,
        "timeout_prob": 0.02,
        "ambiguous_timeout_prob": 0.01,
        "crash_prob": 0.02,
        "node_flaps": 2,
        "flap_outage_s": 45.0,
    },
    # Crash-restart focus: the extender dies mid-gang-bind often; API
    # itself is healthy.  The recovery (complete-or-release) path is the
    # hot one.
    "crash-storm": {
        "crash_prob": 0.3,
        "conflict_prob": 0.02,
    },
    # Watch-stream focus for informer-backed deployments: drops (Gone ->
    # relist) and reordered delivery; no API write faults.
    "watch-flake": {
        "watch_drop_prob": 0.2,
        "watch_reorder_prob": 0.2,
    },
    # Replicated-control-plane focus (tputopo.extender.replicas): the
    # extender crash-restarts mid-gang-bind OFTEN — with racing replicas,
    # each restart's recover() reconciles against binds a peer completed
    # or wiped meanwhile — over a light API flake so CAS-reconciled binds
    # and claim arbitration stay hot at the same time.
    "replica-storm": {
        "crash_prob": 0.25,
        "conflict_prob": 0.03,
        "unavailable_prob": 0.01,
        "timeout_prob": 0.01,
        "ambiguous_timeout_prob": 0.01,
        "node_flaps": 1,
    },
}

DEFAULT_KNOBS: dict = {
    "conflict_prob": 0.0,            # injected CAS 409s
    "unavailable_prob": 0.0,         # transient 500s (before apply)
    "timeout_prob": 0.0,             # timeouts (before apply)
    "ambiguous_timeout_prob": 0.0,   # verb applies, then times out
    "crash_prob": 0.0,               # extender crash mid-gang-bind
    "watch_drop_prob": 0.0,          # watch stream raises Gone
    "watch_reorder_prob": 0.0,       # event held back past its successor
    "node_flaps": 0,                 # extra short fail->repair cycles
    "flap_outage_s": 45.0,           # flap repair delay (virtual seconds)
    "max_consecutive": 2,            # liveness cap per (kind, op) — see above
}


class FaultPlan:
    """Deterministic fault oracle: ``decide(kind, prob, key)`` draws from
    one seeded stream and tallies what it injected (``injected`` by kind)
    and what the consecutive cap suppressed (``suppressed``)."""

    def __init__(self, seed: int, profile: str = "api-flake",
                 **overrides) -> None:
        if profile not in PROFILES:
            raise KeyError(f"unknown chaos profile {profile!r}; "
                           f"available: {sorted(PROFILES)}")
        knobs = {**DEFAULT_KNOBS, **PROFILES[profile], **overrides}
        unknown = set(knobs) - set(DEFAULT_KNOBS)
        if unknown:
            raise ValueError(f"unknown chaos knobs {sorted(unknown)}")
        self.profile = profile
        self.knobs = knobs
        for k, v in knobs.items():
            setattr(self, k, v)
        # Same SeedSequence folding as TraceConfig.rng — a distinct
        # entropy tag keeps the fault stream independent of the trace's.
        self._rng = np.random.Generator(np.random.Philox(
            seed=np.random.SeedSequence(entropy=(0xC4A05, seed))))
        self.injected: dict[str, int] = {}
        self.suppressed = 0
        self._streaks: dict[tuple, int] = {}

    def describe(self) -> dict:
        """The resolved knob set — recorded in the report's ``engine``
        block so two chaos reports differing only in knobs are
        distinguishable."""
        return {"profile": self.profile,
                **{k: self.knobs[k] for k in sorted(self.knobs)}}

    # ---- draws -------------------------------------------------------------

    def _draw(self) -> float:
        return float(self._rng.random())

    def _apply_streak(self, streak_key: tuple | None, kind: str) -> bool:
        """THE consecutive-cap gate, shared by every decision path: a hit
        passes through (tallied) unless ``max_consecutive`` hits already
        landed in a row for ``streak_key`` — then it is suppressed
        (counted) and the streak restarts.  This single definition is
        what the 'retrying max_consecutive + 1 times always gets through'
        liveness contract rests on."""
        if streak_key is not None:
            n = self._streaks.get(streak_key, 0)
            if n >= self.max_consecutive:
                self._streaks.pop(streak_key, None)
                self.suppressed += 1
                return False
            self._streaks[streak_key] = n + 1
        self.injected[kind] = self.injected.get(kind, 0) + 1
        return True

    def decide(self, kind: str, prob: float, key: tuple | None = None) -> bool:
        """One injection decision.  ``key`` scopes the consecutive-failure
        cap: after ``max_consecutive`` injections in a row for the same
        (kind, key), the next hit is suppressed (counted), guaranteeing a
        retried operation eventually passes."""
        if prob <= 0.0:
            return False
        streak_key = None if key is None else (kind,) + key
        if self._draw() >= prob:
            if streak_key is not None:
                self._streaks.pop(streak_key, None)
            return False
        return self._apply_streak(streak_key, kind)

    def op_fault(self, op_key: tuple,
                 kinds_probs: list[tuple[str, float]]) -> str | None:
        """One failure decision for one API call: at most one fault kind
        fires, chosen by stacked probability from ONE draw, and the
        consecutive cap applies to the CALL (``op_key``), not the kind —
        so the liveness contract holds even when an operation is subject
        to several fault kinds (timeout + 500 + ambiguous): a caller
        retrying ``max_consecutive + 1`` times always gets through."""
        u = self._draw()
        acc = 0.0
        chosen = None
        for kind, prob in kinds_probs:
            acc += prob
            if u < acc:
                chosen = kind
                break
        if chosen is None:
            self._streaks.pop(op_key, None)
            return None
        return chosen if self._apply_streak(op_key, chosen) else None

    def crash_point(self, replicas: int) -> int | None:
        """Member index (1..replicas-1) before whose bind the extender
        "dies" this gang attempt, or None.  Only mid-bind points are
        drawn: a crash before member 0 is indistinguishable from no
        attempt, and after the last member the gang is already whole.
        NOT tallied here — an attempt that fails before reaching the
        crash point never crashes; the consumer records the injection
        via :meth:`record` when the crash actually fires."""
        if replicas < 2 or self.crash_prob <= 0.0:
            return None
        if self._draw() >= self.crash_prob:
            return None
        return 1 + int(self._draw() * (replicas - 1))

    def record(self, kind: str, by: int = 1) -> None:
        """Tally a fault the consumer injected from a plan decision
        (e.g. a crash point that actually fired)."""
        self.injected[kind] = self.injected.get(kind, 0) + by

    def flap_events(self, n_nodes: int,
                    horizon_s: float) -> list[tuple[float, float, int]]:
        """Extra (fail_t, repair_t, victim_index) node-flap events to merge
        into the sim's event stream — short outages that exercise the
        evict -> requeue -> re-place chain beyond the trace's organic
        failures.  Drawn once, at engine init (fixed stream position).
        Not tallied here: the engine ``record``s each flap when it LANDS
        (fails a live node or extends an outage) — a flap fully shadowed
        by a longer organic failure of the same node never counts, same
        convention as watch drops."""
        out = []
        for _ in range(int(self.node_flaps)):
            t = round(self._draw() * max(horizon_s, 1.0), 6)
            victim = int(self._draw() * max(n_nodes, 1))
            out.append((t, round(t + self.flap_outage_s, 6), victim))
        return sorted(out)


class ChaosApi:
    """Fault-injecting proxy over an API-server surface (the fake server,
    the sim's copy-free facade, or the REST client — anything with the
    FakeApiServer method shape).  Reads and writes not listed below pass
    through untouched via ``__getattr__``; the engine's own bookkeeping
    writes go to the raw server, so injection lands exactly on the
    control plane under test (scheduler, GC, defrag)."""

    def __init__(self, api, plan: FaultPlan) -> None:
        self._api = api
        self.plan = plan

    def __getattr__(self, name):
        return getattr(self._api, name)

    # ---- helpers -----------------------------------------------------------

    def _guarded(self, verb: str, key: tuple, fn, *, ambiguous: bool = True):
        """One API call under injection: a single plan decision (one
        draw, one per-OPERATION failure streak shared across every fault
        kind) picks at most one of timeout / 500 — raised BEFORE the verb
        applies — or, for write verbs, an ambiguous timeout raised AFTER
        it applied.  The shared streak is what makes the consecutive cap
        a real liveness bound: mixed fault kinds cannot stack past it."""
        p = self.plan
        kinds = [("api_timeout", p.timeout_prob),
                 ("api_unavailable", p.unavailable_prob)]
        if ambiguous:
            kinds.append(("ambiguous_timeout", p.ambiguous_timeout_prob))
        kind = p.op_fault(("op", verb) + key, kinds)
        if kind == "api_timeout":
            raise ApiTimeout(f"injected timeout: {verb} {key}")
        if kind == "api_unavailable":
            raise ApiUnavailable(f"injected 500: {verb} {key}")
        out = fn()
        if kind == "ambiguous_timeout":
            raise ApiTimeout(f"injected timeout AFTER apply: {verb} {key}")
        return out

    # ---- intercepted verbs -------------------------------------------------

    def get(self, kind: str, name: str, namespace: str | None = None) -> dict:
        return self._guarded("get", (kind, namespace, name),
                             lambda: self._api.get(kind, name, namespace),
                             ambiguous=False)  # reads have no apply side

    def patch_annotations(self, kind: str, name: str, patch,
                          namespace: str | None = None,
                          expect_version: str | None = None) -> dict:
        key = (kind, namespace, name)
        p = self.plan
        if expect_version is not None and \
                p.decide("cas_conflict", p.conflict_prob, ("c",) + key):
            # Conflicts live outside the op streak: they are not blind-
            # retried (the caller re-plans), and their own per-kind streak
            # bounds consecutive injections so a re-planned bind cannot
            # starve forever.
            raise Conflict(f"injected CAS conflict: {kind} {name}")
        return self._guarded(
            "patch", key,
            lambda: self._api.patch_annotations(kind, name, patch,
                                                namespace, expect_version))

    def bind_pod(self, name: str, node_name: str,
                 namespace: str | None = None) -> dict:
        return self._guarded(
            "bind", ("pods", namespace, name),
            lambda: self._api.bind_pod(name, node_name, namespace))

    def delete(self, kind: str, name: str,
               namespace: str | None = None) -> None:
        return self._guarded(
            "delete", (kind, namespace, name),
            lambda: self._api.delete(kind, name, namespace),
            ambiguous=False)  # delete-then-timeout replays as NotFound
                              # at the caller, already handled everywhere

    def watch(self, kind: str, resource_version: str,
              timeout_s: float = 30.0):
        """The underlying watch with drop / delayed-delivery injection:
        a drop raises :class:`Gone` after at least one event (the
        informer must relist); reorder holds one event back and delivers
        it after its successor (never dropped — at stream end at the
        latest), so the mirror's newest-wins logic is what's tested, not
        event loss."""
        p = self.plan
        drop_after = None
        if p.watch_drop_prob > 0.0 and p._draw() < p.watch_drop_prob:
            # Armed, not yet tallied: an idle window can end before the
            # drop point, and `injected` records faults that LANDED.
            drop_after = 1 + int(p._draw() * 3)
        held = None
        n = 0
        for ev in self._api.watch(kind, resource_version, timeout_s):
            if drop_after is not None and n >= drop_after:
                if held is not None:
                    yield held
                p.record("watch_drop")
                raise Gone(f"injected watch drop on {kind}")
            if held is None and ev["type"] != "BOOKMARK" and \
                    p.watch_reorder_prob > 0.0 and \
                    p._draw() < p.watch_reorder_prob:
                # Armed, not yet tallied (same contract as the drop):
                # the stream can end before a successor overtakes the
                # held event, in which case the tail delivery below is
                # in-order and no reorder LANDED.
                held = ev
                continue
            yield ev
            n += 1
            if held is not None:
                yield held
                held = None
                n += 1
                p.record("watch_reorder")
        if held is not None:
            yield held
