"""Device-plugin CLI: probe the local host and emit what would be published.

``python -m tputopo.deviceplugin`` runs the discovery shim (native
libtputopo.so when built, pure-Python twin otherwise) and prints the node
annotations + device list the plugin registers with the kubelet — the
dry-run half of the bring-up flow (SURVEY.md §3.1).  Use
``TPUTOPO_FAKE="v5p:2x2x4@0"`` on a box without TPUs.

In-cluster serving wires :class:`tputopo.deviceplugin.plugin.TpuDevicePlugin`
to the kubelet's device-plugin socket; the transport in this repo is the
in-process :class:`tputopo.deviceplugin.api.FakeKubelet` (the image has no
grpcio — see deviceplugin/api.py for the gRPC surface to bind).
"""

from __future__ import annotations

import argparse
import json
import sys


def main() -> int:
    ap = argparse.ArgumentParser(
        prog="tputopo-device-plugin",
        description="TPU topology discovery + node-annotation dry run")
    ap.add_argument("--node-name", default="local")
    ap.add_argument("--slice-id", default="slice-local")
    ap.add_argument("--native", action="store_true",
                    help="require the native libtputopo.so probe (no fallback)")
    ap.add_argument("--serve", action="store_true",
                    help="keep running, re-probing device health every "
                         "--interval seconds (in-cluster mode)")
    ap.add_argument("--interval", type=float, default=30.0)
    args = ap.parse_args()

    from tputopo.discovery import shim
    from tputopo.deviceplugin.reporter import node_annotations_for_probe

    if args.native:
        if shim.ensure_native_built() is None:
            print("error: native libtputopo.so unavailable and --native given",
                  file=sys.stderr)
            return 2
    probe = shim.probe_host()
    if not probe.ok:
        print(f"error: {probe.error}", file=sys.stderr)
        return 1
    out = {
        "backend": probe.backend,
        "node": args.node_name,
        "annotations": node_annotations_for_probe(probe, args.slice_id),
        "devices": [c for c in probe.chips],
    }
    print(json.dumps(out, indent=2))
    if args.serve:
        # In-cluster serving loop: re-probe on an interval so device-file
        # disappearance surfaces as a health flip.  The kubelet gRPC leg
        # binds through deviceplugin/api.py's transport surface; this image
        # carries no grpcio, so the loop is the health heartbeat scaffold.
        import time
        while True:
            time.sleep(args.interval)
            fresh = shim.probe_host()
            if not fresh.ok:
                print(f"probe degraded: {fresh.error}", file=sys.stderr)
            elif fresh.chips != probe.chips:
                print(json.dumps({"event": "topology-changed",
                                  "devices": list(fresh.chips)}))
                probe = fresh
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
