"""The ``nocopy-flow`` checker: interprocedural nocopy taint.

The per-function ``nocopy`` rule stops at the function boundary, so a
helper could launder a stored dict out of sight: ``def members(api):
return api.list_nocopy("pods")`` in a non-owner module hands every
caller a mutable view of the store and the base rule never connects the
dots.  This rule propagates the taint through the call graph:

- **Summaries** (fixpoint): a function *returns nocopy* when any return
  value is tainted — directly from a nocopy source, from a summarized
  callee's result, or by passing through a parameter that a caller
  taints (identity helpers).  A function *mutates a parameter* when it
  stores through / ``del``s / calls a mutating method on it (directly or
  by forwarding it into another mutator).
- **Sources**, beyond the base rule's ``list_nocopy`` / ``get_nocopy``
  / ``fetch``: the ``copy=False`` read family (``.list(...,
  copy=False)``, ``.list_by_meta(..., copy=False)``) — same stored-dict
  contract, previously invisible to the linter — and any call to a
  returns-nocopy function.
- **Findings** (per calling function): mutation of flow-tainted values,
  passing a tainted value into a parameter the callee mutates, storing a
  flow-tainted value onto ``self``, and returning one outside the owner
  modules.  Findings whose taint is visible to the base rule (a direct
  source in the same function, excluding the ``copy=False`` family) are
  left to it — no double report.

Unresolved calls contribute no taint and no mutation — conservative by
construction, per the project's rule that an unresolved edge may never
crash the checker or silently widen a guarantee.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tputopo.lint.callgraph import CallGraph, FunctionInfo, graph_for
from tputopo.lint.core import Checker, Finding, Module, subscript_root
from tputopo.lint.nocopy import (NOCOPY_SOURCES, OWNER_MODULES,
                                 _MUTATING_METHODS)

#: Method names whose call result carries the stored-dict contract when
#: called with ``copy=False``.
COPYFREE_KWARG_SOURCES = frozenset({"list", "list_by_meta"})


def _is_copyfree_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in COPYFREE_KWARG_SOURCES
            and any(kw.arg == "copy"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is False
                    for kw in node.keywords))


def _is_direct_source(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in NOCOPY_SOURCES)


class _Summary:
    __slots__ = ("returns_nocopy", "returns_params", "mutates_params")

    def __init__(self) -> None:
        self.returns_nocopy = False
        self.returns_params: set[str] = set()   # identity passthrough
        self.mutates_params: set[str] = set()


class _FlowScan:
    """One pass over a function body under the current summary map.
    ``collect`` mode updates the function's summary; ``report`` mode
    emits findings."""

    def __init__(self, checker: "NocopyFlowChecker", graph: CallGraph,
                 fn: FunctionInfo, summaries: dict, report: bool) -> None:
        self.checker = checker
        self.graph = graph
        self.fn = fn
        self.summaries = summaries
        self.report = report
        self.params = set(fn.param_names()) - {"self", "cls"}
        # name -> "flow" (interprocedural/copy=False taint — ours) or
        # "direct" (base rule's territory) or "param"
        self.taint: dict[str, str] = {}
        self.summary = summaries.setdefault(fn.key, _Summary())
        self.findings: list[Finding] = []
        self.changed = False

    # ---- taint evaluation --------------------------------------------------

    def _value_taint(self, node: ast.AST) -> str | None:
        if _is_direct_source(node):
            return "direct"
        if _is_copyfree_call(node):
            return "flow"
        if isinstance(node, ast.Call):
            callee = self.graph.resolve(node, self.fn)
            if callee is not None:
                s = self.summaries.get(callee.key)
                if s is not None:
                    if s.returns_nocopy:
                        return "flow"
                    if s.returns_params:
                        # Identity helper: result taint follows the arg,
                        # and the pass through a call boundary makes it
                        # THIS rule's taint (the base rule cannot see
                        # through the helper).
                        for i, arg in enumerate(node.args):
                            names = callee.param_names()
                            if names[:1] in (["self"], ["cls"]):
                                names = names[1:]
                            if i < len(names) \
                                    and names[i] in s.returns_params \
                                    and self._value_taint(arg) in (
                                        "flow", "direct"):
                                return "flow"
            return None
        if isinstance(node, ast.Name):
            if node.id in self.taint:
                return self.taint[node.id]
            if node.id in self.params:
                return "param"
            return None
        if isinstance(node, ast.Subscript):
            return self._value_taint(node.value)
        if isinstance(node, ast.IfExp):
            return (self._value_taint(node.body)
                    or self._value_taint(node.orelse))
        if isinstance(node, ast.BoolOp):
            for v in node.values:
                t = self._value_taint(v)
                if t:
                    return t
            return None
        if isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                t = self._value_taint(e)
                if t:
                    return t
            return None
        return None

    def _flag(self, node: ast.AST, what: str) -> None:
        if self.report:
            self.findings.append(Finding(
                self.fn.relpath, node.lineno, node.col_offset,
                self.checker.rule,
                f"{what} — nocopy/copy=False results are the stored "
                "objects; copy first, go through the copying API, or "
                "waive with a reason"))

    # ---- walk --------------------------------------------------------------

    def run(self) -> list[Finding]:
        for stmt in getattr(self.fn.node, "body", []):
            self._walk(stmt)
        return self.findings

    #: Node-type dispatch, resolved once (the getattr-per-node spelling
    #: dominated the whole-tree scan).
    _DISPATCH: dict[type, str] = {
        ast.Assign: "_visit_Assign", ast.AnnAssign: "_visit_AnnAssign",
        ast.AugAssign: "_visit_AugAssign", ast.Delete: "_visit_Delete",
        ast.For: "_visit_For", ast.Call: "_visit_Call",
        ast.Return: "_visit_Return",
    }

    def _walk(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return  # separate scopes, scanned as their own functions
        name = self._DISPATCH.get(type(node))
        if name is not None:
            getattr(self, name)(node)
        for child in ast.iter_child_nodes(node):
            self._walk(child)

    def _bind(self, target: ast.AST, taint: str | None) -> None:
        if isinstance(target, ast.Name):
            if taint in ("flow", "direct"):
                self.taint[target.id] = taint
            else:
                self.taint.pop(target.id, None)
                self.params.discard(target.id)  # rebound, no longer param
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, taint)

    def _mutation_target(self, target: ast.AST) -> None:
        """A store through a subscript/attribute chain mutates its root."""
        if isinstance(target, (ast.Subscript, ast.Attribute)):
            root = subscript_root(target)
            t = self._value_taint(root)
            if t == "flow":
                self._flag(target, "mutation of a copy-free result")
            elif t == "param" and isinstance(root, ast.Name):
                if root.id not in self.summary.mutates_params:
                    self.summary.mutates_params.add(root.id)
                    self.changed = True
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._mutation_target(e)

    def _visit_Assign(self, node: ast.Assign) -> None:
        taint = self._value_taint(node.value)
        for target in node.targets:
            self._mutation_target(target)
            if isinstance(target, ast.Attribute) \
                    and isinstance(target.value, ast.Name) \
                    and target.value.id == "self" \
                    and taint == "flow" \
                    and not self.checker.is_owner(self.fn.relpath):
                self._flag(node, "copy-free result stored onto self")
            self._bind(target, taint)

    def _visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is None:
            return
        self._mutation_target(node.target)
        self._bind(node.target, self._value_taint(node.value))

    def _visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._mutation_target(node.target)

    def _visit_Delete(self, node: ast.Delete) -> None:
        for target in node.targets:
            self._mutation_target(target)

    def _visit_For(self, node: ast.For) -> None:
        self._bind(node.target, self._value_taint(node.iter))

    def _visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATING_METHODS:
            base = node.func.value
            t = self._value_taint(base)
            if t == "flow":
                self._flag(node, f"mutating call .{node.func.attr}() on a "
                                 "copy-free result")
            elif t == "param":
                root = subscript_root(base)
                if isinstance(root, ast.Name) \
                        and root.id in self.params \
                        and root.id not in self.summary.mutates_params:
                    self.summary.mutates_params.add(root.id)
                    self.changed = True
        # Tainted argument into a parameter the callee mutates.
        callee = self.graph.resolve(node, self.fn)
        if callee is None:
            return
        s = self.summaries.get(callee.key)
        if s is None or not s.mutates_params:
            return
        names = callee.param_names()
        if names[:1] in (["self"], ["cls"]):
            names = names[1:]
        for i, arg in enumerate(node.args):
            if i < len(names) and names[i] in s.mutates_params \
                    and self._value_taint(arg) in ("flow", "direct"):
                self._flag(node, f"nocopy result passed into "
                                 f"{callee.qualname}(), which mutates its "
                                 f"{names[i]!r} parameter")
        for kw in node.keywords:
            if kw.arg in s.mutates_params \
                    and self._value_taint(kw.value) in ("flow", "direct"):
                self._flag(node, f"nocopy result passed into "
                                 f"{callee.qualname}(), which mutates its "
                                 f"{kw.arg!r} parameter")

    def _visit_Return(self, node: ast.Return) -> None:
        if node.value is None:
            return
        t = self._value_taint(node.value)
        if t in ("flow", "direct"):
            if not self.summary.returns_nocopy:
                self.summary.returns_nocopy = True
                self.changed = True
            if t == "flow" and not self.checker.is_owner(self.fn.relpath):
                self._flag(node, "copy-free result escapes via return "
                                 "outside the owner modules")
        elif isinstance(node.value, ast.Name) \
                and node.value.id in self.params:
            if node.value.id not in self.summary.returns_params:
                self.summary.returns_params.add(node.value.id)
                self.changed = True


class NocopyFlowChecker(Checker):
    rule = "nocopy-flow"
    description = ("interprocedural nocopy taint: helpers must not "
                   "launder list_nocopy/get_nocopy/copy=False results "
                   "past the owner-module boundary, and tainted values "
                   "must not reach parameter-mutating callees")

    def __init__(self, owners: frozenset[str] = OWNER_MODULES) -> None:
        self.owners = owners
        self._mods: list[Module] = []

    def is_owner(self, relpath: str) -> bool:
        return relpath in self.owners

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(("tputopo/", "tests/"))

    def check_module(self, mod: Module) -> Iterable[Finding]:
        self._mods.append(mod)
        return ()

    def finalize(self) -> Iterable[Finding]:
        mods, self._mods = self._mods, []
        graph = graph_for(mods)
        summaries: dict = {}
        # Package functions are always scanned (summaries must cover
        # every cross-module flow); test modules only when they touch a
        # nocopy surface at all — a test file that never names one can
        # neither launder nor mutate a stored dict.
        touchy = {m.relpath for m in mods
                  if not m.relpath.startswith("tests/")
                  or "nocopy" in m.source or ".fetch(" in m.source
                  or "copy=False" in m.source}
        fns = sorted((f for f in graph.functions.values()
                      if f.relpath in touchy), key=lambda f: f.key)
        # One full pass, then worklist propagation: when a function's
        # summary changes, only its CALLERS can see different taint, so
        # only they are rescanned (a naive fixpoint re-walked every AST
        # per round).  Each scan reports findings; a rescan REPLACES the
        # function's findings, so the final map equals what a fresh pass
        # under the stable summaries would emit.
        findings_by_fn: dict[tuple, list[Finding]] = {}
        work: list[FunctionInfo] = []
        for fn in fns:
            scan = _FlowScan(self, graph, fn, summaries, report=True)
            findings_by_fn[fn.key] = scan.run()
            if scan.changed:
                work.append(fn)
        budget = 20 * len(fns)  # termination backstop, far above need
        while work and budget > 0:
            fn = work.pop()
            for site in graph.callers_of(fn):
                budget -= 1
                scan = _FlowScan(self, graph, site.caller, summaries,
                                 report=True)
                findings_by_fn[site.caller.key] = scan.run()
                if scan.changed:
                    work.append(site.caller)
        for fn in fns:
            yield from findings_by_fn.get(fn.key, ())
