"""Kubernetes object helpers and the framework's annotation vocabulary.

The reference's cluster-state contract (design.md:76-86, 223-246) carried
one resource name (with a documented drift between ``aliyun.com/gpu`` and
``aliyun.com/gpu-count`` — SURVEY.md §5 "Resource-name drift"; we fix it by
defining exactly one) and two annotation families: per-node topology and the
three-field optimistic assignment handshake on pods.  This module is the
single source of truth for those names in the rebuild.

Objects are plain dicts shaped like real Kubernetes API objects (apiVersion/
kind/metadata/spec/status) so extender HTTP payloads and fixtures read like
the real thing.
"""

from __future__ import annotations

import json
from functools import lru_cache
from typing import Any

# -- The one resource name (fixes the reference's aliyun.com/gpu vs
#    aliyun.com/gpu-count drift, design.md:86,105 vs :135,149).
RESOURCE_CHIPS = "tpu.dev/chips"

# -- Node annotations (analog of GPU_<ABBR>_<i>_<j>, design.md:76-82; a
#    torus is described by shape + host coordinate, not per-edge entries).
ANN_TOPOLOGY = "tpu.dev/topology"          # e.g. "v5p:2x2x4:wrap=000"
ANN_HOST_COORD = "tpu.dev/host-coord"      # e.g. "0,0,1" (host grid coords)
ANN_CHIPS = "tpu.dev/chip-coords"          # JSON list of this node's chip coords
ANN_SLICE_ID = "tpu.dev/slice-id"          # ICI domain id; nodes sharing it share a torus
ANN_TOPOLOGY_HUMAN = "tpu.dev/topology-human"  # human-readable observability surface
ANN_GENERATION_LABEL = "tpu.dev/generation"    # node label for quota classing
                                               # (Gaia heterogeneous quota, PDF §III.A)
ANN_UNHEALTHY = "tpu.dev/unhealthy-chips"      # this node's dead chips ("0,0,0;0,1,0");
                                               # absent == all healthy.  Closes the
                                               # health->scheduler loop: the device
                                               # plugin's health stream (design.md:84-86)
                                               # must reach cluster state, or the
                                               # extender plans onto dead silicon.

# -- Pod annotations: the optimistic assignment handshake
#    (design.md:227-232: ALIYUN_COM_GPU_GROUP / ASSUME_TIME / ASSIGNED).
ANN_GROUP = "tpu.dev/chip-group"           # assigned chip coords, e.g. "0,0,0;0,1,0"
ANN_ASSUME_TIME = "tpu.dev/assume-time"    # unix seconds, stamped at bind
ANN_ASSIGNED = "tpu.dev/assigned"          # "false" at bind -> "true" at Allocate
ANN_GANG_ID = "tpu.dev/gang-id"            # job-level token for gang scheduling
ANN_PREDICTED_GBPS = "tpu.dev/predicted-allreduce-gbps"  # decision record
ANN_BOUND_BY = "tpu.dev/bound-by"          # replica id that committed the bind
                                           # (tputopo.extender.replicas) —
                                           # stamped only when the extender
                                           # carries a replica_id, so the
                                           # single-scheduler vocabulary is
                                           # byte-identical without one.
                                           # recover() reads it to count
                                           # adoptions of a peer's binds.

# -- Checkpoint declaration (tputopo.elastic).  A pod (every member of a
#    gang carries the same values) declares how its job checkpoints; the
#    disruption cost model prices evicting it as work-since-the-last-
#    checkpoint plus the restore bill instead of the whole runtime.
#    Absent == the job never checkpoints — whole-runtime pricing, the
#    pre-elastic vocabulary byte-for-byte.
ANN_CKPT_PERIOD = "tpu.dev/checkpoint-period-s"  # wall seconds between checkpoints
ANN_RESTORE_COST = "tpu.dev/restore-cost-s"      # wall seconds to resume from one

# -- Priority tiers (tputopo.priority).  A pod (or every pod of a gang)
#    declares its tier via this label/annotation; the value is either a
#    named tier or a bare integer 0..MAX_PRIORITY_VALUE.  Higher wins:
#    admission sorts high tiers first, and targeted preemption may evict
#    only *strictly lower* tiers.  Absent == "batch" (0) — the whole
#    pre-priority workload keeps its exact behavior.
LABEL_PRIORITY = "tpu.dev/priority"

#: Named tiers — the operator vocabulary; raw integers between tiers are
#: accepted (e.g. "75") so tenants can subdivide.
PRIORITY_TIERS = {"serving": 100, "prod": 50, "batch": 0}
MAX_PRIORITY_VALUE = 1000

#: Reverse map for reporting: int -> canonical tier name; off-map values
#: render as ``tier-<int>``.
_TIER_NAMES = {v: k for k, v in PRIORITY_TIERS.items()}

Annotations = dict[str, str]


def parse_priority(value: str | int | None) -> int:
    """Validate a ``tpu.dev/priority`` value: a named tier from
    :data:`PRIORITY_TIERS` or an integer in [0, MAX_PRIORITY_VALUE].
    Raises ValueError on anything else — the admission validation path
    (a malformed tier must be rejected at the door, not silently zeroed
    there)."""
    if value is None:
        return 0
    if isinstance(value, str) and value in PRIORITY_TIERS:
        return PRIORITY_TIERS[value]
    try:
        p = int(value)
    except (TypeError, ValueError):
        raise ValueError(
            f"bad {LABEL_PRIORITY} value {value!r}: want a tier name "
            f"{sorted(PRIORITY_TIERS)} or an int in "
            f"[0, {MAX_PRIORITY_VALUE}]") from None
    if not 0 <= p <= MAX_PRIORITY_VALUE:
        raise ValueError(
            f"{LABEL_PRIORITY} value {p} outside [0, {MAX_PRIORITY_VALUE}]")
    return p


def pod_priority(pod: dict) -> int:
    """A pod's priority tier, read from merged metadata (labels shadow
    annotations — the same precedence every gang reader uses).  Lenient:
    a malformed value on a *stored* pod degrades to the batch tier (0)
    instead of wedging a scheduling verb; :func:`parse_priority` is the
    strict validation entry point."""
    md = pod.get("metadata", {})
    meta = {**(md.get("annotations") or {}), **(md.get("labels") or {})}
    try:
        return parse_priority(meta.get(LABEL_PRIORITY))
    except ValueError:
        return 0


def tier_name(priority: int) -> str:
    """Canonical report label of a priority value (``serving`` / ``prod``
    / ``batch``, else ``tier-<int>``)."""
    return _TIER_NAMES.get(priority, f"tier-{priority}")


def make_node(name: str, *, chips: int = 0, labels: Annotations | None = None,
              annotations: Annotations | None = None) -> dict[str, Any]:
    """A Node object advertising ``chips`` units of RESOURCE_CHIPS."""
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {
            "name": name,
            "labels": dict(labels or {}),
            "annotations": dict(annotations or {}),
        },
        "status": {
            "allocatable": {RESOURCE_CHIPS: str(chips)},
            "capacity": {RESOURCE_CHIPS: str(chips)},
        },
    }


def make_pod(name: str, *, namespace: str = "default", chips: int = 0,
             labels: Annotations | None = None,
             annotations: Annotations | None = None,
             node_name: str | None = None) -> dict[str, Any]:
    """A Pod requesting ``chips`` units of RESOURCE_CHIPS in one container."""
    resources = {"limits": {RESOURCE_CHIPS: str(chips)},
                 "requests": {RESOURCE_CHIPS: str(chips)}} if chips else {}
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "labels": dict(labels or {}),
            "annotations": dict(annotations or {}),
        },
        "spec": {
            "containers": [{"name": "main", "resources": resources}],
            **({"nodeName": node_name} if node_name else {}),
        },
        "status": {"phase": "Pending"},
    }


def pod_requested_chips(pod: dict[str, Any]) -> int:
    """Total RESOURCE_CHIPS requested across containers (limits take
    precedence, matching kubelet extended-resource semantics)."""
    total = 0
    for c in pod.get("spec", {}).get("containers", []):
        res = c.get("resources", {})
        v = res.get("limits", {}).get(RESOURCE_CHIPS) \
            or res.get("requests", {}).get(RESOURCE_CHIPS)
        if v is not None:
            total += int(v)
    return total


def coords_to_ann(coords) -> str:
    """Serialize chip coords for ANN_GROUP: ``"0,0,0;0,1,0"`` — the analog
    of the reference's ``ALIYUN_COM_GPU_GROUP: 0,1,2,3`` (design.md:228)."""
    return ";".join(",".join(str(x) for x in c) for c in coords)


@lru_cache(maxsize=8192)
def _ann_to_coords_cached(s: str) -> tuple[tuple[int, ...], ...]:
    return tuple(tuple(int(x) for x in part.split(","))
                 for part in s.split(";"))


def ann_to_coords(s: str) -> list[tuple[int, ...]]:
    """Parse an ANN_GROUP-style coord list.  Parsing is memoized on the
    annotation string: a cluster sync re-reads every pod's (stable) GROUP
    annotation, which at fleet scale was ~10^5 re-parses per trace; the
    returned list is a fresh copy, safe to mutate."""
    if not s:
        return []
    return list(_ann_to_coords_cached(s))


def chips_json(coords_with_paths: list[dict]) -> str:
    return json.dumps(coords_with_paths, separators=(",", ":"))
