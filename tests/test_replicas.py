"""Replicated control plane (tputopo.extender.replicas): racing extender
shards, CAS-reconciled binds with classified conflicts, claim
arbitration, recover() adopting peer binds, deterministic replicated sim
runs, and the server-mode load rig."""

import json

import pytest

from tests.cluster import build_cluster
from tputopo.extender import ExtenderConfig, ExtenderScheduler
from tputopo.extender.replicas import (DEFAULT_REPLICAS, LoadGenerator,
                                       ReplicaSet, WakeSchedule,
                                       start_replica_servers)
from tputopo.extender.scheduler import BindError
from tputopo.extender.state import ClusterState
from tputopo.k8s import make_pod
from tputopo.k8s import objects as ko
from tputopo.obs import Tracer
from tputopo.sim.engine import run_trace, stage_nodes
from tputopo.sim.report import SCHEMA_REPLICAS, SCHEMA_WATERMARK
from tputopo.sim.trace import TraceConfig

GANG = "tpu.dev/gang-id"
SIZE = "tpu.dev/gang-size"


class SetClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def sleep(self, dt: float) -> None:
        self.t += max(0.0, dt)


def _replica_sched(api, clock, rid: str, tracer=None) -> ExtenderScheduler:
    """A sim-shaped replica shard: informer-less bind_from_cache with
    shared_writers (claim arbitration on, single-owner folds off)."""
    return ExtenderScheduler(
        api, ExtenderConfig(state_cache_s=1e12, bind_from_cache=True,
                            shared_writers=True, replica_id=rid),
        clock=clock, tracer=tracer)


def _canon(report: dict) -> str:
    r = dict(report)
    r.pop("throughput", None)
    r.pop("phase_wall", None)
    return json.dumps(r, sort_keys=True)


# ---- WakeSchedule / ReplicaSet construction ---------------------------------


def test_wake_schedule_rr_and_weighted_are_deterministic():
    rr = WakeSchedule(3, seed=0, mode="rr")
    assert [rr.next() for _ in range(7)] == [0, 1, 2, 0, 1, 2, 0]
    a = WakeSchedule(4, seed=7, mode="weighted")
    b = WakeSchedule(4, seed=7, mode="weighted")
    seq = [a.next() for _ in range(64)]
    assert seq == [b.next() for _ in range(64)]
    assert set(seq) == {0, 1, 2, 3}  # every replica gets wakes
    c = WakeSchedule(4, seed=8, mode="weighted")
    assert seq != [c.next() for _ in range(64)]
    # Skewed weights skew the draw toward the heavy replica.
    w = WakeSchedule(2, seed=0, mode="weighted", weights=[9.0, 1.0])
    draws = [w.next() for _ in range(200)]
    assert draws.count(0) > 150
    with pytest.raises(ValueError):
        WakeSchedule(2, mode="nope")
    with pytest.raises(ValueError):
        WakeSchedule(2, mode="weighted", weights=[1.0])


def test_replica_set_asserts_ownership_at_construction():
    """The single-owner refusal: a shard still in in-place-fold mode (or
    without shared_writers at all) is rejected outright — racing writers
    plus in-place folds silently corrupt state."""
    api, _ = build_cluster()
    clock = SetClock()
    unshared = ExtenderScheduler(
        api, ExtenderConfig(state_cache_s=1e12, bind_from_cache=True),
        clock=clock)
    assert unshared._single_owner  # the sim engine's sole-writer mode
    with pytest.raises(ValueError, match="shared_writers"):
        ReplicaSet([unshared], clock=clock)
    ok = _replica_sched(api, clock, "r0")
    assert not ok._single_owner  # shared_writers downgrades to COW
    ReplicaSet([ok], clock=clock)  # constructs fine


def test_shared_writers_bind_publishes_cow_not_inplace():
    """satellite: bind_from_cache's in-place fold must downgrade to
    copy-on-write under shared_writers — the old cached state object
    stays untouched after a bind."""
    api, _ = build_cluster()
    clock = SetClock(10.0)
    sched = _replica_sched(api, clock, "r0")
    api.create("pods", make_pod("p1", chips=2))
    pod = api.get("pods", "p1", "default")
    nodes = ["node-0", "node-1", "node-2", "node-3"]
    sched.sort(pod, nodes)  # warm the cache
    state0 = sched._cached_state
    free0 = {sid: dom.allocator.free_mask
             for sid, dom in state0.domains.items()}
    sched.bind("p1", "default", "node-0")
    assert sched._cached_state is not state0  # replaced, not mutated
    assert {sid: dom.allocator.free_mask
            for sid, dom in state0.domains.items()} == free0
    assert sched.metrics.counters["bind_state_delta"] == 1
    # The bound-by stamp rides every committed bind of an identified
    # replica.
    assert api.get("pods", "p1", "default")["metadata"]["annotations"][
        ko.ANN_BOUND_BY] == "r0"


# ---- crafted races ----------------------------------------------------------


def test_two_replica_race_exactly_one_wins_loser_classified():
    """Two shards plan the same chips from equally fresh views, then race
    the bind: exactly one claim survives, the loser retreats with a
    classified Conflict, and its explain records the cause."""
    api, _ = build_cluster()
    clock = SetClock(100.0)
    a = _replica_sched(api, clock, "r0")
    tracer = Tracer(capacity=8, clock=clock)
    b = _replica_sched(api, clock, "r1", tracer=tracer)
    api.create("pods", make_pod("pa", chips=4))
    api.create("pods", make_pod("pb", chips=4))
    nodes = ["node-0", "node-1", "node-2", "node-3"]
    best_a = max(a.sort(api.get("pods", "pa", "default"), nodes),
                 key=lambda s: (s["Score"], s["Host"]))
    best_b = max(b.sort(api.get("pods", "pb", "default"), nodes),
                 key=lambda s: (s["Score"], s["Host"]))
    assert best_a["Host"] == best_b["Host"]  # same empty-fleet view
    a.bind("pa", "default", best_a["Host"])
    # B's cached view predates A's bind — same-instant race (the clock
    # never moved): the loser classifies it lost_race.
    with pytest.raises(BindError) as ei:
        b.bind("pb", "default", best_b["Host"])
    assert ei.value.reason == "conflict"
    assert ei.value.cause == "lost_race"
    assert b.metrics.counters["replica_bind_lost_race"] == 1
    assert b.metrics.counters["bind_conflicts"] == 1
    ex = tracer.last_explain
    assert ex["conflict"]["cause"] == "lost_race"
    assert ex["conflict"]["winner"] == "default/pa"
    # Exactly one claim survives: the winner's annotations are intact,
    # the loser's were wiped in the retreat, and API truth carries no
    # overlapping claims.
    pa = api.get("pods", "pa", "default")["metadata"]["annotations"]
    pb = api.get("pods", "pb", "default")["metadata"]["annotations"]
    assert pa.get(ko.ANN_GROUP) and pa.get(ko.ANN_BOUND_BY) == "r0"
    assert ko.ANN_GROUP not in pb
    assert ClusterState(api, clock=clock).sync().conflicts == []


def test_stale_cache_race_between_gangs_classified_stale():
    """The crafted gang race: replica A places gang ``g`` whole; replica
    B — whose cached plan predates A's binds — planned gang ``h`` onto
    the same host box and races its first member in.  B must lose with
    cause ``stale_cache`` (the winning claim is older than B's attempt),
    the gang stays un-double-booked, and B's NEXT attempt — from the
    dropped-then-resynced view — places ``h`` cleanly on the free box."""
    api, _ = build_cluster()
    clock = SetClock(50.0)
    a = _replica_sched(api, clock, "r0")
    tracer = Tracer(capacity=8, clock=clock)
    b = _replica_sched(api, clock, "r1", tracer=tracer)
    for gang in ("g", "h"):
        labels = {GANG: gang, SIZE: "2"}
        for m in range(2):
            api.create("pods", make_pod(f"{gang}-{m}", chips=4,
                                        labels=labels))
    nodes = ["node-0", "node-1", "node-2", "node-3"]
    # Both replicas plan their gang against the same EMPTY fleet: the
    # contiguous-host-box preference sends both to the same box.
    sa = a.sort(api.get("pods", "g-0", "default"), nodes)
    ga = max(sa, key=lambda s: (s["Score"], s["Host"]))["Host"]
    sb = b.sort(api.get("pods", "h-0", "default"), nodes)
    hb = max(sb, key=lambda s: (s["Score"], s["Host"]))["Host"]
    assert ga == hb  # identical views -> identical first-member winner
    a.bind("g-0", "default", ga)
    a.bind("g-1", "default",
           max(a.sort(api.get("pods", "g-1", "default"), nodes),
               key=lambda s: (s["Score"], s["Host"]))["Host"])
    clock.t = 51.0  # B's attempt happens AFTER A's claims landed
    with pytest.raises(BindError) as ei:
        b.bind("h-0", "default", hb)
    assert ei.value.reason == "conflict"
    assert ei.value.cause == "stale_cache"
    assert b.metrics.counters["replica_stale_cache_aborts"] == 1
    ex = tracer.last_explain
    assert ex["conflict"]["cause"] == "stale_cache"
    assert ex["conflict"]["winner"].startswith("default/g-")
    # Exactly one gang holds the contested chips; nothing overlaps.
    h0 = api.get("pods", "h-0", "default")["metadata"]["annotations"]
    assert ko.ANN_GROUP not in h0
    assert ClusterState(api, clock=clock).sync().conflicts == []
    # The loser's pod sits bound-but-unclaimed (burned) until the job
    # controller recreates it — model that, then the retry re-syncs from
    # the dropped view and places gang h whole on the remaining nodes.
    api.delete("pods", "h-0", "default")
    api.create("pods", make_pod("h-0", chips=4,
                                labels={GANG: "h", SIZE: "2"}))
    b.invalidate_cached_state()
    for m in range(2):
        d = b.bind(f"h-{m}", "default",
                   max(b.sort(api.get("pods", f"h-{m}", "default"), nodes),
                       key=lambda s: (s["Score"], s["Host"]))["Host"])
        assert d["gang"] == "h"
    state = ClusterState(api, clock=clock).sync()
    assert state.conflicts == []
    assert sum(len(dm.assignments) for dm in state.domains.values()) == 4


def test_injected_cas_conflict_classifies_ambiguous_not_lost_race():
    """Review regression: a conflicting write that applied NOTHING (the
    chaos layer's injected CAS 409 — shared_writers always arms it by
    passing expect_version) leaves no surviving claim; calling that
    'lost_race' would pollute the taxonomy with phantom peers."""
    from tputopo.chaos import ChaosApi, FaultPlan

    api, _ = build_cluster()
    clock = SetClock(5.0)
    chaos = ChaosApi(api, FaultPlan(
        0, "api-flake", conflict_prob=1.0, unavailable_prob=0.0,
        timeout_prob=0.0, ambiguous_timeout_prob=0.0, crash_prob=0.0,
        node_flaps=0))
    sched = ExtenderScheduler(
        chaos, ExtenderConfig(state_cache_s=1e12, bind_from_cache=True,
                              shared_writers=True, replica_id="r0"),
        clock=clock)
    api.create("pods", make_pod("p1", chips=2))
    with pytest.raises(BindError) as ei:
        sched.bind("p1", "default", "node-0")
    assert ei.value.reason == "conflict"
    assert ei.value.cause == "ambiguous_timeout"
    assert sched.metrics.counters["replica_conflict_ambiguous"] == 1
    assert "replica_bind_lost_race" not in sched.metrics.counters
    # Nothing applied: the pod is untouched and a later attempt (the
    # injected streak capped) binds cleanly.
    p1 = api.get("pods", "p1", "default")
    assert not p1["spec"].get("nodeName")


def test_gc_release_wipes_bound_by_stamp():
    """Review regression: the TTL GC's release is the backstop for a
    failed retreat wipe — it must clear tpu.dev/bound-by with the claim,
    or a released pod reads as still-owned by a replica."""
    from tputopo.extender.gc import AssumptionGC

    api, _ = build_cluster()
    clock = SetClock(0.0)
    sched = _replica_sched(api, clock, "r0")
    api.create("pods", make_pod("ghost", chips=2))
    nodes = ["node-0", "node-1", "node-2", "node-3"]
    node = max(sched.sort(api.get("pods", "ghost", "default"), nodes),
               key=lambda s: (s["Score"], s["Host"]))["Host"]
    sched.bind("ghost", "default", node)
    anns = api.get("pods", "ghost", "default")["metadata"]["annotations"]
    assert anns[ko.ANN_BOUND_BY] == "r0"
    clock.t = 1000.0  # past the TTL, never confirmed
    gc = AssumptionGC(api, assume_ttl_s=60.0, clock=clock)
    assert gc.sweep() == ["default/ghost"]
    anns = api.get("pods", "ghost", "default")["metadata"]["annotations"]
    assert ko.ANN_GROUP not in anns
    assert ko.ANN_BOUND_BY not in anns


def test_claim_check_ignores_expired_assumptions():
    """An expired unconfirmed claim is NOT occupancy (sync's TTL rule):
    the claim check must not retreat before a corpse the GC will wipe —
    otherwise replicas stall on placements a single scheduler makes."""
    api, _ = build_cluster()
    clock = SetClock(0.0)
    a = _replica_sched(api, clock, "r0")
    api.create("pods", make_pod("ghost", chips=4))
    nodes = ["node-0", "node-1", "node-2", "node-3"]
    node = max(a.sort(api.get("pods", "ghost", "default"), nodes),
               key=lambda s: (s["Score"], s["Host"]))["Host"]
    a.bind("ghost", "default", node)  # assumed at t=0, never confirmed
    clock.t = 1000.0  # far past the 60 s TTL
    b = _replica_sched(api, clock, "r1")
    api.create("pods", make_pod("fresh", chips=4))
    d = b.bind("fresh", "default", node)  # same node, same chips
    assert d["node"] == node
    assert "bind_conflicts" not in b.metrics.counters


# ---- recover() across replicas ----------------------------------------------


def test_recover_adopts_gang_bound_by_peer():
    """A replica's recover() completing an in-flight gang whose bound
    members a DIFFERENT replica committed counts the adoption — the
    all-or-nothing rule is cluster-wide, not per-replica."""
    api, _ = build_cluster()
    clock = SetClock(10.0)
    a = _replica_sched(api, clock, "r0")
    labels = {GANG: "g", SIZE: "2"}
    api.create("pods", make_pod("g-0", chips=4, labels=labels))
    api.create("pods", make_pod("g-1", chips=4, labels=labels))
    nodes = ["node-0", "node-1", "node-2", "node-3"]
    node0 = max(a.sort(api.get("pods", "g-0", "default"), nodes),
                key=lambda s: (s["Score"], s["Host"]))["Host"]
    a.bind("g-0", "default", node0)
    # Replica r1 restarts (crash) and reconciles the half-bound gang.
    b = _replica_sched(api, clock, "r1")
    outcome = b.recover()
    assert outcome["completed"] == ["default/g"]
    assert b.metrics.counters["recover_foreign_bind_adopted"] == 1
    anns0 = api.get("pods", "g-0", "default")["metadata"]["annotations"]
    anns1 = api.get("pods", "g-1", "default")["metadata"]["annotations"]
    assert anns0[ko.ANN_BOUND_BY] == "r0"  # the peer's bind, adopted as-is
    assert anns1[ko.ANN_BOUND_BY] == "r1"  # completed by the recoverer
    for m in range(2):
        assert api.get("pods", f"g-{m}",
                       "default")["spec"].get("nodeName")


def test_recover_own_gang_counts_no_adoption():
    api, _ = build_cluster()
    clock = SetClock(10.0)
    a = _replica_sched(api, clock, "r0")
    labels = {GANG: "g", SIZE: "2"}
    api.create("pods", make_pod("g-0", chips=4, labels=labels))
    api.create("pods", make_pod("g-1", chips=4, labels=labels))
    nodes = ["node-0", "node-1", "node-2", "node-3"]
    node0 = max(a.sort(api.get("pods", "g-0", "default"), nodes),
                key=lambda s: (s["Score"], s["Host"]))["Host"]
    a.bind("g-0", "default", node0)
    # The SAME replica identity restarts: its own binds are not foreign.
    a2 = _replica_sched(api, clock, "r0")
    outcome = a2.recover()
    assert outcome["completed"] == ["default/g"]
    assert "recover_foreign_bind_adopted" not in a2.metrics.counters


def test_release_wipes_bound_by_stamp():
    """A released gang member must not read as still-owned: the wipe
    clears ANN_BOUND_BY with the claim."""
    api, _ = build_cluster()
    clock = SetClock(10.0)
    a = _replica_sched(api, clock, "r0")
    labels = {GANG: "g", SIZE: "2"}
    api.create("pods", make_pod("g-0", chips=4, labels=labels))
    api.create("pods", make_pod("g-1", chips=4, labels=labels))
    nodes = ["node-0", "node-1", "node-2", "node-3"]
    node0 = max(a.sort(api.get("pods", "g-0", "default"), nodes),
                key=lambda s: (s["Score"], s["Host"]))["Host"]
    a.bind("g-0", "default", node0)
    # Capacity for the rest vanishes -> recover() must release.
    for n in nodes:
        if n != node0:
            api.delete("nodes", n)
    b = _replica_sched(api, clock, "r1")
    outcome = b.recover()
    assert outcome["released"] == ["default/g"]
    anns0 = api.get("pods", "g-0", "default")["metadata"]["annotations"]
    assert ko.ANN_GROUP not in anns0
    assert ko.ANN_BOUND_BY not in anns0


# ---- replicated sim runs ----------------------------------------------------


def _cfg(**kw):
    kw.setdefault("seed", 0)
    kw.setdefault("nodes", 16)
    kw.setdefault("arrivals", 60)
    return TraceConfig(**kw)


@pytest.mark.parametrize("count", [2, 4])
def test_replicated_sim_runs_byte_identical(count):
    cfg = _cfg()
    ra = run_trace(cfg, ["ici", "naive"], replicas={"count": count})
    rb = run_trace(cfg, ["ici", "naive"], replicas={"count": count})
    rj = run_trace(cfg, ["ici", "naive"], replicas={"count": count},
                   jobs=2)
    assert _canon(ra) == _canon(rb) == _canon(rj)
    assert ra["schema"] == SCHEMA_REPLICAS
    assert ra["engine"]["replicas"]["count"] == count
    blk = ra["policies"]["ici"]["replicas"]
    assert blk["count"] == count
    assert len(blk["wakes"]) == count and sum(blk["wakes"]) > 0
    assert set(blk["conflicts_by_cause"]) == {"lost_race", "stale_cache",
                                              "ambiguous_timeout"}
    assert blk["bind_conflicts"] == sum(blk["conflicts_by_cause"].values())
    # Baselines stay unreplicated comparators.
    assert "replicas" not in ra["policies"]["naive"]
    # The race taxonomy reaches the scheduler counter block too (the
    # keep-list registration) whenever conflicts occurred.
    if blk["bind_conflicts"]:
        sched = ra["policies"]["ici"]["scheduler"]
        assert (sched.get("replica_bind_lost_race", 0)
                + sched.get("replica_stale_cache_aborts", 0)
                + sched.get("replica_conflict_ambiguous", 0)
                ) == blk["bind_conflicts"]
    # Sharding must not lose jobs even fault-free: every arrival is
    # terminal or still queued.
    jobs = ra["policies"]["ici"]["jobs"]
    assert jobs["arrived"] == (jobs["completed"] + jobs["ghost_reclaimed"]
                               + jobs["unplaced_at_end"])


def test_replicas_one_and_absent_are_byte_identical():
    cfg = _cfg()
    off = run_trace(cfg, ["ici"])
    one = run_trace(cfg, ["ici"], replicas={"count": 1})
    assert _canon(off) == _canon(one)
    assert off["schema"] == SCHEMA_WATERMARK
    assert "replicas" not in off["policies"]["ici"]
    assert "replicas" not in off["engine"]


def test_wake_schedule_affinity_pins_keys_without_draining_stream():
    """A keyed wake under affinity goes to its stable crc32 shard and
    does NOT consume the seeded schedule stream; keyless wakes (and
    affinity-off schedules) draw exactly the pre-affinity sequence."""
    from tputopo.extender.replicas import affinity_shard

    aff = WakeSchedule(4, seed=0, mode="rr", affinity=True)
    plain = WakeSchedule(4, seed=0, mode="rr")
    assert aff.next_for("job-007") == affinity_shard("job-007", 4)
    assert aff.next_for("job-007") == aff.next_for("job-007")  # stable
    # The rr stream is untouched by the keyed draws above.
    assert [aff.next_for(None) for _ in range(4)] == [0, 1, 2, 3]
    # Affinity OFF ignores keys entirely — byte-identical scheduling.
    assert [plain.next_for("job-007"), plain.next_for("x")] == [0, 1]
    assert "affinity" not in plain.describe()
    assert aff.describe()["affinity"] is True


def test_replica_affinity_sim_deterministic_and_schema_additive():
    """--replica-affinity: byte-deterministic incl. --jobs 2, marker
    keys present only when ON, and the conflict taxonomy still sums."""
    cfg = _cfg()
    knobs = {"count": 4, "affinity": True}
    ra = run_trace(cfg, ["ici"], replicas=knobs)
    rj = run_trace(cfg, ["ici"], replicas=knobs, jobs=2)
    assert _canon(ra) == _canon(rj)
    assert ra["schema"] == SCHEMA_REPLICAS
    assert ra["engine"]["replicas"]["affinity"] is True
    blk = ra["policies"]["ici"]["replicas"]
    assert blk["schedule"]["affinity"] is True
    assert blk["bind_conflicts"] == sum(blk["conflicts_by_cause"].values())
    jobs = ra["policies"]["ici"]["jobs"]
    assert jobs["arrived"] == (jobs["completed"] + jobs["ghost_reclaimed"]
                               + jobs["unplaced_at_end"])
    # Affinity-off runs carry neither marker — the v6 bytes stay pinned.
    off = run_trace(cfg, ["ici"], replicas={"count": 4})
    assert "affinity" not in off["engine"]["replicas"]
    assert "affinity" not in off["policies"]["ici"]["replicas"]["schedule"]
    # The point of the feature: hash-sharding the queue must not RAISE
    # the conflict count on the standard small trace (it cut it 58 -> 44
    # at the time of writing; pin the direction, not the figure).
    assert (blk["bind_conflicts"]
            <= off["policies"]["ici"]["replicas"]["bind_conflicts"])


def test_load_generator_affinity_routes_binds_to_hash_shard():
    """Behavioral pin for the _worker start-shard routing: driven with
    concurrency=1 (no races, so no conflict retries rotate off-shard),
    EVERY bound pod's tpu.dev/bound-by must be its crc32 hash shard —
    a regression to seq-rotation binds ~half the pods elsewhere.  The
    run record carries the replica_affinity marker; the default stays
    unmarked."""
    from tputopo.extender.replicas import affinity_shard

    api, node_objs, _ = stage_nodes(TraceConfig(seed=0, nodes=16,
                                                arrivals=1))
    node_names = sorted(n["metadata"]["name"] for n in node_objs)
    pods = [make_pod(f"load-{i:03d}", chips=1) for i in range(12)]
    api.create_many("pods", pods)
    with start_replica_servers(api, 2) as servers:
        gen = LoadGenerator(servers.urls, node_names, concurrency=1,
                            replica_affinity=True)
        res = gen.run(pods, sort_rounds=0)
    assert res["replica_affinity"] is True
    assert res["binds_ok"] == len(pods) and res["bind_conflicts"] == 0, res
    shards = {affinity_shard(p["metadata"]["name"], 2) for p in pods}
    assert shards == {0, 1}  # the keys actually exercise both replicas
    for pod in api.list("pods"):
        anns = pod["metadata"].get("annotations", {})
        assert anns.get(ko.ANN_GROUP), pod["metadata"]["name"]
        want = f"r{affinity_shard(pod['metadata']['name'], 2)}"
        assert anns.get(ko.ANN_BOUND_BY) == want, (
            pod["metadata"]["name"], anns.get(ko.ANN_BOUND_BY), want)
    assert not LoadGenerator(servers.urls, node_names).replica_affinity


def test_chaos_replica_crashes_hold_invariants_and_determinism():
    """The acceptance gate: replicas crash-restarting mid-gang-bind under
    an API-fault profile end with ZERO invariant violations and zero lost
    jobs, byte-deterministically."""
    cfg = _cfg(arrivals=40)
    for profile in ("api-flake", "replica-storm"):
        ra = run_trace(cfg, ["ici"], chaos=profile,
                       replicas={"count": 4})
        rb = run_trace(cfg, ["ici"], chaos=profile,
                       replicas={"count": 4}, jobs=1)
        assert _canon(ra) == _canon(rb)
        rec = ra["policies"]["ici"]
        c = rec["chaos"]
        assert c["invariants"]["ok"], (profile,
                                       c["invariants"]["violations"])
        jobs = rec["jobs"]
        assert jobs["arrived"] == (jobs["completed"]
                                   + jobs["ghost_reclaimed"]
                                   + jobs["unplaced_at_end"]), profile
    # The storm profile actually exercises per-replica crash-restarts.
    storm = run_trace(cfg, ["ici"], chaos="replica-storm",
                      replicas={"count": 4})
    blk = storm["policies"]["ici"]["replicas"]
    assert sum(blk["crash_restarts"]) >= 1


# ---- server mode ------------------------------------------------------------


def test_server_mode_replicas_race_without_double_booking():
    """Real concurrent HTTP replicas + the load generator: every pod ends
    bound-with-claim, burned (claim-race loser), or errored — and API
    truth carries zero overlapping claims whatever the interleaving."""
    api, node_objs, _ = stage_nodes(TraceConfig(seed=0, nodes=16,
                                                arrivals=1))
    node_names = sorted(n["metadata"]["name"] for n in node_objs)
    pods = [make_pod(f"load-{i:03d}", chips=1) for i in range(24)]
    api.create_many("pods", pods)
    with start_replica_servers(api, 2) as servers:
        assert len(servers.urls) == 2
        for s in servers.schedulers:
            assert s.config.shared_writers and not s._single_owner
        gen = LoadGenerator(servers.urls, node_names, concurrency=4)
        res = gen.run(pods, sort_rounds=1)
    assert res["sort_storm"]["requests"] == 24
    assert res["transport_errors"] == 0
    assert res["binds_ok"] > 0
    accounted = (res["binds_ok"] + res["pods_burned"]
                 + res["bind_errors"] + res["infeasible"])
    assert accounted == len(pods), res
    state = ClusterState(api).sync()
    assert state.conflicts == []
    claimed = sum(len(d.assignments) for d in state.domains.values())
    assert claimed == res["binds_ok"]
    # Every surviving claim carries its binder's identity.
    for pod in api.list("pods"):
        anns = pod["metadata"].get("annotations", {})
        if anns.get(ko.ANN_GROUP):
            assert anns.get(ko.ANN_BOUND_BY) in ("r0", "r1")


def test_default_replica_knobs_shape():
    assert set(DEFAULT_REPLICAS) == {"count", "watch_delay_s", "schedule"}
    assert DEFAULT_REPLICAS["count"] == 1
