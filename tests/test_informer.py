"""List+watch informer cache (VERDICT r1 #6/#10): steady-state sort does
zero API-server LISTs, watch events drive the cache (add/patch/delete),
Gone triggers a relist, and the real REST client leg works against the
watch-capable HTTP mock end-to-end."""

import time

import pytest

from tests.cluster import build_cluster
from tests.k8s_mock import MockKubeApi
from tputopo.extender import ExtenderConfig, ExtenderScheduler
from tputopo.k8s import FakeApiServer, make_pod
from tputopo.k8s import objects as ko
from tputopo.k8s.client import KubeApiClient
from tputopo.k8s.fakeapi import Gone
from tputopo.k8s.informer import Informer


def wait_until(pred, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def test_informer_mirrors_add_patch_delete():
    api = FakeApiServer()
    api.create("nodes", ko.make_node("n1", chips=4))
    inf = Informer(api, watch_timeout_s=1.0).start()
    try:
        assert inf.wait_synced(10)
        assert [n["metadata"]["name"] for n in inf.list("nodes")] == ["n1"]
        assert inf.metrics["lists"] == 2  # one initial list per kind

        api.create("pods", make_pod("p1", chips=2))
        assert wait_until(lambda: len(inf.list("pods")) == 1)
        api.patch_annotations("pods", "p1", {"x": "y"}, namespace="default")
        assert wait_until(lambda: inf.get(
            "pods", "p1", "default")["metadata"]["annotations"].get("x") == "y")
        api.delete("pods", "p1", "default")
        assert wait_until(lambda: not inf.list("pods"))
        # All of that arrived via watch, not relists.
        assert inf.metrics["lists"] == 2
        assert inf.metrics["watch_events"] >= 3
    finally:
        inf.stop()


def test_fakeapi_watch_gone_on_expired_version():
    from tputopo.k8s import fakeapi

    api = FakeApiServer()
    # Generate > window events:
    api.create("nodes", ko.make_node("seed"))
    for i in range(fakeapi._WATCH_WINDOW + 5):
        api.patch_annotations("nodes", "seed", {"i": str(i)})
    with pytest.raises(Gone):
        list(api.watch("nodes", "1", timeout_s=0.1))


def test_informer_relists_after_gone():
    api = FakeApiServer()
    api.create("nodes", ko.make_node("n1", chips=4))

    class GoneOnce:
        def __init__(self, inner):
            self.inner = inner
            self.fired = False

        def list_with_version(self, kind):
            return self.inner.list_with_version(kind)

        def watch(self, kind, rv, timeout_s):
            if kind == "nodes" and not self.fired:
                self.fired = True
                raise Gone("synthetic window expiry")
            yield from self.inner.watch(kind, rv, timeout_s=timeout_s)

    inf = Informer(GoneOnce(api), watch_timeout_s=0.5,
                   relist_backoff_s=0.05).start()
    try:
        assert inf.wait_synced(10)
        api.patch_annotations("nodes", "n1", {"after": "gone"})
        assert wait_until(lambda: inf.get(
            "nodes", "n1")["metadata"]["annotations"].get("after") == "gone")
        assert inf.metrics["relists"] >= 1
    finally:
        inf.stop()


class CountingApi(FakeApiServer):
    def __init__(self):
        super().__init__()
        self.list_calls = 0

    def list(self, *a, **kw):
        self.list_calls += 1
        return super().list(*a, **kw)

    def list_with_version(self, kind):
        self.list_calls += 1
        return super().list_with_version(kind)


def test_sort_zero_lists_in_steady_state():
    """The nodeCacheCapable promise (design.md:102): after the informer
    syncs, sort verbs hit the API server zero times."""
    api = CountingApi()
    build_cluster(api=api)
    inf = Informer(api, watch_timeout_s=1.0).start()
    sched = ExtenderScheduler(api, ExtenderConfig(), informer=inf)
    try:
        assert inf.wait_synced(10)
        api.create("pods", make_pod("p", chips=4))
        assert wait_until(lambda: inf.list("pods"))
        baseline = api.list_calls
        pod = api.get("pods", "p", "default")
        for _ in range(25):
            scores = sched.sort(pod, [f"node-{i}" for i in range(4)])
            assert max(s["Score"] for s in scores) > 0
        assert api.list_calls == baseline, "sort must not LIST the API server"
        # One state build for the burst, the rest served from the rv-keyed
        # cache (the informer mirror did not change between sorts).
        assert sched.metrics.counters["state_from_informer"] == 1
        assert sched.metrics.counters["state_cache_hits"] == 24
        # bind serves from the mirror too (writes stay authoritative via
        # the API server's CAS): zero LISTs, and it publishes its own
        # delta so the next sort needs no rebuild either.
        decision = sched.bind("p", "default", "node-0")
        assert decision["node"] == "node-0"
        assert api.list_calls == baseline, "bind must not LIST the API server"
        assert sched.metrics.counters.get("bind_state_delta", 0) == 1
        # The bind's own watch echo must NOT invalidate the delta-applied
        # state: the next sort is a cache hit, not a rebuild.
        assert wait_until(lambda: inf.get(
            "pods", "p", "default")["spec"].get("nodeName") == "node-0")
        scores = sched.sort(pod, [f"node-{i}" for i in range(4)])
        assert sched.metrics.counters["state_from_informer"] == 1
        # ...and that state reflects the bind: the pod's 4 chips are taken,
        # so an identical request now scores 0 everywhere on this 4-chip-
        # per-node cluster node-0 sat on.
        assert all(s["Score"] == 0 for s in scores
                   if s["Host"] == "node-0"), scores
    finally:
        inf.stop()


def test_gang_sort_zero_lists_in_steady_state():
    api = CountingApi()
    build_cluster(api=api)
    inf = Informer(api, watch_timeout_s=1.0).start()
    sched = ExtenderScheduler(api, ExtenderConfig(), informer=inf)
    try:
        assert inf.wait_synced(10)
        for i in range(2):
            api.create("pods", make_pod(f"g-{i}", chips=4, labels={
                "tpu.dev/gang-id": "g", "tpu.dev/gang-size": "2"}))
        assert wait_until(lambda: len(inf.list("pods")) == 2)
        baseline = api.list_calls
        pod = api.get("pods", "g-0", "default")
        for _ in range(10):
            scores = sched.sort(pod, [f"node-{i}" for i in range(4)])
            assert max(s["Score"] for s in scores) > 0
        assert api.list_calls == baseline, \
            "gang sort (incl. member lookup) must not LIST the API server"
    finally:
        inf.stop()


def test_label_selector_pushdown_through_rest_client():
    with MockKubeApi() as mock:
        client = KubeApiClient(base_url=mock.base_url)
        mock.api.create("pods", make_pod("a", labels={"team": "x"}))
        mock.api.create("pods", make_pod("b", labels={"team": "y"}))
        got = client.list("pods", label_selector={"team": "x"})
        assert [p["metadata"]["name"] for p in got] == ["a"]


def test_end_to_end_schedule_through_watchful_rest_apiserver():
    """VERDICT r1 #10: one pod scheduled end-to-end through a non-fake
    (HTTP) apiserver with the informer watching it — sort from the cache,
    bind authoritative, handshake annotations land, cache converges."""
    with MockKubeApi() as mock:
        build_cluster(api=mock.api)  # plugins seed nodes via the fake core
        client = KubeApiClient(base_url=mock.base_url)
        inf = Informer(client, watch_timeout_s=2.0).start()
        sched = ExtenderScheduler(client, ExtenderConfig(), informer=inf)
        try:
            assert inf.wait_synced(10)
            client.create("pods", make_pod("job", chips=4))
            assert wait_until(lambda: inf.list("pods"))
            pod = client.get("pods", "job", "default")
            scores = sched.sort(pod, [f"node-{i}" for i in range(4)])
            assert sched.metrics.counters["state_from_informer"] >= 1
            best = max(scores, key=lambda s: s["Score"])
            assert best["Score"] > 0
            decision = sched.bind("job", "default", best["Host"])
            assert len(decision["chips"]) == 4
            fresh = client.get("pods", "job", "default")
            assert fresh["spec"]["nodeName"] == best["Host"]
            assert fresh["metadata"]["annotations"][ko.ANN_ASSIGNED] == "false"
            # The watch stream carries the bind back into the cache.
            assert wait_until(lambda: inf.get(
                "pods", "job", "default")["spec"].get("nodeName") == best["Host"])
        finally:
            inf.stop()


def test_bind_write_through_visible_without_watch():
    """The assume-cache leg: a sort issued IMMEDIATELY after a bind must
    plan against the bound state even if no watch event has been processed
    (kube-scheduler cache pattern).  Proven by freezing the watch threads:
    the informer is stopped after sync, so only bind's write-through
    observe() can update the mirror."""
    api = FakeApiServer()
    build_cluster(api=api, spec="v5p:2x2x1", workers=1)
    inf = Informer(api, watch_timeout_s=0.2).start()
    assert inf.wait_synced(10)
    inf.stop()  # freeze: watch can never deliver anything again
    sched = ExtenderScheduler(api, ExtenderConfig(), informer=inf)

    api.create("pods", make_pod("a", chips=2))
    api.create("pods", make_pod("b", chips=2))
    pod_a = api.get("pods", "a", "default")
    pod_b = api.get("pods", "b", "default")

    assert max(s["Score"] for s in sched.sort(pod_a, ["node-0"])) > 0
    da = sched.bind("a", "default", "node-0")
    assert sched.informer.metrics["observes"] >= 1
    # The 2x2 slice has 2 free chips left; sort for b must reflect that
    # (score from a half-used node), and bind b onto the OTHER pair.
    scores = sched.sort(pod_b, ["node-0"])
    assert max(s["Score"] for s in scores) > 0
    db = sched.bind("b", "default", "node-0")
    assert not (set(map(tuple, da["chips"])) & set(map(tuple, db["chips"]))), \
        "write-through failed: second sort/bind reused assigned chips"


def test_observe_newest_resource_version_wins():
    """A delayed watch event older than a write-through observe must not
    regress the mirror."""
    api = FakeApiServer()
    inf = Informer(api, kinds=("pods",), watch_timeout_s=0.2)
    new = {"metadata": {"name": "p", "namespace": "default",
                        "resourceVersion": "7",
                        "annotations": {"x": "new"}}}
    old_event = {"type": "MODIFIED", "rv": "3",
                 "object": {"metadata": {"name": "p", "namespace": "default",
                                         "resourceVersion": "3",
                                         "annotations": {"x": "old"}}}}
    inf._synced["pods"].set()
    inf.observe("pods", new)
    v1 = inf.version()
    inf._apply("pods", old_event)
    assert inf.get("pods", "p", "default")["metadata"]["annotations"]["x"] == "new"
    # And a NEWER event does land.
    inf._apply("pods", {"type": "MODIFIED", "rv": "9", "object": {
        "metadata": {"name": "p", "namespace": "default",
                     "resourceVersion": "9", "annotations": {"x": "newest"}}}})
    assert inf.get("pods", "p", "default")["metadata"]["annotations"]["x"] == "newest"
    assert inf.version() != v1  # observe/events both move the coherence token


def test_relist_preserves_newer_observed_objects():
    """A relist snapshot taken at rv M must not erase write-through
    observes newer than M (the bind-vs-relist race)."""
    api = FakeApiServer()
    inf = Informer(api, kinds=("pods",), watch_timeout_s=0.2)
    api.create("pods", make_pod("a", chips=1))
    inf._relist("pods")
    snap_rv = int(inf._rv["pods"])
    # Concurrent bind: newer object observed after the snapshot was taken.
    newer = {"metadata": {"name": "a", "namespace": "default",
                          "resourceVersion": str(snap_rv + 5),
                          "annotations": {"tpu.dev/assigned": "false"}}}
    fresh = {"metadata": {"name": "b", "namespace": "default",
                          "resourceVersion": str(snap_rv + 6)}}
    inf.observe("pods", newer)
    inf.observe("pods", fresh)  # created after the snapshot entirely
    # Replay a relist with the OLD snapshot rv (simulates the swap landing
    # after the observes): both observed objects must survive.
    items, _ = api.list_with_version("pods")
    api_list_with_version = api.list_with_version
    api.list_with_version = lambda kind: (items, str(snap_rv))
    try:
        inf._relist("pods")
    finally:
        api.list_with_version = api_list_with_version
    a = inf.get("pods", "a", "default")
    assert a["metadata"]["resourceVersion"] == str(snap_rv + 5), \
        "relist regressed an observed bind"
    assert inf.get("pods", "b", "default") is not None


def test_lagging_delete_does_not_remove_newer_incarnation():
    api = FakeApiServer()
    inf = Informer(api, kinds=("pods",), watch_timeout_s=0.2)
    inf._synced["pods"].set()
    new = {"metadata": {"name": "p", "namespace": "default",
                        "resourceVersion": "60"}}
    inf.observe("pods", new)
    # Lagging DELETE for the OLD incarnation (rv 50): must be ignored.
    inf._apply("pods", {"type": "DELETED", "rv": "50", "object": {
        "metadata": {"name": "p", "namespace": "default",
                     "resourceVersion": "50"}}})
    assert inf.get("pods", "p", "default") is not None
    # A DELETE at/after the mirror's version does land.
    inf._apply("pods", {"type": "DELETED", "rv": "61", "object": {
        "metadata": {"name": "p", "namespace": "default",
                     "resourceVersion": "61"}}})
    assert inf.list("pods") == []


def test_rvless_delete_is_unordered():
    """A DELETE whose object carries no parseable resourceVersion (rv 0)
    must not remove a strictly newer observed incarnation (ADVICE r2) —
    but still removes an entry whose version is equally unknown."""
    api = FakeApiServer()
    inf = Informer(api, kinds=("pods",), watch_timeout_s=0.2)
    inf._synced["pods"].set()
    inf.observe("pods", {"metadata": {"name": "p", "namespace": "default",
                                      "resourceVersion": "60"}})
    inf._apply("pods", {"type": "DELETED", "object": {
        "metadata": {"name": "p", "namespace": "default"}}})
    assert inf.get("pods", "p", "default") is not None, \
        "rv-less DELETE removed a newer observed object"
    assert inf.metrics["unordered_deletes_kept"] == 1
    # Both sides unversioned: the delete wins (can't order, honor intent).
    inf._store["pods"][("default", "q")] = {
        "metadata": {"name": "q", "namespace": "default"}}
    inf._apply("pods", {"type": "DELETED", "object": {
        "metadata": {"name": "q", "namespace": "default"}}})
    assert all(p["metadata"]["name"] != "q" for p in inf.list("pods"))


def test_watch_echo_of_observe_does_not_move_version_token():
    """The content-version contract the bind delta fast path relies on:
    the watch echo of an object the mirror already installed via
    write-through observe() (same resourceVersion) changes nothing, so
    the coherence token must not move — while a genuinely newer event,
    a delete, and an observe each move it by exactly one."""
    api = FakeApiServer()
    inf = Informer(api, kinds=("pods",), watch_timeout_s=0.2)
    inf._synced["pods"].set()
    v0 = inf.version()
    obj = {"metadata": {"name": "p", "namespace": "default",
                        "resourceVersion": "5"}}
    v1 = inf.observe("pods", obj)
    assert v1 != v0 and v1 == (str(int(v0[0]) + 1),)
    # Echo: same object, same rv, arriving through the watch.
    inf._apply("pods", {"type": "MODIFIED", "rv": "5", "object": dict(obj)})
    assert inf.version() == v1, "echo event invalidated derived state"
    # Re-observing the identical object is also a no-op.
    assert inf.observe("pods", obj) == v1
    # A genuinely newer event moves the token.
    inf._apply("pods", {"type": "MODIFIED", "rv": "6", "object": {
        "metadata": {"name": "p", "namespace": "default",
                     "resourceVersion": "6"}}})
    v2 = inf.version()
    assert v2 == (str(int(v1[0]) + 1),)
    # A removing delete moves it; a no-op delete does not.
    inf._apply("pods", {"type": "DELETED", "rv": "7", "object": {
        "metadata": {"name": "p", "namespace": "default",
                     "resourceVersion": "7"}}})
    v3 = inf.version()
    assert v3 == (str(int(v2[0]) + 1),)
    inf._apply("pods", {"type": "DELETED", "rv": "8", "object": {
        "metadata": {"name": "ghost", "namespace": "default",
                     "resourceVersion": "8"}}})
    assert inf.version() == v3


def test_bind_write_through_failure_forces_authoritative_path():
    """If a bind's mirror write-through fails, later binds must NOT plan
    from the (now incomplete) mirror — they fall back to authoritative
    API sync until the gap is repaired, so a double-book through the
    stale mirror is impossible (code-review r4)."""
    api = FakeApiServer()
    build_cluster(api=api, spec="v5p:2x2x1", workers=1)
    inf = Informer(api, watch_timeout_s=0.2).start()
    assert inf.wait_synced(10)
    inf.stop()  # freeze the watch: only write-through can update the mirror
    sched = ExtenderScheduler(api, ExtenderConfig(), informer=inf)

    api.create("pods", make_pod("a", chips=2))
    api.create("pods", make_pod("b", chips=2))

    # A real apiserver's binding subresource returns a Status, not the
    # pod — force that shape so bind must read the pod back, and fail
    # that read-back so the write-through cannot happen.
    real_bind_pod = api.bind_pod
    api.bind_pod = lambda *a, **kw: (real_bind_pod(*a, **kw),
                                     {"kind": "Status",
                                      "status": "Success"})[1]
    real_get = api.get
    calls = {"fail": True}

    def flaky_get(kind, name, namespace=None):
        if kind == "pods" and name == "a" and calls["fail"]:
            # First get (bind entry) must work; fail only the read-back.
            calls["n"] = calls.get("n", 0) + 1
            if calls["n"] > 1:
                calls["fail"] = False
                raise RuntimeError("transient 5xx")
        return real_get(kind, name, namespace)

    api.get = flaky_get
    da = sched.bind("a", "default", "node-0")
    api.get = real_get
    api.bind_pod = real_bind_pod
    assert sched.metrics.counters.get("bind_observe_errors", 0) == 1
    assert sched._unmirrored_binds, "failed write-through must be recorded"
    # The mirror is stale (watch frozen, observe failed) — but bind b must
    # still see a's chips as used, via the authoritative fallback.
    db = sched.bind("b", "default", "node-0")
    assert not (set(map(tuple, da["chips"])) & set(map(tuple, db["chips"]))), \
        "bind planned from the stale mirror and double-booked"
    # The repair leg ran during bind b and closed the gap.
    assert not sched._unmirrored_binds
    assert sched.metrics.counters.get("bind_write_through_repaired", 0) == 1


def test_assume_ttl_expiry_visible_under_sustained_bind_traffic():
    """Delta-published bind states must not postpone TTL-expiry judgment:
    the derived state's age is judged from its last full sync, so an
    unconfirmed assumption older than the TTL frees its chips within the
    5 s staleness bound even when binds keep the delta path hot
    (code-review r4)."""
    class Clock:
        def __init__(self, t): self.t = t
        def __call__(self): return self.t

    clock = Clock(1000.0)
    api = FakeApiServer()
    build_cluster(api=api, spec="v5p:2x2x4", workers=4, clock=clock)
    inf = Informer(api, watch_timeout_s=0.5).start()
    assert inf.wait_synced(10)
    sched = ExtenderScheduler(api, ExtenderConfig(assume_ttl_s=60.0),
                              informer=inf, clock=clock)
    try:
        # ghost binds but never confirms (no Allocate).
        api.create("pods", make_pod("ghost", chips=4))
        assert wait_until(lambda: inf.list("pods"))
        sched.bind("ghost", "default", "node-0")
        # Sustained bind traffic ON OTHER NODES: each tick advances the
        # clock and delta-publishes a bind; eventually the ghost's
        # assumption is past the TTL and node-0 (which the fillers never
        # touch) must become placeable again.
        for i in range(20):
            clock.t += 6.0  # 120 s total, well past TTL + staleness bound
            api.create("pods", make_pod(f"t-{i}", chips=2))
            assert wait_until(
                lambda: any(p["metadata"]["name"] == f"t-{i}"
                            for p in inf.list("pods")))
            scores = {s["Host"]: s["Score"]
                      for s in sched.sort(api.get("pods", f"t-{i}", "default"),
                                          [f"node-{n}" for n in range(1, 4)])}
            best = max(scores, key=lambda h: (scores[h], h))
            if scores[best] > 0:
                sched.bind(f"t-{i}", "default", best)
        pod = make_pod("reclaim", chips=4)
        api.create("pods", pod)
        assert wait_until(lambda: any(p["metadata"]["name"] == "reclaim"
                                      for p in inf.list("pods")))
        scores = {s["Host"]: s["Score"]
                  for s in sched.sort(pod, [f"node-{n}" for n in range(4)])}
        assert scores["node-0"] > 0, \
            "expired assumption stayed occupying under sustained binds"
    finally:
        inf.stop()
