"""Real Kubernetes API-server client (stdlib-only REST).

Drop-in for :class:`tputopo.k8s.fakeapi.FakeApiServer` — same method
surface (create/get/list/delete/patch_annotations/patch_labels/bind_pod,
NotFound/Conflict semantics) — so the extender, device plugin, and GC run
unchanged against a live cluster.  The durable-state story is exactly the
reference's (SURVEY.md §5.4): everything lives in object metadata on the
API server; this client is a transport, not a cache.

In-cluster wiring follows the standard conventions: service-account bearer
token + CA bundle from /var/run/secrets/kubernetes.io/serviceaccount, API
host from KUBERNETES_SERVICE_HOST/PORT.  Tests point ``base_url`` at a
plain-HTTP mock (tests/k8s_mock.py).

Optimistic concurrency: ``patch_annotations(expect_version=...)`` embeds
metadata.resourceVersion in the merge patch — the API server rejects a
stale version with 409, which surfaces as :class:`Conflict`, the same
signal the two-phase ASSUME/ASSIGNED handshake consumes in-memory.
"""

from __future__ import annotations

import json
import os
import random
import ssl
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable

from tputopo.k8s.fakeapi import Conflict, Gone, NotFound
from tputopo.k8s.retry import ApiTimeout, ApiUnavailable, RetryPolicy

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

#: HTTP statuses that mean "the server is fine to ask again" — surfaced
#: as :class:`ApiUnavailable` so every caller shares one transient
#: vocabulary (the fake API's chaos layer raises the same types).
_TRANSIENT_HTTP = (429, 500, 502, 503, 504)

#: Methods the transport itself retries: idempotent by HTTP semantics
#: (GET/DELETE) or by payload (merge-PATCH of the same content; a CAS
#: PATCH whose first attempt applied conflicts on replay, which the verb
#: layer resolves).  POST (create/bind) is NOT transport-retried — its
#: ambiguity is the caller's to reconcile (see the bind verb).
_RETRIED_METHODS = frozenset({"GET", "DELETE", "PATCH", "PUT"})


class KubeApiClient:
    def __init__(self, base_url: str | None = None, token: str | None = None,
                 ca_path: str | None = None, timeout_s: float = 10.0,
                 retry: RetryPolicy | None = None) -> None:
        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            base_url = f"https://{host}:{port}"
        self.base_url = base_url.rstrip("/")
        if token is None:
            token_path = os.path.join(_SA_DIR, "token")
            if os.path.exists(token_path):
                with open(token_path) as f:
                    token = f.read().strip()
        self.token = token
        self.timeout_s = timeout_s
        # The transport default is deliberately TIGHT: one fast replay to
        # absorb a connection blip, deadline-capped at the socket timeout.
        # Callers above (scheduler `_api_call`, defrag, GC) wrap verbs in
        # their own RetryPolicy with per-verb deadlines; a loose transport
        # loop underneath would multiply attempts and let a single verb
        # call block for attempts x timeout_s, far past those deadlines.
        self.retry = retry if retry is not None else RetryPolicy(
            max_attempts=2, deadline_s=timeout_s)
        # Per-client entropy for backoff jitter: many extender replicas
        # must not retry a flapping apiserver in lockstep.
        self._retry_rng = random.Random()
        self._ctx: ssl.SSLContext | None = None
        if self.base_url.startswith("https"):
            ca = ca_path or os.path.join(_SA_DIR, "ca.crt")
            self._ctx = ssl.create_default_context(
                cafile=ca if os.path.exists(ca) else None)

    # ---- transport ---------------------------------------------------------

    def _request(self, method: str, path: str, body: dict | None = None,
                 content_type: str = "application/json") -> dict:
        """One HTTP round-trip with the shared retry discipline: transient
        statuses and timeouts become :class:`ApiUnavailable` /
        :class:`ApiTimeout`, and idempotent methods are retried with the
        jittered-backoff :class:`RetryPolicy` before the error escapes to
        the verb layer."""
        if method in _RETRIED_METHODS:
            return self.retry.call(self._request_once, method, path, body,
                                   content_type, rng=self._retry_rng)
        return self._request_once(method, path, body, content_type)

    def _request_once(self, method: str, path: str, body: dict | None = None,
                      content_type: str = "application/json") -> dict:
        url = self.base_url + path
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", content_type)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s,
                                        context=self._ctx) as resp:
                raw = resp.read()
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")[:500]
            if e.code == 404:
                raise NotFound(f"{method} {path}: {detail}") from None
            if e.code == 409:
                raise Conflict(f"{method} {path}: {detail}") from None
            if e.code in _TRANSIENT_HTTP:
                raise ApiUnavailable(
                    f"{method} {path} -> {e.code}: {detail}") from None
            raise RuntimeError(f"{method} {path} -> {e.code}: {detail}") from None
        except TimeoutError as e:  # socket.timeout — response never came
            raise ApiTimeout(f"{method} {path}: {e}") from None
        except urllib.error.URLError as e:
            # Connection refused / DNS / TLS reset — no response, so the
            # request did not apply; a timeout buried in the reason is
            # ambiguous and surfaces as such.
            if isinstance(getattr(e, "reason", None), TimeoutError):
                raise ApiTimeout(f"{method} {path}: {e.reason}") from None
            raise ApiUnavailable(f"{method} {path}: {e.reason}") from None
        return json.loads(raw) if raw else {}

    @staticmethod
    def _collection(kind: str, namespace: str | None) -> str:
        if kind == "nodes":
            return "/api/v1/nodes"
        if kind == "pods":
            if namespace is None:
                return "/api/v1/pods"  # cluster-wide list
            return f"/api/v1/namespaces/{namespace}/pods"
        raise ValueError(f"unsupported kind {kind!r}")

    def _object_path(self, kind: str, name: str, namespace: str | None) -> str:
        if kind == "nodes":
            return f"/api/v1/nodes/{name}"
        if kind == "pods":
            ns = namespace or "default"
            return f"/api/v1/namespaces/{ns}/pods/{name}"
        raise ValueError(f"unsupported kind {kind!r}")

    # ---- FakeApiServer-compatible surface ----------------------------------

    def create(self, kind: str, obj: dict) -> dict:
        md = obj["metadata"]
        ns = md.get("namespace") if kind == "pods" else None
        if kind == "pods":
            ns = ns or "default"
        return self._request("POST", self._collection(kind, ns), obj)

    def get(self, kind: str, name: str, namespace: str | None = None) -> dict:
        return self._request("GET", self._object_path(kind, name, namespace))

    def list(self, kind: str, selector: Callable[[dict], bool] | None = None,
             label_selector: dict[str, str] | None = None,
             chunk_limit: int = 500) -> list[dict]:
        out, _ = self._list_paged(kind, label_selector, chunk_limit)
        # K8s list items omit kind/apiVersion; metadata is intact, which is
        # all the framework's selectors and consumers read.
        if selector:
            out = [o for o in out if selector(o)]
        return sorted(out, key=lambda o: (o["metadata"].get("namespace", ""),
                                          o["metadata"]["name"]))

    def list_assignments(self) -> list[dict]:
        """Pods carrying the chip-group assignment annotation — the GC
        sweep's candidate listing.  A real apiserver has no annotation
        index (field selectors cannot reach annotations), so this is a
        client-side filtered LIST: the O(pods) cost lives here, at the
        REST boundary where it is unavoidable, while indexed backends
        (FakeApiServer) answer in O(assignments)."""
        from tputopo.k8s.objects import ANN_GROUP

        return self.list(
            "pods",
            lambda p: ANN_GROUP in (p["metadata"].get("annotations") or {}))

    def _list_paged(self, kind: str, label_selector: dict[str, str] | None,
                    chunk_limit: int) -> tuple[list[dict], str]:
        """Server-side selector push-down + apiserver chunking (limit /
        continue) — a cluster-wide pod list no longer transfers every pod
        when a label selector narrows it, and never in one giant response."""
        base = self._collection(kind, None)
        params = []
        if label_selector:
            sel = ",".join(f"{k}={v}" for k, v in sorted(label_selector.items()))
            params.append("labelSelector=" + urllib.parse.quote(sel))
        if chunk_limit:
            params.append(f"limit={chunk_limit}")
        items: list[dict] = []
        cont = None
        rv = ""
        while True:
            qs = list(params)
            if cont:
                qs.append("continue=" + urllib.parse.quote(cont))
            path = base + ("?" + "&".join(qs) if qs else "")
            resp = self._request("GET", path)
            items.extend(resp.get("items", []))
            meta = resp.get("metadata", {})
            rv = meta.get("resourceVersion", rv)
            cont = meta.get("continue")
            if not cont:
                return items, rv

    def list_with_version(self, kind: str) -> tuple[list[dict], str]:
        items, rv = self._list_paged(kind, None, 500)
        items.sort(key=lambda o: (o["metadata"].get("namespace", ""),
                                  o["metadata"]["name"]))
        return items, rv

    def watch(self, kind: str, resource_version: str,
              timeout_s: float = 30.0):
        """Stream watch events (``{"type", "object", "rv"}``) for ``kind``
        from ``resource_version``; returns when the server closes the
        stream at ``timeoutSeconds``.  HTTP 410 surfaces as
        :class:`~tputopo.k8s.fakeapi.Gone` (informer relists)."""
        path = (f"{self._collection(kind, None)}?watch=1"
                f"&resourceVersion={urllib.parse.quote(resource_version)}"
                f"&allowWatchBookmarks=true&timeoutSeconds={int(timeout_s)}")
        url = self.base_url + path
        req = urllib.request.Request(url, method="GET")
        req.add_header("Accept", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            resp = urllib.request.urlopen(req, timeout=timeout_s + 10,
                                          context=self._ctx)
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")[:500]
            if e.code == 410:
                raise Gone(f"watch {kind}@{resource_version}: {detail}") from None
            if e.code in _TRANSIENT_HTTP:
                raise ApiUnavailable(
                    f"watch {kind} -> {e.code}: {detail}") from None
            raise RuntimeError(f"watch {kind} -> {e.code}: {detail}") from None
        except TimeoutError as e:
            raise ApiTimeout(f"watch {kind}: {e}") from None
        except urllib.error.URLError as e:
            raise ApiUnavailable(f"watch {kind}: {e.reason}") from None
        with resp:
            for raw in resp:
                line = raw.strip()
                if not line:
                    continue
                ev = json.loads(line)
                obj = ev.get("object", {})
                if ev.get("type") == "ERROR":
                    # In-stream 410 (expired watch window) arrives as a
                    # Status object, not an HTTP error.
                    if obj.get("code") == 410:
                        raise Gone(f"watch {kind}: {obj.get('message')}")
                    raise RuntimeError(f"watch {kind} error: {obj}")
                rv = obj.get("metadata", {}).get("resourceVersion", "")
                yield {"type": ev.get("type"), "object": obj, "rv": rv}

    def delete(self, kind: str, name: str, namespace: str | None = None) -> None:
        self._request("DELETE", self._object_path(kind, name, namespace))

    def patch_annotations(self, kind: str, name: str, patch: dict[str, str | None],
                          namespace: str | None = None,
                          expect_version: str | None = None) -> dict:
        body: dict = {"metadata": {"annotations": {
            k: (None if v is None else str(v)) for k, v in patch.items()}}}
        if expect_version is not None:
            body["metadata"]["resourceVersion"] = expect_version
        return self._request(
            "PATCH", self._object_path(kind, name, namespace), body,
            content_type="application/merge-patch+json")

    def patch_labels(self, kind: str, name: str, patch: dict[str, str | None],
                     namespace: str | None = None) -> dict:
        body = {"metadata": {"labels": {
            k: (None if v is None else str(v)) for k, v in patch.items()}}}
        return self._request(
            "PATCH", self._object_path(kind, name, namespace), body,
            content_type="application/merge-patch+json")

    def bind_pod(self, name: str, node_name: str, namespace: str | None = None) -> dict:
        ns = namespace or "default"
        binding = {
            "apiVersion": "v1",
            "kind": "Binding",
            "metadata": {"name": name, "namespace": ns},
            "target": {"apiVersion": "v1", "kind": "Node", "name": node_name},
        }
        return self._request(
            "POST", f"/api/v1/namespaces/{ns}/pods/{name}/binding", binding)
