# lint-corpus-relpath: tputopo/corpus/lockset_bad.py
"""KNOWN-BAD lockset corpus: every construct here must be flagged."""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0  # guarded-by: _lock
        self._cache = {}  # shared, deliberately unannotated

    # thread-root: corpus worker thread
    def rmw_across_regions(self):
        with self._lock:
            n = self._n
        # lock dropped: a concurrent writer in this window is lost
        with self._lock:
            self._n = n + 1  # BAD: non-atomic read-modify-write

    # thread-root: corpus worker thread
    def unguarded_on_one_path(self, flag):
        if flag:
            with self._lock:
                return self._n
        return self._n  # BAD: read with no lock on this path

    def helper(self):  # holds-lock: _lock
        self._n += 1

    # thread-root: corpus worker thread
    def broken_claim(self):
        self.helper()  # BAD: claims _lock held, caller never takes it

    # thread-root: corpus worker thread
    def unannotated_mutation(self):
        self._cache.pop("k", None)  # BAD: lock-free container mutation
