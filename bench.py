"""Headline benchmark: end-to-end scheduling latency of the topology-aware
extender, A/B'd against the reference's published cost.

The reference's only published performance axis for the scheduler itself is
mean scheduling time (Gaia paper §IV Exp.5, Fig. 10: the stock kube-scheduler
takes ~2.5 s per pod; topology-aware Gaia ~2.7-3.6 s — topology awareness
there COSTS latency).  This framework's claim is that slice-shape enumeration
on a regular ICI torus is cheap enough to be free: the bench drives the same
hot loop (sort over all feasible nodes -> bind winner, SURVEY.md §3.2) for a
realistic pod mix on a fake v5p-128 cluster (64 chips, 16 hosts — BASELINE
config 5 scale) and reports the p50 sort+bind wall time per pod.

vs_baseline = Gaia's topology-aware mean scheduling time (2700 ms, PDF
Fig. 10 Exp.1 setup) divided by our p50 — i.e. how many times faster this
scheduler reaches a *better-informed* decision than the reference design's
own published number.

Placement quality is asserted, not just timed: every multi-chip placement
must be a contiguous box at the ideal predicted all-reduce bandwidth for
its size (quality_vs_ideal == 1.0), and the gang decisions must tile
disjointly — otherwise the bench refuses to print a result.  Extra context
(quality, workload step time on the local accelerator) rides in the same
JSON line under "extras".

Prints exactly ONE JSON line:
  {"metric": ..., "value": ..., "unit": "ms", "vs_baseline": ..., "extras": {...}}
"""

from __future__ import annotations

import json
import statistics
import sys
import time

GAIA_SCHED_MS = 2700.0  # Gaia topology-aware mean scheduling time, PDF Fig. 10


def bench_scheduler(repeats: int = 5) -> dict:
    from tests.cluster import build_cluster
    from tputopo.extender.config import ExtenderConfig
    from tputopo.extender.scheduler import ExtenderScheduler
    from tputopo.extender.state import ClusterState
    from tputopo.k8s import make_pod
    from tputopo.topology.score import predict_allreduce_gbps
    from tputopo.topology.slices import enumerate_shapes

    lat_ms: list[float] = []
    quality: list[float] = []

    for rep in range(repeats):
        api, _ = build_cluster(spec="v5p:4x4x4", workers=16)
        sched = ExtenderScheduler(api, ExtenderConfig())
        nodes = [n["metadata"]["name"] for n in api.list("nodes")]

        # True ideal bandwidth per request size: best box shape of volume k
        # on the empty torus (what the scheduler itself calls ideal).
        dom = ClusterState(api).sync().domains["slice-a"]
        ideal_for = {
            k: predict_allreduce_gbps(
                dom.topology,
                enumerate_shapes(dom.topology, k, dom.allocator.cost)[0].dims,
                dom.allocator.cost)
            for k in (2, 4)
        }

        # Pod mix: the BASELINE configs' request sizes — singles, ICI pairs,
        # 4-chip host slices, and a 4x4-chip DP gang.
        pods = []
        for i in range(4):
            pods.append(make_pod(f"one-{rep}-{i}", chips=1))
        for i in range(4):
            pods.append(make_pod(f"pair-{rep}-{i}", chips=2))
        for i in range(4):
            pods.append(make_pod(f"quad-{rep}-{i}", chips=4))
        for i in range(4):
            p = make_pod(f"gang-{rep}-{i}", chips=4)
            p["metadata"]["labels"] = {"tpu.dev/gang-id": f"dp-{rep}",
                                       "tpu.dev/gang-size": "4"}
            pods.append(p)
        for p in pods:
            api.create("pods", p)

        gang_chips: list[tuple] = []
        for p in pods:
            name = p["metadata"]["name"]
            t0 = time.perf_counter()
            scores = sched.sort(api.get("pods", name, "default"), nodes)
            best = max(scores, key=lambda s: (s["Score"], s["Host"]))
            if best["Score"] <= 0:
                raise SystemExit(f"bench: no feasible node for {name}")
            decision = sched.bind(name, "default", best["Host"])
            lat_ms.append((time.perf_counter() - t0) * 1e3)

            k = len(decision["chips"])
            if k > 1:
                if not decision["contiguous"]:
                    raise SystemExit(f"bench: non-contiguous placement for {name}")
                q = decision["predicted_allreduce_gbps"] / ideal_for[k]
                if q < 1.0:
                    raise SystemExit(
                        f"bench: {name} placed at {q:.2f} of ideal bandwidth "
                        f"({decision['predicted_allreduce_gbps']} vs "
                        f"{ideal_for[k]} GB/s)")
                quality.append(q)
            if name.startswith("gang-"):
                gang_chips.extend(tuple(c) for c in decision["chips"])

        if len(set(gang_chips)) != 16:
            raise SystemExit("bench: gang replicas did not tile disjointly")

    lat_ms.sort()
    return {
        "p50_ms": statistics.median(lat_ms),
        "p95_ms": lat_ms[int(len(lat_ms) * 0.95) - 1],
        "pods_scheduled": len(lat_ms),
        "quality_vs_ideal": min(quality) if quality else None,
    }


def bench_ab_gain() -> float:
    """Mean predicted-bandwidth advantage of topology-aware placement over
    count-only first-fit across randomized churn traces (the Gaia Exp.6
    analog in model units; see tests/test_ab_study.py)."""
    import statistics as stats

    from tests.test_ab_study import run_trace

    traces = [run_trace(seed) for seed in range(3)]
    return round(stats.mean(t["bw_smart"] / t["bw_naive"] for t in traces), 2)


def bench_workload_step() -> dict | None:
    """Forward-step wall time of the flagship LM on the local accelerator
    (one real TPU chip under the driver; CPU elsewhere).  Context only."""
    try:
        import jax

        from tputopo.workloads.model import ModelConfig, forward, init_params
        import jax.numpy as jnp
        import numpy as np

        config = ModelConfig(vocab_size=2048, d_model=512, n_layers=4,
                             n_heads=8, n_kv_heads=4, d_ff=1024, max_seq=512,
                             compute_dtype=jnp.bfloat16)
        params = init_params(config, jax.random.key(0))
        rng = np.random.default_rng(0)
        batches = [jnp.asarray(rng.integers(0, config.vocab_size, (8, 256)))
                   for _ in range(4)]
        fn = jax.jit(lambda p, t: forward(p, t, config))
        fn(params, batches[0]).block_until_ready()  # compile
        times = []
        for i in range(12):
            t0 = time.perf_counter()
            # jnp.sum forces a full device round-trip: float() on the result
            # cannot return before the forward pass actually finished, even
            # if the platform's block_until_ready is optimistic.
            float(jnp.sum(fn(params, batches[i % 4])))
            times.append(time.perf_counter() - t0)
        t = statistics.median(times)
        toks = batches[0].size
        return {
            "platform": jax.devices()[0].platform,
            "fwd_step_ms": round(t * 1e3, 3),
            "fwd_tokens_per_s": round(toks / t),
        }
    except Exception as e:  # pragma: no cover - context only, never fatal
        print(f"bench: workload step skipped: {e}", file=sys.stderr)
        return None


def main() -> None:
    sched = bench_scheduler()
    workload = bench_workload_step()
    p50 = sched["p50_ms"]
    out = {
        "metric": "scheduler_sort_bind_p50_latency",
        "value": round(p50, 3),
        "unit": "ms",
        # Gaia's topology-aware scheduler needed 2700 ms per pod (PDF Fig.10);
        # ratio >1 = this framework decides that many times faster.
        "vs_baseline": round(GAIA_SCHED_MS / p50, 1),
        "extras": {
            "baseline": "Gaia topology-aware mean scheduling time 2700 ms (PDF Fig. 10)",
            "p95_ms": round(sched["p95_ms"], 3),
            "pods_scheduled": sched["pods_scheduled"],
            "cluster": "fake v5p-128 (4x4x4 chips, 16 hosts)",
            "placement_quality_vs_ideal": sched["quality_vs_ideal"],
            "bandwidth_gain_vs_count_only": bench_ab_gain(),
            "workload_fwd": workload,
        },
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
