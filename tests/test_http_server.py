"""HTTP extender tests: drive the real socket with urllib, the way
kube-scheduler would (SURVEY.md §4.3 — the API is plain HTTP+JSON)."""

import json
import urllib.error
import urllib.request

import pytest

from tests.cluster import build_cluster
from tputopo.extender import ExtenderConfig, ExtenderHTTPServer, ExtenderScheduler
from tputopo.k8s import make_pod


@pytest.fixture()
def server():
    api, _ = build_cluster()
    config = ExtenderConfig()
    sched = ExtenderScheduler(api, config)
    srv = ExtenderHTTPServer(sched, config, port=0).start()  # ephemeral port
    yield api, srv
    srv.stop()


def post(srv, path, payload):
    host, port = srv.address
    req = urllib.request.Request(
        f"http://{host}:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=5) as resp:
        return resp.status, json.loads(resp.read())


def get(srv, path):
    host, port = srv.address
    with urllib.request.urlopen(f"http://{host}:{port}{path}", timeout=5) as resp:
        return resp.status, resp.read().decode()


def test_sort_and_bind_over_http(server):
    api, srv = server
    api.create("pods", make_pod("web-train", chips=4))
    pod = api.get("pods", "web-train", "default")

    status, scores = post(srv, "/tputopo-scheduler/sort",
                          {"Pod": pod, "NodeNames": ["node-0", "node-1"]})
    assert status == 200
    assert {s["Host"] for s in scores} == {"node-0", "node-1"}
    assert all(s["Score"] > 0 for s in scores)

    status, result = post(srv, "/tputopo-scheduler/bind",
                          {"PodName": "web-train", "PodNamespace": "default",
                           "Node": "node-1"})
    assert status == 200 and result["Error"] == ""
    bound = api.get("pods", "web-train", "default")
    assert bound["spec"]["nodeName"] == "node-1"


def test_sort_accepts_full_node_items(server):
    api, srv = server
    api.create("pods", make_pod("p", chips=1))
    pod = api.get("pods", "p", "default")
    nodes = {"Items": api.list("nodes")}
    status, scores = post(srv, "/tputopo-scheduler/sort",
                          {"Pod": pod, "Nodes": nodes})
    assert status == 200 and len(scores) == 4


def test_bind_failure_reports_error_string(server):
    api, srv = server
    status, result = post(srv, "/tputopo-scheduler/bind",
                          {"PodName": "ghost", "PodNamespace": "default",
                           "Node": "node-0"})
    assert status == 200
    assert "not found" in result["Error"]


def test_malformed_requests_get_400(server):
    api, srv = server
    status = None
    try:
        post(srv, "/tputopo-scheduler/sort", {"NodeNames": []})  # no Pod
    except urllib.error.HTTPError as e:
        status = e.code
        body = json.loads(e.read())
        assert "Pod" in body["error"]
    assert status == 400
    try:
        post(srv, "/tputopo-scheduler/bind", {"PodName": "x"})
    except urllib.error.HTTPError as e:
        assert e.code == 400
    try:
        post(srv, "/tputopo-scheduler/nope", {})
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_health_metrics_state_policy(server):
    api, srv = server
    assert get(srv, "/healthz") == (200, "ok\n")

    api.create("pods", make_pod("p", chips=2))
    pod = api.get("pods", "p", "default")
    post(srv, "/tputopo-scheduler/sort", {"Pod": pod, "NodeNames": ["node-0"]})
    post(srv, "/tputopo-scheduler/bind",
         {"PodName": "p", "PodNamespace": "default", "Node": "node-0"})

    _, metrics = get(srv, "/metrics")
    assert "tputopo_extender_sort_requests_total 1" in metrics
    assert "tputopo_extender_bind_success_total 1" in metrics
    assert "tputopo_extender_sort_latency_p50_ms" in metrics
    assert "tputopo_extender_sort_latency_p95_ms" in metrics

    _, state_raw = get(srv, "/state")
    state = json.loads(state_raw)
    assert state["fragmentation"]["slice-a"]["used_chips"] == 2
    assert state["decisions"][-1]["pod"] == "default/p"

    _, policy_raw = get(srv, "/policy")
    policy = json.loads(policy_raw)
    assert policy["extenders"][0]["prioritizeVerb"] == "sort"


def test_state_served_from_informer_mirror_zero_api_lists():
    """GET /state must ride the informer mirror like the verbs do
    (nodeCacheCapable posture): a monitoring scraper polling it in steady
    state causes ZERO API-server LISTs and zero informer relists."""
    from tputopo.k8s.informer import Informer

    api, _ = build_cluster()
    informer = Informer(api, watch_timeout_s=2.0).start()
    try:
        informer.wait_synced()
        config = ExtenderConfig()
        sched = ExtenderScheduler(api, config, informer=informer)
        srv = ExtenderHTTPServer(sched, config, port=0).start()
        try:
            get(srv, "/state")  # prime the state build once
            informer_lists_before = informer.metrics["lists"]
            api_lists = 0
            real_list = api.list

            def counting_list(*args, **kwargs):
                nonlocal api_lists
                api_lists += 1
                return real_list(*args, **kwargs)

            api.list = counting_list
            try:
                for _ in range(5):
                    status, raw = get(srv, "/state")
                    assert status == 200
                    assert "fragmentation" in json.loads(raw)
            finally:
                api.list = real_list
            assert api_lists == 0, "steady-state /state polls hit the API server"
            assert informer.metrics["lists"] == informer_lists_before
            assert informer.metrics["relists"] == 0
            assert sched.metrics.counters.get("state_cache_hits", 0) >= 4
        finally:
            srv.stop()
    finally:
        informer.stop()
