# lint-corpus-relpath: tputopo/corpus/release_ok.py
"""Clean twin of release_bad: with / try-finally close on every path."""

import threading


class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self.budget = 3

    def with_span(self, span, risky):
        with span:
            risky()

    def finally_acquire(self, risky):
        self._lock.acquire()
        try:
            risky()
        finally:
            self._lock.release()

    def finally_span(self, span, flag, risky):
        span.__enter__()
        try:
            if flag:
                return None
            risky()
        finally:
            span.__exit__(None, None, None)
        return True

    def restored_budget(self, risky):
        saved = self.budget
        self.budget = 99
        try:
            risky()
        finally:
            self.budget = saved
