"""Fake API server + object model tests."""

import threading

import pytest

from tputopo.k8s import Conflict, FakeApiServer, NotFound, make_node, make_pod
from tputopo.k8s import objects as ko


def test_create_get_list_delete():
    api = FakeApiServer()
    api.create("nodes", make_node("n0", chips=4))
    api.create("pods", make_pod("p0", chips=2))
    assert api.get("nodes", "n0")["status"]["allocatable"][ko.RESOURCE_CHIPS] == "4"
    assert len(api.list("pods")) == 1
    api.delete("pods", "p0", namespace="default")
    with pytest.raises(NotFound):
        api.get("pods", "p0", namespace="default")
    with pytest.raises(Conflict):
        api.create("nodes", make_node("n0"))


def test_requested_chips_parsing():
    assert ko.pod_requested_chips(make_pod("p", chips=4)) == 4
    assert ko.pod_requested_chips(make_pod("p", chips=0)) == 0


def test_group_annotation_roundtrip():
    coords = [(0, 0, 1), (0, 1, 1)]
    s = ko.coords_to_ann(coords)
    assert s == "0,0,1;0,1,1"
    assert ko.ann_to_coords(s) == coords
    assert ko.ann_to_coords("") == []


def test_patch_annotations_merge_and_delete():
    api = FakeApiServer()
    api.create("pods", make_pod("p0", annotations={"a": "1"}))
    api.patch_annotations("pods", "p0", {"b": "2"}, namespace="default")
    obj = api.patch_annotations("pods", "p0", {"a": None}, namespace="default")
    assert obj["metadata"]["annotations"] == {"b": "2"}


def test_patch_cas_conflict():
    api = FakeApiServer()
    obj = api.create("pods", make_pod("p0"))
    rv = obj["metadata"]["resourceVersion"]
    api.patch_annotations("pods", "p0", {"x": "1"}, namespace="default")
    with pytest.raises(Conflict):
        api.patch_annotations("pods", "p0", {"y": "2"}, namespace="default",
                              expect_version=rv)


def test_bind_pod_once():
    api = FakeApiServer()
    api.create("pods", make_pod("p0", chips=1))
    pod = api.bind_pod("p0", "n3", namespace="default")
    assert pod["spec"]["nodeName"] == "n3"
    with pytest.raises(Conflict):
        api.bind_pod("p0", "n4", namespace="default")
    assert api.pods_on_node("n3")[0]["metadata"]["name"] == "p0"


def test_deep_copy_isolation():
    api = FakeApiServer()
    api.create("nodes", make_node("n0", chips=4))
    got = api.get("nodes", "n0")
    got["status"]["allocatable"][ko.RESOURCE_CHIPS] = "999"
    assert api.get("nodes", "n0")["status"]["allocatable"][ko.RESOURCE_CHIPS] == "4"


def test_concurrent_patches_are_serialized():
    api = FakeApiServer()
    api.create("pods", make_pod("p0"))
    errs = []

    def worker(i):
        try:
            for j in range(50):
                api.patch_annotations("pods", "p0", {f"k{i}-{j}": "v"},
                                      namespace="default")
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    anns = api.get("pods", "p0", "default")["metadata"]["annotations"]
    assert len(anns) == 200


# ---- copy-free reads: get_nocopy / handles / the mutation guard -------------


def test_get_nocopy_returns_stored_object():
    api = FakeApiServer()
    api.create("pods", make_pod("p0", chips=2))
    a = api.get_nocopy("pods", "p0", "default")
    b = api.get_nocopy("pods", "p0", "default")
    assert a is b  # no copy: the stored dict itself
    with pytest.raises(NotFound):
        api.get_nocopy("pods", "nope", "default")
    # A server-side patch is visible through the same reference (stored
    # dicts are mutated in place) — part of the documented contract.
    api.patch_annotations("pods", "p0", {"k": "v"}, "default")
    assert a["metadata"]["annotations"]["k"] == "v"


def test_object_handle_survives_patch_and_recreate():
    """The handle is keyed, not identity-bound: it tracks the object
    through in-place patches AND through a delete/recreate cycle (the sim's
    requeued-job case), raising NotFound only while the object is gone."""
    api = FakeApiServer()
    api.create("pods", make_pod("p0", chips=1))
    h = api.handle("pods", "p0", "default")
    assert h.fetch()["metadata"]["name"] == "p0"
    api.patch_annotations("pods", "p0", {"a": "1"}, "default")
    assert h.fetch()["metadata"]["annotations"]["a"] == "1"
    api.delete("pods", "p0", "default")
    with pytest.raises(NotFound):
        h.fetch()
    api.create("pods", make_pod("p0", chips=1))
    fresh = h.fetch()
    assert fresh["metadata"].get("annotations", {}).get("a") is None
    assert fresh is api.get_nocopy("pods", "p0", "default")


def test_nocopy_guard_catches_caller_mutation():
    """Satellite: the debug-mode digest guard must catch a get_nocopy
    caller breaking the read-only contract — content changed while the
    resourceVersion did not move (the server's own writes always bump)."""
    api = FakeApiServer()
    api.nocopy_guard = True
    api.create("pods", make_pod("p0", chips=1))
    pod = api.get_nocopy("pods", "p0", "default")
    # Legitimate traffic never trips it: repeat reads, server writes.
    api.get_nocopy("pods", "p0", "default")
    api.patch_annotations("pods", "p0", {"ok": "1"}, "default")
    api.verify_nocopy_digests()
    pod = api.get_nocopy("pods", "p0", "default")
    # tpulint: disable=nocopy -- deliberate violation: this test exercises the digest guard
    pod["spec"]["illegal"] = True  # the contract violation
    with pytest.raises(RuntimeError, match="nocopy contract violation"):
        api.get_nocopy("pods", "p0", "default")


def test_nocopy_guard_checks_before_server_writes():
    """A violation must also surface at the next server-side write to the
    object (and via verify_nocopy_digests), not only at the next read —
    otherwise a mutate-then-patch sequence would launder the mutation into
    a legitimate-looking version bump."""
    api = FakeApiServer()
    api.nocopy_guard = True
    api.create("pods", make_pod("p0", chips=1))
    # tpulint: disable=nocopy -- deliberate violation: this test exercises the digest guard
    api.get_nocopy("pods", "p0", "default")["status"]["phase"] = "Hacked"
    with pytest.raises(RuntimeError, match="nocopy contract violation"):
        api.verify_nocopy_digests()
    with pytest.raises(RuntimeError, match="nocopy contract violation"):
        api.patch_annotations("pods", "p0", {"k": "v"}, "default")


def test_create_echo_optout_copy_count(monkeypatch):
    """Satellite: create() historically deep-copied twice per object on
    top of the watch-log emit copy; echo=False must skip exactly the echo
    deepcopy and return a metadata-only stub.  With no watch consumer
    attached the emit copy is lazy too — a watcher-less create(echo=False)
    costs exactly the ONE store copy."""
    import copy as copymod

    real = copymod.deepcopy
    calls = {"n": 0}

    def counting(x, memo=None, _nil=[]):  # noqa: B006 — mirrors copy.deepcopy's real signature
        calls["n"] += 1
        return real(x, memo)

    monkeypatch.setattr(copymod, "deepcopy", counting)
    api = FakeApiServer()
    calls["n"] = 0
    echoed = api.create("pods", make_pod("p0", chips=1))
    with_echo = calls["n"]
    calls["n"] = 0
    stub = api.create("pods", make_pod("p1", chips=1), echo=False)
    without_echo = calls["n"]
    assert without_echo == with_echo - 1  # exactly the echo copy gone
    assert without_echo == 1  # store copy only: no watcher, no emit copy
    # The stub still answers the questions a creator has.
    assert stub["metadata"]["name"] == "p1"
    assert stub["metadata"]["namespace"] == "default"
    assert stub["metadata"]["resourceVersion"] == \
        api.get("pods", "p1", "default")["metadata"]["resourceVersion"]
    # The full echo stays an independent deep copy.
    echoed["spec"]["mutated"] = True
    assert "mutated" not in api.get("pods", "p0", "default")["spec"]


def test_watch_log_copy_is_lazy_until_attach(monkeypatch):
    """Satellite (ROADMAP sim bottleneck 2): _emit's deepcopy-into-
    watch-log must not run while no watch consumer has ever attached
    (the sim has no watchers — the emit copy was ~10% of sim wall);
    attaching via list_with_version/watch turns logging back on, and a
    watcher asking for an rv that predates the attach gets Gone (the
    relist path), never silently missing events."""
    import copy as copymod

    from tputopo.k8s.fakeapi import Gone

    real = copymod.deepcopy
    calls = {"n": 0}

    def counting(x, memo=None, _nil=[]):  # noqa: B006 — mirrors copy.deepcopy's real signature
        calls["n"] += 1
        return real(x, memo)

    monkeypatch.setattr(copymod, "deepcopy", counting)
    api = FakeApiServer()
    api.create("pods", make_pod("p0", chips=1), echo=False)
    calls["n"] = 0
    api.patch_annotations("pods", "p0", {"a": "1"}, "default")
    patch_copies_unwatched = calls["n"]
    # patch_annotations returns a deepcopy of the object (1); the emit
    # copy must be gone.
    assert patch_copies_unwatched == 1
    assert api._watch_log == []  # nothing retained for nobody

    # A watcher from an rv predating the attach: Gone -> relist, the
    # same recovery as a scrolled retention window.
    with pytest.raises(Gone):
        list(api.watch("pods", "1", timeout_s=0.05))

    # Attach via the informer's sync point: events after the returned rv
    # are logged (with their emit copy) and delivered.
    _, rv = api.list_with_version("pods")
    calls["n"] = 0
    api.patch_annotations("pods", "p0", {"a": "2"}, "default")
    assert calls["n"] == patch_copies_unwatched + 1  # emit copy is back
    events = list(api.watch("pods", rv, timeout_s=0.05))
    assert [e["type"] for e in events if e["type"] != "BOOKMARK"] \
        == ["MODIFIED"]
    anns = events[0]["object"]["metadata"]["annotations"]
    assert anns["a"] == "2"
