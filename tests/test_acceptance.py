"""Acceptance tests: the five BASELINE.json configs, end to end against the
CPU-emulated discovery backend (BASELINE.md "Targets for the TPU-native
rebuild"; the rebuild analog of Gaia's Exp.1-4, PDF §IV)."""

import pytest

from tests.cluster import build_cluster
from tests.test_extender import Clock, all_nodes, gang_pod, make_scheduler
from tputopo.extender import ClusterState
from tputopo.k8s import make_pod
from tputopo.k8s import objects as ko
from tputopo.topology.score import predict_multidomain_allreduce_gbps, score_chip_set


def schedule(sched, api, pod_name, namespace="default"):
    """One full scheduling cycle: sort over all nodes, bind to the winner."""
    pod = api.get("pods", pod_name, namespace)
    scores = sched.sort(pod, all_nodes(api))
    best = max(scores, key=lambda s: (s["Score"], s["Host"]))
    assert best["Score"] > 0, f"no feasible node for {pod_name}: {scores}"
    return sched.bind(pod_name, namespace, best["Host"])


def test_config1_single_chip_allocate_smoke():
    """Config 1: single-pod 1-chip request through the whole pipeline —
    sort, bind, kubelet Allocate, env injection, handshake confirm."""
    clock = Clock(1000.0)
    api, plugins = build_cluster(clock=clock)
    sched = make_scheduler(api, clock=clock)
    api.create("pods", make_pod("smoke", chips=1))
    decision = schedule(sched, api, "smoke")
    node = decision["node"]
    chip_id = ",".join(str(x) for x in decision["chips"][0])

    resp = plugins[node].kubelet.allocate(ko.RESOURCE_CHIPS, [chip_id])
    envs = resp.container_responses[0].envs
    assert envs["TPU_VISIBLE_CHIPS"] in {"0", "1", "2", "3"}
    assert envs["TPU_ACCELERATOR_TYPE"] == "v5p-32"
    pod = api.get("pods", "smoke", "default")
    assert pod["metadata"]["annotations"][ko.ANN_ASSIGNED] == "true"
    assert pod["spec"]["nodeName"] == node


def test_config2_adjacent_pair():
    """Config 2: 2-chip request must land on an ICI-neighbor pair (the
    NVLink-pair score -> ICI-neighbor score analog, Gaia Exp.4)."""
    clock = Clock(1000.0)
    api, _ = build_cluster(clock=clock)
    sched = make_scheduler(api, clock=clock)
    api.create("pods", make_pod("pair", chips=2))
    decision = schedule(sched, api, "pair")
    state = ClusterState(api, clock=clock).sync()
    dom = state.domains["slice-a"]
    a, b = [tuple(c) for c in decision["chips"]]
    assert dom.topology.hop_distance(a, b) == 1
    assert decision["predicted_allreduce_gbps"] == 200.0  # 2 dirs x 100 GB/s


def test_config3_8chip_contiguous_2x2x2():
    """Config 3: an 8-chip 2x2x2 contiguous slice (gang of two v5p hosts),
    the shape the JAX pmap all-reduce bench runs on."""
    clock = Clock(1000.0)
    api, _ = build_cluster(clock=clock)
    sched = make_scheduler(api, clock=clock)
    for i in range(2):
        api.create("pods", gang_pod(f"bench-{i}", "bench", 2, 4))
    for i in range(2):
        schedule(sched, api, f"bench-{i}")
    state = ClusterState(api, clock=clock).sync()
    dom = state.domains["slice-a"]
    used = dom.allocator.used
    assert len(used) == 8
    score = score_chip_set(dom.topology, used, dom.allocator.cost)
    # A contiguous 2x2x2 box: 3 axes x 200 GB/s.
    assert score == pytest.approx(600.0)


def test_config4_gang_4x4_on_v5p32():
    """Config 4: gang-schedule 4 x (4-chip) DP replicas on v5p-32; replicas
    disjoint, each contiguous, union tiles the slice."""
    clock = Clock(1000.0)
    api, _ = build_cluster(clock=clock)
    sched = make_scheduler(api, clock=clock)
    for i in range(4):
        api.create("pods", gang_pod(f"dp-{i}", "llama", 4, 4))
    decisions = [schedule(sched, api, f"dp-{i}") for i in range(4)]
    assert all(d["contiguous"] for d in decisions)
    assert all(d["predicted_allreduce_gbps"] == 400.0 for d in decisions)
    all_chips = [tuple(c) for d in decisions for c in d["chips"]]
    assert len(set(all_chips)) == 16  # disjoint, complete tiling
    assert len({d["node"] for d in decisions}) == 4


def test_config5_multihost_v5p128_with_dcn_scoring():
    """Config 5: a v5p-128 (64-chip 4x4x4, 16 hosts) scheduled as a 16-pod
    gang; the union must be the full contiguous box (cross-host ICI), and
    the DCN model must rank any cross-domain alternative strictly lower."""
    clock = Clock(1000.0)
    api, _ = build_cluster(spec="v5p:4x4x4", workers=16, clock=clock)
    sched = make_scheduler(api, clock=clock)
    for i in range(16):
        api.create("pods", gang_pod(f"big-{i:02d}", "v5p128", 16, 4))
    decisions = [schedule(sched, api, f"big-{i:02d}") for i in range(16)]
    assert len({d["node"] for d in decisions}) == 16
    state = ClusterState(api, clock=clock).sync()
    dom = state.domains["slice-a"]
    used = dom.allocator.used
    assert len(used) == 64
    ici_score = score_chip_set(dom.topology, used, dom.allocator.cost)
    # Full 4x4x4 box, no wrap (pod max is 16x16x24): 3 axes x 100*4/6.
    assert ici_score == pytest.approx(3 * 100.0 * 4 / 6)

    # DCN comparison: the same 64 chips split across two 32-chip domains
    # is bounded by the narrowest domain's DCN pipe — far below ICI.
    half_a = frozenset(c for c in used if c[0] < 2)
    half_b = frozenset(c for c in used if c[0] >= 2)
    dcn_score = predict_multidomain_allreduce_gbps(
        [(dom.topology, half_a), (dom.topology, half_b)], dom.allocator.cost)
    assert dcn_score < ici_score / 2


def test_scheduler_latency_budget():
    """Latency sanity vs the Gaia baseline: Gaia's topology-aware scheduling
    added +0.2-1.0 s per pod on top of ~2.5 s (PDF Fig. 10).  Our sort+bind
    cycle on a 16-host domain must stay well under that envelope."""
    clock = Clock(1000.0)
    api, _ = build_cluster(spec="v5p:4x4x4", workers=16, clock=clock)
    sched = make_scheduler(api, clock=clock)
    for i in range(8):
        api.create("pods", make_pod(f"lat-{i}", chips=4))
        schedule(sched, api, f"lat-{i}")
    p50_sort = sched.metrics.p50_ms("sort")
    p50_bind = sched.metrics.p50_ms("bind")
    # Absolute-ms gate policy (VERDICT r3 #8): measured p50s are ~1 ms;
    # the 1000 ms bound is the reference's own latency envelope with
    # ~1000x headroom for shared-host timing variance — a correctness
    # backstop, not a perf assertion (bench.py owns the perf numbers).
    assert p50_sort is not None and p50_sort < 1000.0
    assert p50_bind is not None and p50_bind < 1000.0
