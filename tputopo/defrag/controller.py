"""Defragmentation controller: the rescheduling loop around the planner.

One :meth:`DefragController.run_cycle` per period (the extender's defrag
thread, or the simulator's periodic ``defrag`` event): detect pressure,
plan, and — when every guard passes — execute the plan through the
existing eviction/requeue path: delete the victim pods (the job
controller / sim engine recreates them Pending, and the gang re-places
through the normal scheduling path), then verify the target box actually
came free.

Guards, in gate order (each abort is counted and attributed):

- **hysteresis**: pressure must persist for ``hysteresis`` consecutive
  cycles before any plan executes — one transient spike of arrivals must
  not evict running jobs.
- **cooldown**: at least ``cooldown_s`` (caller-clock seconds) between
  executed plans — the evicted gangs need time to re-place before the
  next migration makes churn compound.
- **max-concurrent**: no new plan while ``max_concurrent`` earlier
  migrations are still in flight (an evicted job's pods exist but are
  not yet re-bound).

Observability: every cycle opens a ``defrag`` flight-recorder trace with
``plan`` / ``evict`` / ``verify`` phase spans and an explain record (the
plan, or the structured abort reason); executed work increments the
Prometheus counters ``defrag_plans_considered`` / ``defrag_plans_executed``
/ ``defrag_plans_aborted`` / ``defrag_chips_moved`` when an extender
:class:`~tputopo.extender.scheduler.Metrics` is wired, plus the
controller's own deterministic counter dict (the sim report's ``defrag``
block).
"""

from __future__ import annotations

import random
import time

from tputopo.defrag.planner import (MigrationPlan, dedupe_demands,
                                    list_pods_nocopy, pending_demand,
                                    plan_migration, target_demands)
from tputopo.extender.state import ClusterState
from tputopo.k8s.fakeapi import NotFound
from tputopo.k8s.retry import ApiUnavailable, RetryPolicy, bind_retry
from tputopo.obs import NULL_TRACER


class DefragController:
    """Owns the defrag policy knobs and the cycle state machine.

    ``evict`` is the eviction hook: called once per victim with the
    :class:`~tputopo.defrag.planner.Victim`; the default deletes the
    victim's pods through the API server (the production path — the job
    controller recreates them).  The simulator injects its own hook so
    eviction flows through the engine's requeue bookkeeping.

    ``state_factory`` builds the authoritative
    :class:`~tputopo.extender.state.ClusterState` for planning and
    verification; the default syncs from ``api``.
    """

    #: Deterministic per-run counters (the sim report's ``defrag`` block).
    COUNTER_KEYS = ("cycles", "no_demand", "no_pressure", "plans_considered",
                    "plans_executed", "plans_aborted", "aborted_hysteresis",
                    "aborted_cooldown", "aborted_concurrent",
                    "aborted_no_plan", "jobs_evicted", "chips_moved",
                    "boxes_restored", "verify_failed")

    def __init__(self, api, *, clock=time.time, tracer=None, metrics=None,
                 assume_ttl_s: float = 60.0, cost_for_generation=None,
                 target_chips: int = 0, max_moves: int = 2,
                 max_chips_moved: int = 64, cooldown_s: float = 300.0,
                 hysteresis: int = 2, max_concurrent: int = 1,
                 evict=None, state_factory=None, retry_rng=None,
                 cost_of=None) -> None:
        self.api = api
        self.clock = clock
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.metrics = metrics
        self.target_chips = target_chips
        self.max_moves = max_moves
        self.max_chips_moved = max_chips_moved
        self.cooldown_s = cooldown_s
        self.hysteresis = max(1, hysteresis)
        self.max_concurrent = max_concurrent
        self._evict = evict if evict is not None else self._evict_via_api
        # Checkpoint-aware victim repricing (tputopo.elastic): a factory
        # returning the per-cycle ``cost_of`` callable plan_migration
        # charges with (rebuilt each cycle — costs are a function of
        # "now").  None keeps the pre-elastic chips-moved ranking.
        self._cost_of_factory = cost_of
        # Eviction deletes go through the shared retry policy via the one
        # shared ``bind_retry`` wiring: a transient API failure
        # mid-eviction must not wedge the cycle (and the sweep advances
        # virtual time deterministically when the clock sleeps), and each
        # retry is attributed (retry_api_timeout / retry_api_unavailable
        # under the defrag_ metrics prefix) like every other call site.
        # Jitter rng: per-instance entropy by default (no lockstep across
        # replicas); the sim injects a pinned one.
        retry_rng = retry_rng if retry_rng is not None else random.Random()
        self._retry_call = bind_retry(RetryPolicy(), clock, retry_rng,
                                      inc=self._count)
        self._state_factory = state_factory or (lambda: ClusterState(
            api, assume_ttl_s=assume_ttl_s, clock=clock,
            cost_for_generation=cost_for_generation).sync())
        self.counters = {k: 0 for k in self.COUNTER_KEYS}
        self._pressure_streak = 0
        self._last_exec_t: float | None = None
        # In-flight migrations: victim key -> (namespace, pod names,
        # evicted-at).  A migration is done once every pod is re-bound;
        # see _refresh_inflight for the missing-pod and TTL rules.
        self._inflight: dict[str, tuple[str, tuple[str, ...], float]] = {}
        self.last_plan: MigrationPlan | None = None  # observability

    # ---- helpers -----------------------------------------------------------

    def _count(self, key: str, by: int = 1) -> None:
        # .get, not []: fault-path keys (evict_errors, verify_replans)
        # appear lazily on first increment — COUNTER_KEYS stays the
        # pre-zeroed deterministic report vocabulary, so fault-free report
        # bytes are unchanged by the fault counters' existence.
        self.counters[key] = self.counters.get(key, 0) + by
        if self.metrics is not None:
            self.metrics.inc(f"defrag_{key}", by)

    def _evict_via_api(self, victim) -> None:
        for pod in victim.pods:
            try:
                self._retry_call(self.api.delete, "pods", pod,
                                 victim.namespace)
            except NotFound:
                continue  # completed/deleted meanwhile — nothing to move
            except ApiUnavailable:
                # Retries exhausted on one pod: count it and keep going —
                # a partial eviction fails verification, and the verify
                # path's re-plan (below) picks the work back up; a raise
                # here would wedge the controller loop instead.
                self._count("evict_errors")
                continue

    def demands(self, state: ClusterState) -> list[tuple[int, int]]:
        """The demand shapes this cycle plans for: the configured fixed
        target when set (a within-host or whole-hosts box of
        ``target_chips``, per domain geometry), else the pending pods'
        shapes."""
        if self.target_chips > 0:
            return target_demands(state, self.target_chips)
        # tpulint: disable=hot-path-scan -- amortized: one pending-pod scan per defrag PERIOD (cooldown/hysteresis-gated controller cycle), not per scheduling verb
        return pending_demand(list_pods_nocopy(state.api))

    #: In-flight entries older than this many cooldown periods (min. the
    #: assume TTL) are abandoned: a victim whose pods never reappeared
    #: (job cancelled, controller gone) must not hold a migration slot
    #: forever.
    _INFLIGHT_TTL_FLOOR_S = 60.0

    def _refresh_inflight(self) -> int:
        """Drop finished migrations; return the count still in flight.

        A victim is DONE only when every pod of it is re-BOUND.  A
        missing pod is indeterminate, not done: in the production path
        eviction deletes the pod and the job controller recreates it a
        beat later — observing that gap as completion would let
        back-to-back cycles bypass the max-concurrent gate entirely.
        Entries are abandoned (dropped) only after a TTL, covering jobs
        that genuinely never come back."""
        now = self.clock()
        ttl = max(self._INFLIGHT_TTL_FLOOR_S, self.cooldown_s)
        done = []
        for key, (ns, pods, evicted_t) in sorted(self._inflight.items()):
            unbound = False
            for pod in pods:
                try:
                    obj = self.api.get("pods", pod, ns)
                except NotFound:
                    unbound = True  # deleted or not yet recreated
                    break
                except ApiUnavailable:
                    unbound = True  # indeterminate — keep the slot held
                    break
                if not obj.get("spec", {}).get("nodeName"):
                    unbound = True  # recreated, still Pending
                    break
            if not unbound or now - evicted_t > ttl:
                done.append(key)
        for key in done:
            del self._inflight[key]
        return len(self._inflight)

    # ---- the cycle ---------------------------------------------------------

    def run_cycle(self, state: ClusterState | None = None,
                  demands: list[tuple[int, int]] | None = None) -> dict:
        """One defrag cycle.  Returns a deterministic record:
        ``{"action": "noop"|"aborted"|"executed", "reason": ...,
        "plan": <plan dict>|None, "restored": bool|None}``."""
        self._count("cycles")
        tr = self.tracer.start("defrag")
        with tr:
            return self._cycle_spanned(tr, state, demands)

    def _cycle_spanned(self, tr, state, demands) -> dict:
        with tr.phase("plan") as sp:
            if state is None:
                state = self._state_factory()
            if demands is None:
                demands = self.demands(state)
            demands = dedupe_demands(d for d in demands
                                     if d[0] >= 1 and d[1] >= 1
                                     and d[0] * d[1] > 1)
            sp.count("demand_shapes", len(demands))
            if not demands:
                self._pressure_streak = 0
                self._count("no_demand")
                return self._done(tr, "noop", "no_demand")
            # Planning doubles as the pressure test: a plan search that
            # finds every demand placeable (or no domain pressured) is
            # the "no pressure" outcome; the plan itself is only ACTED on
            # once the guards pass.  ``pressured`` collects the shapes
            # the one scan found pressured — no second pass to classify
            # a None return.
            self._count("plans_considered")
            pressured: list = []
            plan = plan_migration(state, demands, max_moves=self.max_moves,
                                  max_chips_moved=self.max_chips_moved,
                                  pressured_out=pressured,
                                  cost_of=(self._cost_of_factory()
                                           if self._cost_of_factory else None))
            self.last_plan = plan
            if plan is None:
                if not pressured:
                    self._pressure_streak = 0
                    self._count("no_pressure")
                    return self._done(tr, "noop", "no_pressure")
                self._pressure_streak += 1
                self._count("plans_aborted")
                self._count("aborted_no_plan")
                return self._done(tr, "aborted", "no_plan_within_budget")
            self._pressure_streak += 1
            sp.count("victims", len(plan.victims))
            if self._pressure_streak < self.hysteresis:
                self._count("plans_aborted")
                self._count("aborted_hysteresis")
                return self._done(tr, "aborted", "hysteresis", plan)
            now = self.clock()
            if (self._last_exec_t is not None
                    and now - self._last_exec_t < self.cooldown_s):
                self._count("plans_aborted")
                self._count("aborted_cooldown")
                return self._done(tr, "aborted", "cooldown", plan)
            if self._refresh_inflight() >= self.max_concurrent:
                self._count("plans_aborted")
                self._count("aborted_concurrent")
                return self._done(tr, "aborted", "max_concurrent", plan)

        with tr.phase("evict") as sp:
            for victim in plan.victims:
                self._evict(victim)
                self._inflight[victim.key] = (victim.namespace, victim.pods,
                                              self.clock())
            sp.count("jobs", len(plan.victims))
            sp.count("chips", plan.chips_moved)
            self._count("plans_executed")
            self._count("jobs_evicted", len(plan.victims))
            self._count("chips_moved", plan.chips_moved)
            self._last_exec_t = self.clock()
            self._pressure_streak = 0

        with tr.phase("verify") as sp:
            try:
                after = self._state_factory()
                dom = after.domains.get(plan.slice_id)
                restored = (dom is not None
                            and plan.box_mask & dom.allocator.used_mask == 0)
            except ApiUnavailable:
                # Verification itself failed transiently: indeterminate,
                # treated like a failed verify — the re-plan below covers
                # it instead of the old raise wedging the loop.
                restored = False
            sp.count("restored" if restored else "failed")
            self._count("boxes_restored" if restored else "verify_failed")
            if not restored:
                # Re-plan instead of wedging: the evictions happened but
                # the box is not (provably) free — something re-placed
                # into it, a delete failed, or the verify read itself
                # errored.  Pressure is still real, so carry the streak at
                # the hysteresis threshold: the next cycle may plan again
                # as soon as the cooldown passes, rather than re-earning
                # ``hysteresis`` pressured cycles on top of it.
                self._pressure_streak = self.hysteresis
                self._count("verify_replans")
        return self._done(tr, "executed",
                          "restored" if restored else "box_not_free",
                          plan, restored)

    def _done(self, tr, action: str, reason: str,
              plan: MigrationPlan | None = None,
              restored: bool | None = None) -> dict:
        record = {"action": action, "reason": reason,
                  "plan": plan.describe() if plan is not None else None,
                  "restored": restored}
        if tr.enabled:
            tr.explain({"verb": "defrag", **record})
        return record
