"""The driver-artifact contract (VERDICT r4 #1/#2): bench.py prints its one
headline JSON line with rc=0 even when the TPU runtime is absent or wedged,
and ``dryrun_multichip`` never initializes a non-CPU backend.

Rounds 3 and 4 published NOTHING (rc=1 gate suicide, then rc=124 hang on a
wedged accelerator runtime) despite all the underlying work being healthy.
These tests pin the survival contract so it cannot regress silently.
"""

import json
import os
import signal
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402


@pytest.fixture
def restore_sigterm():
    prev = signal.getsignal(signal.SIGTERM)
    yield
    signal.signal(signal.SIGTERM, prev)


def _stub_headline(monkeypatch):
    monkeypatch.setattr(bench, "bench_scheduler", lambda: {
        "p50_ms": 1.0, "p95_ms": 2.0, "pods_scheduled": 4,
        "quality_vs_ideal": 1.0})
    monkeypatch.setattr(bench, "bench_scale", lambda: {"stub": True})
    monkeypatch.setattr(bench, "bench_ab_gain", lambda: 3.0)
    monkeypatch.setattr(bench, "bench_sim", lambda: {"stub": True})
    monkeypatch.setattr(bench, "bench_batch", lambda: {"stub": True})
    monkeypatch.setattr(bench, "bench_elastic", lambda: {"stub": True})
    monkeypatch.setattr(bench, "bench_shards", lambda: {"stub": True})


def test_headline_publishes_when_tpu_unavailable(monkeypatch, capsys,
                                                 restore_sigterm):
    """TPU preflight fails (the wedged-runtime case) -> every TPU sub-bench
    is marked skipped, the headline still prints as exactly one JSON line,
    and the exit code is 0."""
    _stub_headline(monkeypatch)
    monkeypatch.setattr(bench, "_tpu_preflight", lambda t: {
        "ok": False, "detail": "stub: no accelerator"})

    def boom(name, timeout_s, extra):
        raise AssertionError("no sub-bench subprocess may run without TPU")

    monkeypatch.setattr(bench, "_run_sub", boom)
    bench.main()  # must NOT raise SystemExit: rc stays 0
    lines = [ln for ln in capsys.readouterr().out.splitlines() if ln.strip()]
    assert len(lines) == 1
    out = json.loads(lines[0])
    assert out["metric"] == "scheduler_sort_bind_p50_latency"
    assert out["value"] == 1.0
    for sub in ("hbm", "decode", "moe", "serving", "workload_fwd"):
        assert out["extras"][sub]["skipped"] == "tpu_unavailable"
    assert out["extras"]["budget"]["budget_s"] > 0


def test_budget_exhaustion_skips_but_still_publishes(monkeypatch, capsys,
                                                     restore_sigterm):
    """A spent budget marks the remaining TPU sub-benches skipped instead of
    running them — the JSON line and rc=0 survive."""
    _stub_headline(monkeypatch)
    monkeypatch.setattr(bench, "_tpu_preflight",
                        lambda t: {"ok": True, "platform": "stub"})
    monkeypatch.setenv("BENCH_BUDGET_S", "0")

    def boom(name, timeout_s, extra):
        raise AssertionError("budget-exhausted sub-bench must not spawn")

    monkeypatch.setattr(bench, "_run_sub", boom)
    bench.main()
    lines = [ln for ln in capsys.readouterr().out.splitlines() if ln.strip()]
    assert len(lines) == 1
    out = json.loads(lines[0])
    assert out["extras"]["decode"]["skipped"].startswith("budget_exhausted")


def test_sub_correctness_failure_flags_exit_code(monkeypatch, capsys,
                                                 restore_sigterm):
    """A sub-bench correctness violation (error starting 'correctness:')
    must surface as exit code 1 — but only AFTER the JSON line printed."""
    _stub_headline(monkeypatch)
    monkeypatch.setattr(bench, "_tpu_preflight",
                        lambda t: {"ok": True, "platform": "stub"})
    monkeypatch.setattr(bench, "_run_sub", lambda name, timeout_s, extra: {
        "error": "correctness: stub violation"})
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 1
    lines = [ln for ln in capsys.readouterr().out.splitlines() if ln.strip()]
    assert len(lines) == 1
    assert json.loads(lines[0])["extras"]["hbm"]["error"].startswith(
        "correctness:")


def test_sub_main_unknown_name_is_loud(capsys):
    rc = bench._sub_main(["nonexistent"])
    assert rc == 2
    out = json.loads(capsys.readouterr().out.strip())
    assert "unknown sub-bench" in out["error"]


def test_parent_process_never_initializes_a_backend():
    """bench.py's parent process must never touch a JAX backend — on a
    wedged runtime that is an uncatchable hang.  Run the full main() in a
    subprocess under a platform that ERRORS on backend init: if any parent
    code path initializes the default backend, the run crashes; the
    contract is it publishes the headline with rc=0."""
    env = dict(os.environ)
    # An unknown platform makes jax.devices() raise immediately on a stock
    # JAX install; on images whose sitecustomize pins a hardware platform
    # (ignoring JAX_PLATFORMS) the probe meets the REAL backend instead —
    # either way the parent must survive, and the short preflight cap keeps
    # the wedged-runtime case from eating the test's clock.
    env["JAX_PLATFORMS"] = "definitely_not_a_platform"
    # Budget sized so that even if the pinned platform initializes and
    # passes preflight, the remaining budget is under the 45 s floor and
    # every TPU sub-bench deterministically skips — the test never runs
    # accelerator work, whatever the runtime's mood.
    env["BENCH_BUDGET_S"] = "50"
    env["BENCH_TPU_PREFLIGHT_S"] = "5"
    # The timeout is plumbing, not the contract under test: it only has
    # to outlast the CPU-side sub-benches (the fleet_xl leg's traced
    # phase-breakdown replay is the long pole at ~1 min on a loaded
    # host) so the backend-isolation assertions below get to run.
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=480, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1
    out = json.loads(lines[0])
    assert out["metric"] == "scheduler_sort_bind_p50_latency"
    assert "tpu_preflight" in out["extras"]
    for sub in ("hbm", "decode", "moe", "serving", "workload_fwd"):
        assert "skipped" in out["extras"][sub], out["extras"][sub]


from jax_features import requires_num_cpu_devices  # noqa: E402


# dryrun_multichip forces virtual CPU devices via the
# jax_num_cpu_devices config option; without it the subprocess cannot
# start on this JAX.
@requires_num_cpu_devices
def test_dryrun_multichip_is_cpu_only_and_hang_immune():
    """MULTICHIP_r04 died because dryrun_multichip touched the default
    backend before forcing CPU.  Pin the fix: under a default platform that
    ERRORS on first touch (stand-in for one that hangs), the dry run must
    still complete — proving it configures the CPU platform before any
    backend init — and its tail must name the multislice leg (VERDICT r4
    #4)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "definitely_not_a_platform"
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    proc = subprocess.run(
        [sys.executable, "-c",
         "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "dryrun_multichip OK" in proc.stdout
    assert "multislice" in proc.stdout


def test_timeline_summary_digest():
    """bench_sim's fleet/fleet_xl blocks fold a compact digest of the
    traced replay's timeline block — saturation onset, peak queue depth,
    emitted point count — and report None (not a crash) when the replay
    carried no timeline (the feature-off shape)."""
    assert bench._timeline_summary({}) is None
    assert bench._timeline_summary({"timeline": None}) is None
    rec = {"timeline": {
        "points": 42,
        "saturation": {"onset_t": 115.5, "peak_queue_depth": 22,
                       "peak_queue_t": 332.5, "above_util_s": 459.4,
                       "util_threshold": 0.9, "last_arrival_t": 616.7,
                       "drain_s": 1264.7},
    }}
    assert bench._timeline_summary(rec) == {
        "saturation_onset_t": 115.5,
        "peak_queue_depth": 22,
        "points": 42,
    }


def test_calibration_provenance_split_lands(monkeypatch, capsys,
                                            restore_sigterm):
    """When the hbm sub-bench reports a measurement, the calibration
    record must carry the calibrated/spec_only provenance split — a
    deployer needs to know which cost-model axes are measured vs
    spec-sheet (the design.md:47 weight-table lesson)."""
    _stub_headline(monkeypatch)
    monkeypatch.delenv("BENCH_BUDGET_S", raising=False)  # need budget > 45s
    monkeypatch.setattr(bench, "_tpu_preflight",
                        lambda t: {"ok": True, "platform": "stub"})

    def fake_sub(name, timeout_s, extra):
        if name == "hbm":
            return {"measured_hbm_gbps": 600.0, "generation": "v5e"}
        return {"skipped": "stub"}

    monkeypatch.setattr(bench, "_run_sub", fake_sub)
    bench.main()
    out = json.loads(capsys.readouterr().out.strip())
    cal = out["extras"]["calibration"]
    assert cal["provenance"]["calibrated"] == ["hbm_gbps"]
    assert "dcn_host_gbps" in cal["provenance"]["spec_only"]
    assert "ici_link_gbps" in cal["provenance"]["spec_only"]
    assert cal["cost_override"]["v5e"]["hbm_gbps"] == 600.0
