"""Weight-only int8 quantization for the serving path.

Decode is HBM-bound: BENCH_r04 measures the bf16 decode loop at ~99% of
the chip's measured HBM stream bandwidth, so the only remaining lever on
tokens/s is streaming fewer bytes.  Weight-only int8 (symmetric,
per-output-channel) halves the streamed weight bytes for a near-lossless
accuracy cost — the standard serving trade, expressed TPU-first:

- A quantized weight is the pair ``{"int8": q, "scale": s}`` where ``q``
  is int8 and ``s`` is float32 with a kept (size-1) reduction axis, so
  every leaf still scans over the leading layer axis exactly like its
  unquantized twin — the decode/prefill `lax.scan` machinery is unchanged.
- Matmul sites use :func:`qdot`, which computes ``(x @ q) * s`` — the
  per-output-channel scale commutes with the contraction over the input
  axis, so the MXU dot reads the int8 tensor directly (XLA fuses the
  int8->bf16 convert into the dot operand) and the scale lands as one
  cheap output-row multiply.  Dequantize-then-dot would materialize a
  bf16 copy of the weight and stream HBM at the unquantized rate.
- Gather sites (the embedding) use :func:`deq_rows`: rows are quantized
  per-row so the gather fetches int8 rows + one scale each.

Scope: **inference only** (decode / serving / forward for parity checks).
Training keeps float32 masters — quantization is a deployment step, not
an optimizer state format.  The reference has no serving leg at all (it
schedules training containers, Gaia PDF §IV Exp.6); this module is part
of the workload layer (SURVEY §1 L5) that placement serves.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: Weight names quantized in the stacked-layer tree (dense + MoE FFN).
#: Router and norm weights stay float32: they are O(D) or O(E) — streaming
#: them quantized saves nothing and the router's softmax is scale-sensitive.
_LAYER_WEIGHTS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def is_quantized(w) -> bool:
    """True for a quantized-leaf dict (``int8`` or grouped ``int4``)."""
    return isinstance(w, dict) and ("int8" in w or "int4" in w)


def _is_int4(w) -> bool:
    """True for a grouped-int4 leaf (``{"int4": [..., G, g, out], ...}``)."""
    return isinstance(w, dict) and "int4" in w


def _quantize_leaf(w: jax.Array, axis: int) -> dict:
    """Symmetric absmax int8 over ``axis`` (kept), scale in float32.

    Zero channels get scale 1/127 so q is exactly 0 and dequant exact.
    """
    amax = jnp.max(jnp.abs(w), axis=axis, keepdims=True)
    scale = jnp.where(amax > 0, amax, 1.0) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return {"int8": q, "scale": scale.astype(jnp.float32)}


def _quantize_leaf4(w: jax.Array, group: int) -> dict:
    """Grouped symmetric int4 over the contraction axis (``-2``).

    int4's 15 levels are too coarse for one scale per whole input column;
    the standard mitigation is group-wise scales: the ``in`` axis splits
    into groups of ``group`` (clipped to a divisor), each with its own
    absmax scale.  Stored as ``{"int4": [..., G, g, out],
    "scale": [..., G, 1, out]}`` — XLA bit-packs s4 two-per-byte on TPU,
    so the weight stream is half of int8's on the HBM-bound decode path.
    """
    *lead, din, dout = w.shape
    g = max(1, min(group, din))
    while din % g:
        g -= 1
    if g < min(group, din) and g < 8:
        # The divisor walk collapsed (e.g. a prime input dim): with
        # near-per-element f32 scales the "int4" tree streams MORE bytes
        # than bf16 — surface the cliff instead of silently labeling a
        # regression int4.
        import warnings

        warnings.warn(
            f"int4 group size degraded to {g} for input dim {din} "
            f"(requested {group}); scales now dominate the stream — "
            "pick a group_size dividing the model's inner dims",
            stacklevel=2)
    G = din // g
    wg = w.reshape(*lead, G, g, dout)
    amax = jnp.max(jnp.abs(wg), axis=-2, keepdims=True)
    scale = jnp.where(amax > 0, amax, 1.0) / 7.0
    q = jnp.clip(jnp.round(wg / scale), -7, 7).astype(jnp.int4)
    return {"int4": q, "scale": scale.astype(jnp.float32)}


def quantize_params(params: dict, *, bits: int = 8,
                    group_size: int = 128) -> dict:
    """Quantize an LM parameter tree (init_params layout) for serving.

    ``bits=8`` (default): dense/MoE matmul weights ``[.., in, out]``
    quantize per output channel (absmax over the contraction axis,
    ``axis=-2``); the embedding quantizes per row (``axis=-1``) because
    it is gathered, not contracted.  Norm weights and the MoE router stay
    float32.

    ``bits=4``: matmul weights quantize grouped int4 (``group_size``
    input channels per scale — see :func:`_quantize_leaf4`), halving the
    streamed bytes again vs int8.  The embedding stays int8 per-row: it
    is gathered O(batch) rows per step, not streamed whole, so coarser
    quantization there buys nothing and costs accuracy.
    """
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")

    def mat(w):
        return (_quantize_leaf(w, axis=-2) if bits == 8
                else _quantize_leaf4(w, group_size))

    layers = dict(params["layers"])
    for name in _LAYER_WEIGHTS:
        if name in layers:
            layers[name] = mat(layers[name])
    if "moe" in layers:
        moe = dict(layers["moe"])
        for name in ("w_gate", "w_up", "w_down"):
            moe[name] = mat(moe[name])
        layers["moe"] = moe
    out = dict(params)
    out["layers"] = layers
    out["embed"] = _quantize_leaf(params["embed"], axis=-1)
    out["lm_head"] = mat(params["lm_head"])
    return out


def qdot(x: jax.Array, w) -> jax.Array:
    """``x @ w`` for a raw, quantized, or LoRA-wrapped weight.

    Quantized int8: ``(x @ q) * s`` — scale applied after the contraction,
    so the dot's HBM read is the int8 tensor.  ``w`` may carry leading
    batch axes (a scan slice or a stacked expert table); the scale's kept
    ``in`` axis is squeezed to broadcast over the dot output.

    Grouped int4 is stricter: a leaf must be **scan-sliced first** —
    ``{"int4": [G, g, out], "scale": [G, 1, out]}`` with NO leading axes.
    The group einsum's ellipsis belongs to ``x``'s batch dims, so a
    still-stacked table (layer or expert axis) cannot broadcast against
    it — it would error on mismatched dims or, worse, broadcast silently
    wrong when they coincide.  Such weights are rejected loudly below;
    slice them (``jax.tree.map(lambda a: a[i], leaf)`` or ``lax.scan``)
    or contract via :func:`deq` instead.

    LoRA (``{"lora_base", "lora_a", "lora_b", "lora_scale"}`` — see
    workloads/lora.py): the frozen base dot (itself raw or quantized)
    plus the low-rank delta ``(x @ a) @ b * scale``.  The adapter math
    runs in f32 (a/b are f32 masters being trained) and casts once.
    """
    if isinstance(w, dict) and "lora_base" in w:
        base = qdot(x, w["lora_base"])
        xf = x.astype(jnp.float32)
        delta = (xf @ w["lora_a"]) @ w["lora_b"] * w["lora_scale"]
        return base + delta.astype(base.dtype)
    if _is_int4(w):
        # Grouped int4: per-group partial dots, scale, then sum over
        # groups.  The einsum reads the packed s4 tensor directly (the
        # convert fuses into the dot operand, as with int8); the group
        # axis adds one cheap [.., G, O] reduction.  Partials accumulate
        # in f32 (preferred_element_type + f32 scales) — at bf16 compute
        # a G-way chain of bf16 adds would stack ~eps*sqrt(G) error on
        # top of the quantization error; the cast back to x.dtype happens
        # once, after the group sum.
        # f32 operands rather than preferred_element_type: the CPU
        # backend's dot thunk rejects bf16 x bf16 = f32, and on TPU the
        # s4->f32 convert fuses into the dot operand exactly like
        # s4->bf16 would — the leg stays HBM-bound either way.
        if w["int4"].ndim > 3:
            raise ValueError(
                f"qdot int4 weight has leading axes (shape "
                f"{tuple(w['int4'].shape)}; want [groups, group, out]): "
                "scan-slice the stacked leaf before qdot, or use deq()")
        q = w["int4"].astype(jnp.float32)                 # [G, g, O]
        s = jnp.squeeze(w["scale"], axis=-2)              # [..., G, O] f32
        G, g = q.shape[-3], q.shape[-2]
        xg = x.reshape(*x.shape[:-1], G, g).astype(jnp.float32)
        part = jnp.einsum("...Gg,...Ggo->...Go", xg, q)
        return (part * s).sum(axis=-2).astype(x.dtype)
    if is_quantized(w):
        s = jnp.squeeze(w["scale"], axis=-2).astype(x.dtype)
        return (x @ w["int8"].astype(x.dtype)) * s
    return x @ w.astype(x.dtype)


def deq(w, dtype) -> jax.Array:
    """Materialize a weight at ``dtype`` (for einsum sites that contract
    over a non-standard axis — e.g. the MoE capacity dispatch).  Grouped
    int4 leaves merge their (G, g) axes back into the original ``in``."""
    if _is_int4(w):
        wf = w["int4"].astype(dtype) * w["scale"].astype(dtype)
        return wf.reshape(*wf.shape[:-3], wf.shape[-3] * wf.shape[-2],
                          wf.shape[-1])
    if is_quantized(w):
        return w["int8"].astype(dtype) * w["scale"].astype(dtype)
    return w.astype(dtype)


def deq_rows(w, idx: jax.Array, dtype) -> jax.Array:
    """Row-gather (embedding lookup) for a raw or row-quantized table."""
    if is_quantized(w):
        return w["int8"][idx].astype(dtype) * w["scale"][idx].astype(dtype)
    return w.astype(dtype)[idx]


def quantize_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Quantize K or V rows for an int8 KV cache: symmetric absmax over
    the head_dim (last axis, kept), one f32 scale per (batch, position,
    kv-head).  At long context the cache read — not the weight stream —
    dominates decode's HBM traffic; int8 halves it.  The scales fold
    exactly into the attention einsums (per key position into the logits,
    per value position into the probabilities), so the cache is read at
    int8 with no dequantized copy."""
    d = _quantize_leaf(x, axis=-1)
    return d["int8"], d["scale"]


def fold_kv_scale(s: jax.Array) -> jax.Array:
    """[B, S, KV, 1] cache scales -> [B, KV, 1, 1, S], the broadcast
    layout of the grouped-GQA attention einsums' ``bkgts`` output — the
    per-key-position factor that makes the int8 contraction exact."""
    return jnp.moveaxis(s[..., 0], 1, -1)[:, :, None, None, :]


def streamed_bytes(params: dict, compute_itemsize: int = 2) -> int:
    """Bytes a decode step streams from HBM for this parameter tree.

    Every weight except the embedding (gathered, O(B) rows) is read once
    per step: quantized leaves stream int8 + their f32 scales; raw matmul
    weights — dense projections, MoE expert tables, the lm_head — stream
    at the model's COMPUTE dtype (``compute_itemsize`` bytes: 2 for the
    bf16 default; pass 4 for a compute_dtype=float32 model, whose casts
    are no-ops), because the model consumes every one of them through a
    cast-to-compute-dtype dot whose loop-invariant cast XLA hoists out of
    the decode scan.  Norms and the router are consumed at f32.  Mirrors
    the accounting bench_decode uses for the ceiling.
    """
    matmul_names = _LAYER_WEIGHTS + ("lm_head",)

    def leaf_bytes(name: str, v) -> int:
        if _is_int4(v):
            # XLA bit-packs s4 two-per-byte on TPU.
            return v["int4"].size // 2 + v["scale"].size * 4
        if is_quantized(v):
            return v["int8"].size + v["scale"].size * 4
        return v.size * (compute_itemsize if name in matmul_names else 4)

    total = 0

    def walk(tree: dict):
        nonlocal total
        for k, v in tree.items():
            if isinstance(v, dict) and not is_quantized(v):
                walk(v)
            else:
                total += leaf_bytes(k, v)

    walk(params["layers"])
    total += leaf_bytes("final_norm", params["final_norm"])
    total += leaf_bytes("lm_head", params["lm_head"])
    return total
