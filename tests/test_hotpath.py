"""Fleet hot-path elimination (ISSUE 13): the single-owner in-place fold,
the incremental score index, the copy-free fakeapi write path, and the GC
next-expiry watermark — each leg's equivalence property and its kill
switch, plus the all-switches-off report identity that pins the legacy
paths byte-for-byte."""

from __future__ import annotations

import copy as copymod
import json
import random

import pytest

from tests.cluster import build_cluster
from tputopo.extender.config import ExtenderConfig
from tputopo.extender.gc import AssumptionGC
from tputopo.extender.scheduler import ExtenderScheduler, Metrics
from tputopo.extender.state import ClusterState
from tputopo.k8s import objects as ko
from tputopo.k8s.fakeapi import FakeApiServer
from tputopo.k8s.objects import make_pod


class _Clock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t


def _sync(api, clock):
    return ClusterState(api, clock=clock).sync()


def _bind_pod(api, name, node, chips, clock, *, assigned=False, gang=None):
    anns = {
        ko.ANN_GROUP: ko.coords_to_ann(chips),
        ko.ANN_ASSUME_TIME: str(clock()),
        ko.ANN_ASSIGNED: "true" if assigned else "false",
    }
    if gang:
        anns[ko.ANN_GANG_ID] = gang
    api.create("pods", make_pod(name, chips=len(chips), annotations=anns,
                                node_name=node))
    return api.get("pods", name, "default")


def _state_facts(state: ClusterState) -> dict:
    """Everything the fold equivalence contract covers: the pod index
    (records + status + held chips), per-domain occupancy and derived
    lists, the conflict/expiry ledgers, and the sync-time cursor."""
    return {
        "pod_index": {
            key: (rec.sid, rec.status, tuple(rec.held),
                  rec.pa.node_name, tuple(map(tuple, rec.pa.chips)),
                  rec.pa.assigned, rec.pa.assume_time, rec.pa.gang_id)
            for key, rec in state._pod_index.items()
        },
        "occupancy": {sid: dom.allocator.used_mask
                      for sid, dom in state.domains.items()},
        "unhealthy": {sid: frozenset(dom.unhealthy)
                      for sid, dom in state.domains.items()},
        "assignments": {
            sid: sorted(f"{pa.namespace}/{pa.pod_name}"
                        for pa in dom.assignments)
            for sid, dom in state.domains.items()
        },
        "expired": sorted(f"{pa.namespace}/{pa.pod_name}"
                          for pa in state.expired),
        "conflicts": sorted(f"{pa.namespace}/{pa.pod_name}"
                            for pa in state.conflicts),
        "synced_at": state._synced_at,
    }


def _random_event(api, clock, rng, live, step):
    """One random cluster mutation + its informer-vocabulary event —
    the same op mix as test_state_delta's fold fuzz."""
    topo_chips = [(x, y, z) for x in range(2) for y in range(2)
                  for z in range(4)]
    op = rng.random()
    clock.t += rng.random()
    if op < 0.4 or not live:
        name = f"p{step}"
        node = f"node-{rng.randrange(4)}"
        k = rng.choice([1, 2, 4])
        free = set(_sync(api, clock).free_chips_on_node(node))
        chips = sorted(free)[:k]
        if len(chips) < k:
            return None
        obj = _bind_pod(api, name, node, chips, clock,
                        assigned=rng.random() < 0.5)
        live.append(name)
        return ("pods", "ADDED", obj)
    if op < 0.6:
        name = rng.choice(live)
        api.patch_annotations("pods", name, {ko.ANN_ASSIGNED: "true"},
                              namespace="default")
        return ("pods", "MODIFIED", api.get("pods", name, "default"))
    if op < 0.8:
        name = live.pop(rng.randrange(len(live)))
        api.patch_annotations("pods", name,
                              {ko.ANN_GROUP: None, ko.ANN_ASSIGNED: None,
                               ko.ANN_ASSUME_TIME: None},
                              namespace="default")
        return ("pods", "MODIFIED", api.get("pods", name, "default"))
    if op < 0.9:
        name = live.pop(rng.randrange(len(live)))
        obj = api.get("pods", name, "default")
        api.delete("pods", name, "default")
        return ("pods", "DELETED", obj)
    node = f"node-{rng.randrange(4)}"
    bad = rng.sample(topo_chips, rng.randrange(0, 3))
    api.patch_annotations(
        "nodes", node,
        {ko.ANN_UNHEALTHY: ko.coords_to_ann(bad) if bad else None})
    return ("nodes", "MODIFIED", api.get("nodes", node))


# ---- leg 1: single-owner in-place fold ---------------------------------------


def test_fold_inplace_matches_cow_over_random_event_streams():
    """Property: fold_inplace and _cow+with_events produce EQUAL states
    (pod index, occupancy, derived lists, sync cursor) across randomized
    event streams, and agree on when a fold is unappliable."""
    clock = _Clock()
    api, _ = build_cluster(clock=clock)
    rng = random.Random(23)
    cow_state = _sync(api, clock)
    inp_state = _sync(api, clock)
    live: list[str] = []
    folds = fallbacks = 0
    for step in range(140):
        event = _random_event(api, clock, rng, live, step)
        if event is None:
            continue
        cow_reasons: list[str] = []
        inp_reasons: list[str] = []
        cow_new = cow_state.with_events([event], cow_reasons)
        inp_new = inp_state.fold_inplace([event], inp_reasons)
        assert (cow_new is None) == (inp_new is None), (step, event[:2])
        if cow_new is None:
            assert cow_reasons == inp_reasons
            fallbacks += 1
            cow_state = _sync(api, clock)
            inp_state = _sync(api, clock)
            continue
        folds += 1
        assert inp_new is inp_state  # mutated, not replaced
        cow_state = cow_new
        assert _state_facts(cow_state) == _state_facts(inp_state), \
            (step, event[:2])
    assert folds > 40  # the fuzz actually exercised the fold path


def test_fold_inplace_kill_switch_restores_cow():
    clock = _Clock()
    api, _ = build_cluster(clock=clock)
    state = _sync(api, clock)
    obj = _bind_pod(api, "p", "node-0", [(0, 0, 0)], clock)
    try:
        ClusterState.FOLD_INPLACE = False
        new = state.fold_inplace([("pods", "ADDED", obj)])
        # Feature-off: a copy-on-write clone, receiver untouched.
        assert new is not None and new is not state
        assert (0, 0, 0) in state.free_chips_on_node("node-0")
        assert (0, 0, 0) not in new.free_chips_on_node("node-0")
    finally:
        ClusterState.FOLD_INPLACE = True
    new2 = state.fold_inplace([("pods", "ADDED", obj)])
    assert new2 is state  # feature-on: mutation in place
    assert (0, 0, 0) not in state.free_chips_on_node("node-0")


def test_fold_inplace_failure_means_discard():
    """A None from fold_inplace may leave the state partially mutated —
    the contract is 'discard and full-sync', which must land on the same
    facts as a fresh sync."""
    clock = _Clock()
    api, _ = build_cluster(clock=clock)
    _bind_pod(api, "a", "node-0", [(0, 0, 0)], clock)
    state = _sync(api, clock)
    overlap = _bind_pod(api, "b", "node-0", [(0, 0, 0)], clock)
    reasons: list[str] = []
    assert state.fold_inplace([("pods", "ADDED", overlap)], reasons) is None
    assert reasons == ["overlap"]
    assert _state_facts(_sync(api, clock)) == _state_facts(_sync(api, clock))


# ---- leg 2: incremental score index ------------------------------------------


def _index_matches_uncached(sched, state):
    idx = getattr(state, "_score_index", None) or {}
    for k, kd in idx.items():
        for node, score in kd.items():
            assert score == sched._score_node_uncached(state, k, node), \
                (k, node)


def test_score_index_matches_uncached_after_every_fold():
    """Property: every (k, node) entry the index holds equals a fresh
    _score_node_uncached against the CURRENT state, after sorts, event
    folds, and bind deltas."""
    clock = _Clock()
    api, _ = build_cluster(clock=clock)
    sched = ExtenderScheduler(
        api, ExtenderConfig(state_cache_s=1e12, bind_from_cache=True),
        clock=clock)
    nodes = [f"node-{i}" for i in range(4)]
    api.create("pods", make_pod("q0", chips=2))
    sched.sort(api.get("pods", "q0", "default"), nodes)
    state = sched._cached_state
    assert state is not None and state._score_index
    _index_matches_uncached(sched, state)
    # Bind delta: the bound domain's entries are evicted; survivors
    # still match (here: one domain, so the index empties and refills).
    sched.bind("q0", "default", "node-1")
    assert sched._cached_state is state  # in-place single-owner delta
    _index_matches_uncached(sched, state)
    api.create("pods", make_pod("q1", chips=4))
    sched.sort(api.get("pods", "q1", "default"), nodes)
    _index_matches_uncached(sched, state)
    # Out-of-band fold (the engine's invalidate path): a wipe releases
    # chips — the index must never serve a pre-release score.
    api.patch_annotations("pods", "q0",
                          {ko.ANN_GROUP: None, ko.ANN_ASSIGNED: None,
                           ko.ANN_ASSUME_TIME: None}, namespace="default")
    sched.apply_events([("pods", "MODIFIED",
                         api.get("pods", "q0", "default"))])
    assert sched._cached_state is state
    sched.sort(api.get("pods", "q1", "default"), nodes)
    _index_matches_uncached(sched, state)


def test_score_index_scores_equal_legacy_memo_scores():
    """The index and the legacy (k, node) memo must hand back identical
    scores and identical hit counters for the same sort sequence."""
    def run(score_index: bool):
        clock = _Clock()
        api, _ = build_cluster(clock=clock)
        sched = ExtenderScheduler(
            api, ExtenderConfig(state_cache_s=1e12, bind_from_cache=True),
            clock=clock)
        try:
            ExtenderScheduler.SCORE_INDEX = score_index
            nodes = [f"node-{i}" for i in range(4)]
            out = []
            for i, k in enumerate((1, 2, 2, 4, 1)):
                api.create("pods", make_pod(f"s{i}", chips=k))
                out.append(sched.sort(api.get("pods", f"s{i}", "default"),
                                      nodes))
            return out, sched.metrics.counters.get("score_memo_hits", 0)
        finally:
            ExtenderScheduler.SCORE_INDEX = True

    with_index = run(True)
    legacy = run(False)
    assert with_index == legacy


def test_sort_best_equals_max_over_sort():
    """sort_best must select exactly the entry max(sort(...), key=(Score,
    Host)) selects — gang and single-pod shapes, traced and untraced —
    or None precisely when nothing scores positive."""
    from tputopo.extender.scheduler import BEST_SCORE_KEY
    from tputopo.obs import Tracer

    def check(sched, pod, nodes):
        scores = sched.sort(pod, nodes)
        legacy = max(scores, key=BEST_SCORE_KEY) if scores else None
        got = sched.sort_best(pod, nodes)
        if legacy is None or legacy["Score"] <= 0:
            assert got is None or got == legacy  # same infeasible branch
        else:
            assert got == legacy

    for tracer in (None, "on"):
        clock = _Clock()
        api, _ = build_cluster(clock=clock)
        kwargs = {"clock": clock}
        if tracer:
            kwargs["tracer"] = Tracer(capacity=16, clock=clock)
        sched = ExtenderScheduler(
            api, ExtenderConfig(state_cache_s=1e12, bind_from_cache=True),
            **kwargs)
        nodes = [f"node-{i}" for i in range(4)]
        api.create("pods", make_pod("single", chips=2))
        check(sched, api.get("pods", "single", "default"), nodes)
        gang_labels = {"tpu.dev/gang-id": "g", "tpu.dev/gang-size": "2"}
        for m in range(2):
            api.create("pods", make_pod(f"g-{m}", chips=4,
                                        labels=gang_labels))
        check(sched, api.get("pods", "g-0", "default"), nodes)
        # Infeasible (too big) and empty-candidate shapes.
        api.create("pods", make_pod("huge", chips=64))
        check(sched, api.get("pods", "huge", "default"), nodes)
        check(sched, api.get("pods", "single", "default"), [])


# ---- leg 3: copy-free fakeapi write path -------------------------------------


def test_nocopy_writes_structural_sharing_and_frozen_snapshots():
    api = FakeApiServer(nocopy_writes=True)
    api.create("pods", make_pod("p0", chips=2), echo=False)
    before = api.get_nocopy("pods", "p0", "default")
    rv_before = before["metadata"]["resourceVersion"]
    patched = api.patch_annotations("pods", "p0", {"a": "1"}, "default")
    after = api.get_nocopy("pods", "p0", "default")
    # The write REPLACED the stored incarnation...
    assert patched is after and after is not before
    # ...sharing the untouched substructure...
    assert after["spec"] is before["spec"]
    assert after["status"] is before["status"]
    # ...and the old reference is frozen at its resourceVersion.
    assert before["metadata"]["resourceVersion"] == rv_before
    assert "a" not in (before["metadata"].get("annotations") or {})
    assert after["metadata"]["annotations"]["a"] == "1"
    # bind_pod: fresh spec/status dicts, metadata bumped, store replaced.
    bound = api.bind_pod("p0", "node-7", "default")
    assert bound is api.get_nocopy("pods", "p0", "default")
    assert bound["spec"]["nodeName"] == "node-7"
    assert "nodeName" not in after["spec"]  # prior incarnation frozen
    # delete: the popped object is not mutated by the delete's rv bump.
    rv_bound = bound["metadata"]["resourceVersion"]
    api.delete("pods", "p0", "default")
    assert bound["metadata"]["resourceVersion"] == rv_bound


def test_nocopy_writes_zero_deepcopies_on_the_write_path(monkeypatch):
    real = copymod.deepcopy
    calls = {"n": 0}

    def counting(x, memo=None, _nil=[]):  # noqa: B006 — mirrors copy.deepcopy's real signature
        calls["n"] += 1
        return real(x, memo)

    monkeypatch.setattr(copymod, "deepcopy", counting)
    api = FakeApiServer(nocopy_writes=True)
    calls["n"] = 0
    api.create_many("pods", [make_pod(f"p{i}", chips=1) for i in range(3)])
    api.patch_annotations("pods", "p0", {"a": "1"}, "default")
    api.patch_labels("pods", "p1", {"l": "1"}, "default")
    api.bind_pod("p2", "node-0", "default")
    api.delete("pods", "p1", "default")
    assert calls["n"] == 0  # the whole write path is copy-free unwatched
    # Reads through the copying API still deepcopy (contract unchanged).
    api.get("pods", "p0", "default")
    assert calls["n"] == 1


def test_nocopy_writes_keeps_meta_index_and_watch_semantics():
    api = FakeApiServer(nocopy_writes=True)
    api.create("pods", make_pod("g0", chips=1,
                                labels={"tpu.dev/gang-id": "g"}),
               echo=False)
    api.bind_pod("g0", "node-1", "default")
    # The meta index must track the REPLACED incarnation, not the stale one.
    hits = api.list_by_meta("pods", "tpu.dev/gang-id", "g", copy=False)
    assert [p["spec"].get("nodeName") for p in hits] == ["node-1"]
    # Watch events are still deepcopied at emit once a consumer attaches.
    _, rv = api.list_with_version("pods")
    api.patch_annotations("pods", "g0", {"x": "1"}, "default")
    events = [e for e in api.watch("pods", rv, timeout_s=0.05)
              if e["type"] != "BOOKMARK"]
    assert len(events) == 1
    stored = api.get_nocopy("pods", "g0", "default")
    assert events[0]["object"] is not stored
    assert events[0]["object"]["metadata"]["annotations"]["x"] == "1"


def test_assignment_index_tracks_group_annotation():
    api = FakeApiServer()
    clock = _Clock()
    assert api.list_assignments() == []
    _bind_pod(api, "held", "node-0", [(0, 0, 0)], clock)
    api.create("pods", make_pod("pending", chips=2), echo=False)
    assert [p["metadata"]["name"] for p in api.list_assignments()] \
        == ["held"]
    # Wipe removes it from the index; re-stamp restores it; delete drops it.
    api.patch_annotations("pods", "held", {ko.ANN_GROUP: None}, "default")
    assert api.list_assignments() == []
    api.patch_annotations("pods", "held",
                          {ko.ANN_GROUP: "0,0,0"}, "default")
    assert [p["metadata"]["name"] for p in api.list_assignments()] \
        == ["held"]
    api.delete("pods", "held", "default")
    assert api.list_assignments() == []


# ---- leg 4: GC next-expiry watermark -----------------------------------------


def _stale_pod(api, clock, name="stale-0", assume_t=0.0):
    api.create("pods", make_pod(name, chips=2), echo=False)
    api.patch_annotations("pods", name, {
        ko.ANN_GROUP: "0,0,0;1,0,0",
        ko.ANN_ASSUME_TIME: str(assume_t),
        ko.ANN_ASSIGNED: "false",
    }, "default")
    api.bind_pod(name, "node-0", "default")


def test_watermark_skips_provably_empty_sweeps():
    clock = _Clock(t=100.0)
    api, _ = build_cluster(clock=clock)
    _stale_pod(api, clock, assume_t=90.0)  # 10 s old, TTL 60
    metrics = Metrics()
    gc = AssumptionGC(api, assume_ttl_s=60.0, clock=clock, metrics=metrics)
    assert gc.sweep() == []  # first sweep always scans
    assert metrics.counters.get("gc_sweeps_skipped", 0) == 0
    clock.t = 120.0
    assert gc.sweep() == []  # provably empty: skipped without a scan
    assert metrics.counters["gc_sweeps_skipped"] == 1
    assert metrics.counters["gc_sweeps"] == 2
    clock.t = 151.0  # 90 + 60 < 151: the assumption expired — must scan
    assert gc.sweep() == ["default/stale-0"]
    anns = api.get("pods", "stale-0", "default")["metadata"]["annotations"]
    assert ko.ANN_GROUP not in anns


def test_watermark_kill_switch_scans_every_sweep():
    clock = _Clock(t=100.0)
    api, _ = build_cluster(clock=clock)
    _stale_pod(api, clock, assume_t=90.0)
    metrics = Metrics()
    gc = AssumptionGC(api, assume_ttl_s=60.0, clock=clock, metrics=metrics)
    try:
        AssumptionGC.WATERMARK = False
        assert gc.sweep() == []
        clock.t = 120.0
        assert gc.sweep() == []
        assert "gc_sweeps_skipped" not in metrics.counters
        clock.t = 151.0
        assert gc.sweep() == ["default/stale-0"]
    finally:
        AssumptionGC.WATERMARK = True


def test_failed_release_keeps_the_next_sweep_scanning():
    """A victim whose release patch failed stays expired — the watermark
    must keep the NEXT sweep scanning so the retry happens (the chaos
    liveness contract)."""
    from tputopo.k8s.retry import ApiUnavailable

    class _FlakyPatch:
        def __init__(self, api, failures):
            self._api = api
            self.failures = failures

        def __getattr__(self, name):
            return getattr(self._api, name)

        def patch_annotations(self, *a, **kw):
            if self.failures > 0:
                self.failures -= 1
                raise ApiUnavailable("injected")
            return self._api.patch_annotations(*a, **kw)

    clock = _Clock(t=1000.0)
    api, _ = build_cluster(clock=clock)
    _stale_pod(api, clock, assume_t=0.0)  # long expired
    gc = AssumptionGC(_FlakyPatch(api, failures=1), assume_ttl_s=60.0,
                      clock=clock)
    assert gc.sweep() == []  # release failed transiently
    clock.t += 1.0
    assert gc.sweep() == ["default/stale-0"]  # NOT skipped: retried


def test_gc_fallback_lister_for_index_less_readers():
    """A reader without list_assignments (no assignment index) must fall
    back to the whole-store scan with identical victims."""

    class _Plain:
        list_assignments = None  # getattr(...) or-falls-through

        def __init__(self, api):
            self._api = api

        def __getattr__(self, name):
            return getattr(self._api, name)

    clock = _Clock(t=1000.0)
    api, _ = build_cluster(clock=clock)
    _stale_pod(api, clock, assume_t=0.0)
    gc = AssumptionGC(_Plain(api), assume_ttl_s=60.0, clock=clock)
    assert gc.sweep() == ["default/stale-0"]


# ---- all four kill switches: the legacy paths stay byte-identical ------------


def _run_small_trace(chaos=None):
    from tputopo.sim.engine import run_trace
    from tputopo.sim.trace import TraceConfig

    report = run_trace(TraceConfig(seed=0, nodes=16, arrivals=60),
                       ["ici", "naive"], chaos=chaos)
    report.pop("throughput", None)
    report.pop("phase_wall", None)
    for pol in report.get("policies", {}).values():
        # The XL hot-path fold counter is presence-gated: it exists ONLY
        # when DIRTY_FOLD fired, so the on-run carries it and the
        # off-run (byte-identical to the pre-switch schema) must not.
        # Strip it so the identity assertion covers everything else.
        # (The pass's probe/memo counters never reach sim reports — they
        # are outside the keep-list by the gang_domains_screened rule.)
        pol.get("scheduler", {}).pop("state_dirty_folds", None)
    return json.dumps(report, sort_keys=True)


@pytest.mark.parametrize("chaos", [None, "api-flake"])
def test_all_kill_switches_off_report_is_byte_identical(chaos):
    """Flipping every leg off must reproduce the optimized run's report
    byte-for-byte (minus the wall blocks) — the legs are pure mechanics,
    never policy.  Covers the original four fleet hot-path switches AND
    the XL hot-path pass's six (mask probes, dirty folds, annotation
    templates, capacity memo, assignment-parse cache, plan-state reuse)."""
    from tputopo.sim.engine import SimEngine

    on = _run_small_trace(chaos=chaos)
    try:
        ClusterState.FOLD_INPLACE = False
        ExtenderScheduler.SCORE_INDEX = False
        SimEngine.NOCOPY_WRITES = False
        AssumptionGC.WATERMARK = False
        ExtenderScheduler.VECTOR_CAP_MEMO = False
        ExtenderScheduler.DIRTY_FOLD = False
        ExtenderScheduler.BIND_ANN_TEMPLATE = False
        ExtenderScheduler.MASK_GANG_PROBE = False
        ClusterState.PA_CACHE = False
        SimEngine.PLAN_STATE_REUSE = False
        off = _run_small_trace(chaos=chaos)
    finally:
        ClusterState.FOLD_INPLACE = True
        ExtenderScheduler.SCORE_INDEX = True
        SimEngine.NOCOPY_WRITES = True
        AssumptionGC.WATERMARK = True
        ExtenderScheduler.VECTOR_CAP_MEMO = True
        ExtenderScheduler.DIRTY_FOLD = True
        ExtenderScheduler.BIND_ANN_TEMPLATE = True
        ExtenderScheduler.MASK_GANG_PROBE = True
        ClusterState.PA_CACHE = True
        SimEngine.PLAN_STATE_REUSE = True
    assert on == off
