"""Flight recorder (tputopo.obs) + observability surfaces: Prometheus
exposition conformance of /metrics, /debug/traces shape and the gang-bind
explain record, the per-reason state-delta fallback split, GC sweep
metrics, the decision-buffer retention knob, and the sim's deterministic
phases/explain/first-divergence contract."""

import json
import urllib.error
import urllib.request

import pytest

from tests.cluster import build_cluster
from tputopo.extender import (ExtenderConfig, ExtenderHTTPServer,
                              ExtenderScheduler)
from tputopo.k8s import make_pod
from tputopo.obs import NULL_TRACER, Tracer


@pytest.fixture()
def server():
    api, _ = build_cluster()
    config = ExtenderConfig()
    sched = ExtenderScheduler(api, config)
    srv = ExtenderHTTPServer(sched, config, port=0).start()
    yield api, sched, srv
    srv.stop()


def post(srv, path, payload):
    host, port = srv.address
    req = urllib.request.Request(
        f"http://{host}:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=5) as resp:
        return resp.status, json.loads(resp.read())


def get(srv, path):
    host, port = srv.address
    with urllib.request.urlopen(f"http://{host}:{port}{path}",
                                timeout=5) as resp:
        return resp.status, resp.read().decode()


def _bind_gang(api, srv, gang_id="g1", size=2, chips=4):
    labels = {"tpu.dev/gang-id": gang_id, "tpu.dev/gang-size": str(size)}
    for m in range(size):
        api.create("pods", make_pod(f"{gang_id}-{m}", chips=chips,
                                    labels=labels))
    pod = api.get("pods", f"{gang_id}-0", "default")
    _, scores = post(srv, "/tputopo-scheduler/sort",
                     {"Pod": pod,
                      "NodeNames": [f"node-{i}" for i in range(4)]})
    best = max(scores, key=lambda s: (s["Score"], s["Host"]))
    status, res = post(srv, "/tputopo-scheduler/bind",
                       {"PodName": f"{gang_id}-0",
                        "PodNamespace": "default", "Node": best["Host"]})
    assert status == 200 and res["Error"] == ""


# ---- /metrics: Prometheus exposition conformance ---------------------------


def _parse_exposition(text):
    """{family: {"help": ..., "type": ..., "samples": [(name, labels, value)]}}
    — enforcing that HELP/TYPE precede their family's samples."""
    families, current = {}, None
    for line in text.strip().splitlines():
        if line.startswith("# HELP "):
            _, _, name, help_text = line.split(" ", 3)
            current = families.setdefault(
                name, {"help": None, "type": None, "samples": []})
            current["help"] = help_text
        elif line.startswith("# TYPE "):
            _, _, name, mtype = line.split(" ", 3)
            assert name in families, f"TYPE before HELP for {name}"
            families[name]["type"] = mtype
        else:
            metric, value = line.rsplit(" ", 1)
            labels = ""
            if "{" in metric:
                metric, labels = metric.split("{", 1)
                labels = "{" + labels
            base = metric
            for suffix in ("_bucket", "_sum", "_count"):
                if base.endswith(suffix) and base[: -len(suffix)] in families:
                    base = base[: -len(suffix)]
                    break
            assert base in families, f"sample {metric} without HELP/TYPE"
            families[base]["samples"].append((metric, labels, float(value)))
    return families


def test_metrics_prometheus_conformance(server):
    api, sched, srv = server
    _bind_gang(api, srv)
    _, text = get(srv, "/metrics")
    families = _parse_exposition(text)
    # Every family carries both a HELP and a TYPE, and at least one sample.
    for name, fam in families.items():
        assert fam["help"], name
        assert fam["type"] in ("counter", "gauge", "histogram"), name
        assert fam["samples"], name
    # Counters end in _total (Prometheus naming convention).
    for name, fam in families.items():
        if fam["type"] == "counter":
            assert name.endswith("_total"), name
    # Histogram contract for each verb that observed latency.
    for verb in ("sort", "bind"):
        fam = families[f"tputopo_extender_{verb}_latency_ms"]
        assert fam["type"] == "histogram"
        buckets = [(labels, v) for metric, labels, v in fam["samples"]
                   if metric.endswith("_bucket")]
        counts = [v for _, v in buckets]
        assert counts == sorted(counts), f"{verb} buckets not monotone"
        assert buckets[-1][0] == '{le="+Inf"}'
        count = next(v for metric, _, v in fam["samples"]
                     if metric.endswith("_count"))
        total = next(v for metric, _, v in fam["samples"]
                     if metric.endswith("_sum"))
        assert counts[-1] == count  # +Inf bucket == _count
        assert count == len(sched.metrics.latencies_ms[verb])
        # The exposition rounds _sum to 3 decimals, so the right bound is
        # ABSOLUTE 5e-4 (a rel tolerance on a small wall-clock sum flaked
        # whenever the true value sat just past the rounding midpoint).
        assert total == pytest.approx(
            sum(sched.metrics.latencies_ms[verb]), rel=1e-3, abs=5.1e-4)
    # The quantile gauges survive alongside the histograms.
    assert families["tputopo_extender_sort_latency_p95_ms"]["type"] == "gauge"
    # build_info and the buffer gauges.
    assert families["tputopo_extender_build_info"]["samples"][0][2] == 1.0
    assert "version=" in families["tputopo_extender_build_info"]["samples"][0][1]
    assert families["tputopo_extender_decisions_buffer_len"]["samples"][0][2] == 1.0


def test_metrics_informer_gauges():
    """With an informer wired, /metrics exports synced/journal-depth
    gauges and the informer's own counters."""
    from tputopo.k8s.informer import Informer

    api, _ = build_cluster()
    informer = Informer(api, watch_timeout_s=2.0).start()
    try:
        informer.wait_synced()
        config = ExtenderConfig()
        sched = ExtenderScheduler(api, config, informer=informer)
        srv = ExtenderHTTPServer(sched, config, port=0).start()
        try:
            _, text = get(srv, "/metrics")
            families = _parse_exposition(text)
            assert families["tputopo_extender_informer_synced"][
                "samples"][0][2] == 1.0
            assert "tputopo_extender_informer_journal_len" in families
            assert families["tputopo_extender_informer_lists_total"][
                "samples"][0][2] >= 2.0
        finally:
            srv.stop()
    finally:
        informer.stop()


# ---- /debug/traces ---------------------------------------------------------


def test_debug_traces_gang_bind_explain(server):
    """The acceptance shape: after a gang bind, /debug/traces?n=1 returns
    a trace with nested phase spans and an explain record naming at least
    one scored node and one rejected node with a structured reason."""
    api, sched, srv = server
    _bind_gang(api, srv)  # 2x4-chip gang planned over 2 of 4 nodes
    status, raw = get(srv, "/debug/traces?n=1")
    assert status == 200
    body = json.loads(raw)
    assert body["enabled"] is True
    assert body["recorded"] >= 2  # the sort + the bind
    (trace,) = body["traces"]
    assert trace["verb"] == "bind"
    phase_names = [p["name"] for p in trace["phases"]]
    assert {"state", "plan", "cas_patch", "publish"} <= set(phase_names)
    # Nested spans: the state phase shows HOW the state was obtained.
    state_phase = next(p for p in trace["phases"] if p["name"] == "state")
    assert state_phase.get("children") or state_phase.get("counters")
    ex = trace["explain"]
    assert ex["verb"] == "bind" and ex["gang"]["id"] == "g1"
    scored = [n for n in ex["nodes"] if "score_gbps" in n]
    rejected = [n for n in ex["nodes"] if "rejected" in n]
    assert scored and rejected
    assert any(n.get("chosen") for n in scored)
    assert rejected[0]["rejected"] in (
        "not_in_gang_plan", "insufficient_free_chips",
        "gang_domain_mismatch", "wrong_generation")
    assert ex["gang"]["plan_nodes"]  # the chosen plan is named


def test_debug_traces_n_param_and_sort_explain(server):
    api, sched, srv = server
    _bind_gang(api, srv)
    _, raw = get(srv, "/debug/traces?n=2")
    traces = json.loads(raw)["traces"]
    assert [t["verb"] for t in traces] == ["sort", "bind"]
    sort_ex = traces[0]["explain"]
    assert len(sort_ex["nodes"]) == 4  # every candidate got a verdict
    assert all("score" in n for n in sort_ex["nodes"])
    # Bad n is a 400, not a 503.
    with pytest.raises(urllib.error.HTTPError) as e:
        get(srv, "/debug/traces?n=bogus")
    assert e.value.code == 400


def test_null_tracer_serves_empty(server):
    api, _, _ = server
    config = ExtenderConfig(trace_enabled=False)
    sched = ExtenderScheduler(api, config)
    assert sched.tracer is NULL_TRACER
    srv = ExtenderHTTPServer(sched, config, port=0).start()
    try:
        api.create("pods", make_pod("solo", chips=1))
        pod = api.get("pods", "solo", "default")
        post(srv, "/tputopo-scheduler/sort",
             {"Pod": pod, "NodeNames": ["node-0"]})
        _, raw = get(srv, "/debug/traces?n=5")
        body = json.loads(raw)
        assert body == {"enabled": False, "recorded": 0, "traces": []}
    finally:
        srv.stop()


def test_tracer_traces_n_bounds_are_strict():
    """traces(n<=0) must return nothing, not the whole ring (buf[-0:])."""
    tracer = Tracer(capacity=8)
    for i in range(5):
        with tracer.start("verb", i=i):
            pass
    assert tracer.traces(0) == []
    assert tracer.traces(-3) == []
    assert len(tracer.traces(2)) == 2
    assert len(tracer.traces(100)) == 5


def test_explain_rejections_are_capped(monkeypatch):
    """On a fleet wider than the cap, explain records keep the scored/
    planned nodes and collapse excess rejections into nodes_omitted —
    a record must stay KB-sized at thousands of nodes."""
    monkeypatch.setattr(ExtenderScheduler, "_EXPLAIN_REJECT_CAP", 2)
    api, _ = build_cluster()  # 4 nodes: 1 chosen + 3 rejections for k=1
    sched = ExtenderScheduler(api, ExtenderConfig())
    api.create("pods", make_pod("solo", chips=1))
    pod = api.get("pods", "solo", "default")
    sched.sort(pod, [f"node-{i}" for i in range(4)])
    sched.bind("solo", "default", "node-0")
    bind_ex = sched.tracer.last_explain
    rejected = [n for n in bind_ex["nodes"] if "rejected" in n]
    assert len(rejected) == 2
    assert bind_ex["nodes_omitted"] == 1
    assert any(n.get("chosen") for n in bind_ex["nodes"])


# ---- satellite: fallback reason split, GC metrics, retention knob ----------


def test_state_delta_fallback_reasons_are_split():
    from tputopo.k8s import objects as ko

    api, _ = build_cluster()
    sched = ExtenderScheduler(
        api, ExtenderConfig(state_cache_s=1e12, bind_from_cache=True))
    api.create("pods", make_pod("seed-pod", chips=4))
    sched.bind("seed-pod", "default", "node-0")
    state = sched._state(allow_cache=True)
    assert sched._cached_state is state
    # Node churn: a known node's DELETED event cannot fold.
    node = api.get("nodes", "node-0")
    sched.apply_events([("nodes", "DELETED", node)])
    c = sched.metrics.counters
    assert c["state_delta_fallbacks"] == 1
    assert c["state_delta_fallback_node_churn"] == 1
    # Overlap: a pod event claiming already-held chips cannot fold.
    state = sched._state(allow_cache=True)
    held = sched.api.get("pods", "seed-pod", "default")
    anns = held["metadata"]["annotations"]
    clash = {
        "metadata": {"name": "clash", "namespace": "default",
                     "annotations": {
                         ko.ANN_GROUP: anns[ko.ANN_GROUP],
                         ko.ANN_ASSUME_TIME: anns[ko.ANN_ASSUME_TIME],
                         ko.ANN_ASSIGNED: "false"}},
        "spec": {"nodeName": held["spec"]["nodeName"]},
    }
    sched.apply_events([("pods", "ADDED", clash)])
    c = sched.metrics.counters
    assert c["state_delta_fallbacks"] == 2
    assert c["state_delta_fallback_overlap"] == 1


def test_gc_sweeps_are_observable():
    from tputopo.extender.gc import AssumptionGC
    from tputopo.extender.scheduler import Metrics
    from tputopo.k8s import objects as ko

    api, _ = build_cluster()
    clock = [1000.0]
    sched = ExtenderScheduler(api, ExtenderConfig(),
                              clock=lambda: clock[0])
    api.create("pods", make_pod("stale", chips=2))
    sched.bind("stale", "default", "node-0")
    metrics = Metrics()
    gc = AssumptionGC(api, assume_ttl_s=60.0, clock=lambda: clock[0],
                      metrics=metrics)
    clock[0] += 120.0  # assumption expires, never confirmed
    released = gc.sweep()
    assert released == ["default/stale"]
    assert metrics.counters["gc_sweeps"] == 1
    assert metrics.counters["gc_assumptions_released"] == 1
    assert len(metrics.latencies_ms["gc"]) == 1
    # Second sweep releases nothing but is still counted.
    gc.sweep()
    assert metrics.counters["gc_sweeps"] == 2
    assert metrics.counters["gc_assumptions_released"] == 1


def test_decisions_retention_is_configurable():
    api, _ = build_cluster()
    sched = ExtenderScheduler(api, ExtenderConfig(decisions_retention=2))
    for i in range(4):
        api.create("pods", make_pod(f"p{i}", chips=1))
        sched.bind(f"p{i}", "default", f"node-{i % 4}")
    assert len(sched.decisions) == 2
    assert sched.decisions[-1]["pod"] == "default/p3"


# ---- sim: deterministic phases / explains / first divergence ---------------

SMALL = dict(nodes=8, spec="v5p:2x2x4", arrivals=40)


def _run(flight_trace=True, seed=0, policies=("ici", "naive")):
    from tputopo.sim.engine import run_trace
    from tputopo.sim.trace import TraceConfig

    return run_trace(TraceConfig(seed=seed, **SMALL), list(policies),
                     flight_trace=flight_trace, return_states=True)


def test_sim_explains_and_phases_are_byte_deterministic():
    """Fixed seed => explain records, decision logs, and the phases count
    block are byte-identical across runs (wall-ms lives only in
    phase_wall/throughput, which this comparison never touches)."""
    ra, sa = _run()
    rb, sb = _run()
    for x, y in zip(sa, sb):
        assert json.dumps(x.decision_log, sort_keys=True) == \
            json.dumps(y.decision_log, sort_keys=True)
        assert x.phases == y.phases
    assert sa[0].phases  # the traced ici run actually recorded phases
    assert ra["policies"]["ici"]["phases"] == rb["policies"]["ici"]["phases"]
    body, other = dict(ra), dict(rb)
    for r in (body, other):
        r.pop("throughput"), r.pop("phase_wall")
    assert json.dumps(body, sort_keys=True) == \
        json.dumps(other, sort_keys=True)
    # Explain records never carry wall-clock fields.
    flat = json.dumps(sa[0].decision_log)
    assert "wall_ms" not in flat and "wall_s" not in flat


def test_sim_first_divergence_names_decision_with_both_explains():
    report, _ = _run()
    fd = report["ab"]["first_divergence"]["ici-vs-naive"]
    assert fd is not None  # these policies demonstrably diverge
    assert isinstance(fd["index"], int)
    ici, naive = fd["ici"], fd["naive"]
    assert ici["explain"]["policy"] == "ici"
    assert {"sort", "bind"} <= set(ici["explain"])
    assert naive["explain"]["policy"] == "naive"
    assert naive["explain"]["first_fit_walk"]
    # The divergent decision is concretely named on both sides.
    assert ici["job"] and naive["job"]


def test_sim_divergence_against_itself_is_none():
    from tputopo.sim.engine import first_divergence

    _, states = _run(policies=("ici",))
    assert first_divergence(states[0], states[0]) is None


def test_sim_untraced_still_names_divergence_without_explains():
    report, states = _run(flight_trace=False)
    assert report["policies"]["ici"]["phases"] == {}
    fd = report["ab"]["first_divergence"]["ici-vs-naive"]
    assert fd is not None and "explain" not in fd["ici"]
    assert states[0].phase_wall_ms == {}


def test_sim_phases_cover_the_verb_pipeline():
    report, _ = _run()
    phases = report["policies"]["ici"]["phases"]
    for key in ("sort", "sort/state", "sort/score", "bind",
                "bind/plan", "bind/cas_patch", "bind/publish"):
        assert key in phases, key
        assert phases[key]["count"] > 0
    # Deterministic span counters rode along (nodes scored per sort).
    assert phases["sort/score"]["counters"]["nodes"] > 0
    # Baselines don't run the extender pipeline: no phases recorded.
    assert report["policies"]["naive"]["phases"] == {}


@pytest.mark.slow
def test_disabled_tracer_throughput_within_noise_of_baseline():
    """Perf smoke (slow tier): with the flight recorder DISABLED the
    replay must sustain the PR-3-era throughput — the NullTracer path is
    branch-cheap by contract, so an instrumentation-induced slowdown
    (e.g. explain assembly leaking onto the untraced path) shows up here.
    The floor is the PR-3 figure for this config (~390-500 events/s
    depending on host) with ~2x headroom for host noise, same posture as
    test_sim_throughput_floor."""
    from tputopo.sim.engine import run_trace
    from tputopo.sim.trace import TraceConfig

    cfg = TraceConfig(seed=0, nodes=16, spec="v5p:2x2x4", arrivals=120)
    tp = run_trace(cfg, ["ici"], flight_trace=False)["throughput"]
    assert tp["events"] > 300
    assert tp["events_per_s"] > 150.0, tp
