# lint-corpus-relpath: tputopo/corpus/effects_ok.py
"""Clean twin of effects_bad: copy on EVERY path, or stay read-only."""


def thin(pods):
    pods = [dict(p) for p in pods]  # copy on the one path there is
    pods.sort(key=len)
    return pods


def census(pods):
    return sum(1 for p in pods if p.get("seen"))  # read-only


def caller(api):
    thin(api.list_nocopy("pods"))
    census(api.list_nocopy("pods"))
