"""LoRA adapters: parameter-efficient finetuning of the flagship LM.

A placement framework's workload layer schedules big pretrained models
onto slices; finetuning all of their weights per task wastes both HBM
(full AdamW moments) and checkpoint traffic.  LoRA trains a low-rank
delta ``x @ a @ b * (alpha/rank)`` next to each frozen projection:

- **Leaf wrapper, not a model fork**: a targeted projection becomes
  ``{"lora_base": w, "lora_a": [L, d, r], "lora_b": [L, r, out],
  "lora_scale": [L]}`` and :func:`tputopo.workloads.quant.qdot` — the
  single matmul site every projection already goes through — adds the
  low-rank term.  The stacked leading layer axis means the decode /
  prefill / pipeline ``lax.scan`` machinery is untouched.
- **Composes with quantization** (the QLoRA serving shape): the frozen
  base may be an int8 or grouped-int4 leaf — the adapter rides on top of
  the quantized stream, so a finetuned variant costs ``2 L d r`` extra
  floats instead of a second full model copy.
- **Training state is the adapter only**: the optimizer sees just the
  a/b tensors (AdamW moments shrink by the same factor), the base tree
  is a frozen argument.  ``b`` initializes to zero, so step 0's forward
  equals the base model exactly.

Sharding: ``a`` is replicated (tiny — d x r); ``b``'s output axis
follows the base's column-parallel ``tp`` sharding so the delta lands
already-sharded where the base dot's output lives.  Default targets are
the attention q/v projections (the standard LoRA recipe); any
column-parallel projection name works.  Row-parallel targets (wo,
w_down) are rejected: their inputs arrive tp-sharded, and the low-rank
contraction would need its own psum — a cost the adapter should not
silently add.

The reference schedules training containers and has no finetuning story
(SURVEY §0); this is workload-layer capability (SURVEY §1 L5).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tputopo.workloads import sharding as shardlib
from tputopo.workloads.model import ModelConfig

#: Column-parallel projections LoRA may target ([.., d_in, d_out] with the
#: output axis tp-sharded).  Row-parallel ones (wo, w_down) would need a
#: psum for the adapter contraction — rejected, see module docstring.
_COL_PARALLEL = ("wq", "wk", "wv", "w_gate", "w_up")
DEFAULT_TARGETS = ("wq", "wv")


def _target_dims(c: ModelConfig, name: str) -> tuple[int, int]:
    return {
        "wq": (c.d_model, c.n_heads * c.head_dim),
        "wk": (c.d_model, c.n_kv_heads * c.head_dim),
        "wv": (c.d_model, c.n_kv_heads * c.head_dim),
        "w_gate": (c.d_model, c.d_ff),
        "w_up": (c.d_model, c.d_ff),
    }[name]


def init_lora(config: ModelConfig, key: jax.Array, *, rank: int = 8,
              alpha: float = 16.0,
              targets: tuple[str, ...] = DEFAULT_TARGETS) -> dict:
    """Adapter pytree: ``{"layers": {name: {"a", "b", "scale"}}}``.

    ``a`` ~ N(0, 1/d) (the base init's scaling), ``b`` = 0 — the delta
    starts exactly zero.  ``scale`` carries alpha/rank per layer so scan
    slices stay self-contained.
    """
    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")
    for name in targets:
        if name not in _COL_PARALLEL:
            raise ValueError(
                f"LoRA target {name!r} is not column-parallel; supported: "
                f"{_COL_PARALLEL} (row-parallel targets would need their "
                "own psum)")
        if config.moe is not None and name in ("w_gate", "w_up"):
            raise ValueError(
                f"target {name!r} is an MoE expert table under this "
                "config; adapter routing over experts is not supported")
    L = config.n_layers
    out = {}
    for i, name in enumerate(targets):
        din, dout = _target_dims(config, name)
        k = jax.random.fold_in(key, i)
        out[name] = {
            "a": jax.random.normal(k, (L, din, rank), jnp.float32)
            / jnp.sqrt(jnp.float32(din)),
            "b": jnp.zeros((L, rank, dout), jnp.float32),
            "scale": jnp.full((L,), alpha / rank, jnp.float32),
        }
    return {"layers": out}


def lora_view(base_params: dict, lora: dict) -> dict:
    """The parameter tree the forward pass consumes: targeted leaves
    wrapped as lora dicts (qdot applies the delta), everything else the
    frozen base.  Pure tree surgery — no copies of the base weights."""
    layers = dict(base_params["layers"])
    for name, ad in lora["layers"].items():
        if name not in layers:
            raise ValueError(f"lora target {name!r} not in base layers")
        layers[name] = {"lora_base": layers[name], "lora_a": ad["a"],
                        "lora_b": ad["b"], "lora_scale": ad["scale"]}
    out = dict(base_params)
    out["layers"] = layers
    return out


def merge_lora(base_params: dict, lora: dict) -> dict:
    """Fold the adapter into raw float base weights (deployment without
    the extra dot).  Quantized bases cannot merge losslessly — serve them
    through the lora_view path instead (that IS the QLoRA shape)."""
    from tputopo.workloads.quant import is_quantized

    layers = dict(base_params["layers"])
    for name, ad in lora["layers"].items():
        w = layers[name]
        if is_quantized(w):
            raise ValueError(
                f"cannot merge into quantized base leaf {name!r}; serve "
                "via lora_view instead")
        delta = jnp.einsum("ldr,lro->ldo", ad["a"], ad["b"])
        layers[name] = w + delta * ad["scale"][:, None, None]
    out = dict(base_params)
    out["layers"] = layers
    return out


def lora_shardings(plan: shardlib.MeshPlan, lora: dict):
    """NamedShardings for the adapter tree: ``a`` replicated (d x r is
    tiny), ``b`` output-axis over ``tp`` (matching the base's
    column-parallel layout), stacked layer axis over ``pp`` when active."""
    pp = "pp" if plan.axes.get("pp", 1) > 1 else None

    def leaf(name: str):
        if name == "b":
            return plan.sharding(pp, None, "tp")
        if name == "a":
            return plan.sharding(pp, None, None)
        return plan.sharding(pp)  # scale [L]

    return {"layers": {t: {k: leaf(k) for k in ad}
                       for t, ad in lora["layers"].items()}}


def make_sharded_lora_train_step(plan: shardlib.MeshPlan,
                                 config: ModelConfig, lora: dict,
                                 lr: float = 3e-4,
                                 n_micro: int | None = None,
                                 accum_steps: int = 1):
    """Compile one LoRA optimizer step over ``plan``.

    ``(lora_state, base_params, tokens) -> (lora_state, loss)`` — grads
    flow only to the adapter (the base is a frozen argument; its
    stop-gradient is implicit in differentiating w.r.t. the lora arg),
    AdamW moments exist only for a/b, and only the adapter state is
    donated.  The base may be raw f32/bf16 or a quantized serving tree
    (the QLoRA shape).  Composes exactly like the full train step: the
    forward runs the GPipe pipeline when the plan has pp > 1, and
    ``accum_steps`` layers gradient accumulation on top.
    """
    import optax

    from tputopo.workloads.model import forward_with_aux
    from tputopo.workloads.train import (TrainState, loss_fn,
                                         make_optimizer, opt_shardings)

    ad_shard = lora_shardings(plan, lora)
    state_shard = TrainState(
        params=ad_shard,
        opt_state=opt_shardings(make_optimizer(lr), lora, ad_shard, plan),
        step=plan.replicated())
    if plan.axes.get("pp", 1) > 1:
        from functools import partial

        from tputopo.workloads.pipeline import pipelined_forward_with_aux

        fwd = partial(pipelined_forward_with_aux, plan=plan, n_micro=n_micro)
    else:
        fwd = forward_with_aux

    def step_fn(state: TrainState, base_params, tokens):
        with shardlib.activate(plan):
            def lora_loss(adapter, mb):
                return loss_fn(lora_view(base_params, adapter), mb,
                               config, fwd)

            if accum_steps <= 1:
                loss, grads = jax.value_and_grad(lora_loss)(state.params,
                                                            tokens)
            else:
                B = tokens.shape[0]
                if B % accum_steps:
                    raise ValueError(f"batch {B} not divisible by "
                                     f"accum_steps {accum_steps}")
                micro = tokens.reshape(accum_steps, B // accum_steps,
                                       tokens.shape[1])
                micro = shardlib.constrain(micro, None, "dp", "sp")

                def acc(carry, mb):
                    loss_sum, grad_sum = carry
                    l, g = jax.value_and_grad(lora_loss)(state.params, mb)
                    return (loss_sum + l,
                            jax.tree.map(jnp.add, grad_sum, g)), None

                zeros = jax.tree.map(jnp.zeros_like, state.params)
                (loss_sum, grad_sum), _ = jax.lax.scan(
                    acc, (jnp.zeros((), jnp.float32), zeros), micro)
                loss = loss_sum / accum_steps
                grads = jax.tree.map(lambda g: g / accum_steps, grad_sum)
            opt = make_optimizer(lr)
            updates, opt_state = opt.update(grads, state.opt_state,
                                            state.params)
            params = optax.apply_updates(state.params, updates)
            return TrainState(params=params, opt_state=opt_state,
                              step=state.step + 1), loss

    return jax.jit(step_fn, donate_argnums=(0,),
                   out_shardings=(state_shard, plan.replicated()))


def make_sharded_lora_state(plan: shardlib.MeshPlan, config: ModelConfig,
                            key: jax.Array, *, rank: int = 8,
                            alpha: float = 16.0,
                            targets: tuple[str, ...] = DEFAULT_TARGETS,
                            lr: float = 3e-4):
    """Adapter TrainState initialized directly into its sharded layout."""
    from functools import partial

    from tputopo.workloads.train import (TrainState, make_optimizer,
                                         opt_shardings)

    template = jax.eval_shape(partial(init_lora, config, rank=rank,
                                      alpha=alpha, targets=targets), key)
    ad_shard = lora_shardings(plan, template)
    shardings = TrainState(
        params=ad_shard,
        opt_state=opt_shardings(make_optimizer(lr), template, ad_shard,
                                plan),
        step=plan.replicated())

    @partial(jax.jit, out_shardings=shardings)
    def init():
        lora = init_lora(config, key, rank=rank, alpha=alpha,
                         targets=targets)
        return TrainState(params=lora,
                          opt_state=make_optimizer(lr).init(lora),
                          step=jnp.zeros((), jnp.int32))

    with plan.mesh:
        return init()
