"""Baseline (topology-blind) allocation policies, for A/B comparison.

The reference proves its value by A/B against the stock kube-scheduler
(Gaia PDF §IV Exp.5/6: the default scheduler picks by count only, landing
jobs on scattered devices; Fig. 11 contrasts a scattered vs link-local
placement).  ``naive_pick`` reproduces that behavior for a TPU node: take
the k lowest-indexed free chips, ignoring geometry — exactly what a
count-only extended-resource scheduler plus the kubelet's arbitrary
device pick does.  Used by tests and bench to quantify the bandwidth and
fragmentation delta of topology awareness.
"""

from __future__ import annotations

from tputopo.topology.model import ChipTopology, Coord


def naive_pick(topo: ChipTopology, free: frozenset[Coord], k: int) -> tuple[Coord, ...] | None:
    """First-fit: the k lowest row-major-indexed free chips (count-only)."""
    if len(free) < k:
        return None
    ordered = sorted(free, key=topo.index)
    return tuple(ordered[:k])


class NaiveAllocator:
    """Count-only bookkeeping twin of :class:`tputopo.topology.slices.Allocator`."""

    def __init__(self, topo: ChipTopology):
        self.topo = topo
        self._used: set[Coord] = set()

    @property
    def free(self) -> frozenset[Coord]:
        return frozenset(c for c in self.topo.chips if c not in self._used)

    def allocate(self, k: int) -> tuple[Coord, ...] | None:
        picked = naive_pick(self.topo, self.free, k)
        if picked is not None:
            self._used.update(picked)
        return picked

    def release(self, chips) -> None:
        for c in chips:
            self._used.discard(tuple(c))
