"""Pipeline parallelism: the SPMD GPipe schedule must be pure layout —
bit-compatible (up to f32 tolerance) with the plain layer scan — and
trainable end to end, including composed with MoE expert parallelism
(pp x ep x tp on the 8-device CPU mesh: all five logical axes exist, three
active here, dp/sp covered by test_workloads/test_ring)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax_features import requires_shard_map
from tputopo.workloads.model import ModelConfig, forward_with_aux, init_params
from tputopo.workloads.moe import MoEConfig
from tputopo.workloads.pipeline import pipelined_forward_with_aux
from tputopo.workloads.sharding import build_mesh
from tputopo.workloads.train import (
    loss_fn, make_sharded_state, make_sharded_train_step, make_train_state,
    train_step,
)

TINY = ModelConfig(vocab_size=128, d_model=32, n_layers=4, n_heads=4,
                   n_kv_heads=2, d_ff=64, max_seq=64,
                   compute_dtype=jnp.float32)


def _toks(batch=4, seq=32, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).integers(0, 128, (batch, seq)))


@requires_shard_map
def test_pipelined_forward_matches_plain_forward():
    plan = build_mesh({"pp": 2, "dp": 2, "tp": 2})
    params = init_params(TINY, jax.random.key(0))
    toks = _toks()
    ref_logits, ref_aux = forward_with_aux(params, toks, TINY)
    with plan.mesh:
        logits, aux = jax.jit(
            lambda p, t: pipelined_forward_with_aux(p, t, TINY, plan))(
                params, toks)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               rtol=2e-4, atol=2e-4)
    assert float(aux) == pytest.approx(float(ref_aux), abs=1e-6)


@requires_shard_map
def test_pipelined_forward_more_microbatches():
    """M > pp shrinks the bubble; the math must not notice."""
    plan = build_mesh({"pp": 4, "dp": 1, "tp": 2})
    params = init_params(TINY, jax.random.key(0))
    toks = _toks(batch=8)
    ref_logits, _ = forward_with_aux(params, toks, TINY)
    with plan.mesh:
        logits, _ = jax.jit(
            lambda p, t: pipelined_forward_with_aux(p, t, TINY, plan,
                                                    n_micro=8))(params, toks)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_shape_validation():
    plan = build_mesh({"pp": 2, "dp": 2, "tp": 2})
    params = init_params(TINY, jax.random.key(0))
    with pytest.raises(ValueError, match="microbatch"):
        pipelined_forward_with_aux(params, _toks(batch=3), TINY, plan)
    odd = ModelConfig(vocab_size=128, d_model=32, n_layers=3, n_heads=4,
                      n_kv_heads=2, d_ff=64, max_seq=64,
                      compute_dtype=jnp.float32)
    with pytest.raises(ValueError, match="stages"):
        pipelined_forward_with_aux(init_params(odd, jax.random.key(0)),
                                   _toks(), odd, plan)


@requires_shard_map
def test_pipelined_train_step_matches_unsharded():
    """Full train step through the pipeline (grads flow through ppermute,
    the banked output buffer, and the masked psum) == plain step."""
    plan = build_mesh({"pp": 2, "dp": 2, "tp": 2})
    toks = _toks(seed=1)

    ref_state = make_train_state(TINY, jax.random.key(2), lr=1e-2)
    ref_loss = float(loss_fn(ref_state.params, toks, TINY))

    sh_state = make_sharded_state(plan, TINY, jax.random.key(2), lr=1e-2)
    step = make_sharded_train_step(plan, TINY, lr=1e-2)
    sh_state, sh_loss = step(sh_state, toks)
    assert float(sh_loss) == pytest.approx(ref_loss, rel=1e-4)

    ref_state, _ = jax.jit(
        lambda s, t: train_step(s, t, TINY, lr=1e-2))(ref_state, toks)
    for a, b in zip(jax.tree.leaves(ref_state.params),
                    jax.tree.leaves(sh_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_pipeline_layer_params_sharded_over_pp():
    plan = build_mesh({"pp": 2, "dp": 2, "tp": 2})
    state = make_sharded_state(plan, TINY, jax.random.key(0))
    wq = state.params["layers"]["wq"]  # [L, D, N*Hd]
    assert wq.sharding.shard_shape(wq.shape)[0] == TINY.n_layers // 2, \
        "each pipeline stage must hold only its own layers"


@pytest.mark.slow
def test_pipeline_composed_with_moe_ep():
    """pp=2 x ep=2 x tp=2: pipelined MoE training step runs and learns."""
    cfg = ModelConfig(vocab_size=128, d_model=32, n_layers=2, n_heads=4,
                      n_kv_heads=2, d_ff=64, max_seq=64,
                      compute_dtype=jnp.float32,
                      moe=MoEConfig(n_experts=4, top_k=2,
                                    capacity_factor=2.0))
    plan = build_mesh({"pp": 2, "ep": 2, "tp": 2})
    toks = _toks(seed=3)

    ref_state = make_train_state(cfg, jax.random.key(2), lr=5e-3)
    ref_loss = float(loss_fn(ref_state.params, toks, cfg))

    state = make_sharded_state(plan, cfg, jax.random.key(2), lr=5e-3)
    step = make_sharded_train_step(plan, cfg, lr=5e-3)
    state, first = step(state, toks)
    # Cross-entropy is exact; the aux term's balance statistics are
    # per-routing-group, and under pipelining the group is the microbatch —
    # a real (documented) semantic difference, so only near-parity holds.
    assert float(first) == pytest.approx(ref_loss, rel=2e-2)
    for _ in range(6):
        state, loss = step(state, toks)
    assert float(loss) < float(first)


@requires_shard_map
def test_flash_attention_composes_with_pipeline():
    """The Pallas dispatch's inner shard_map must nest inside the
    pipeline's manual-pp region (it targets the context abstract mesh and
    maps only the non-manual axes)."""
    import dataclasses

    cfg = dataclasses.replace(TINY, attn_impl="flash")
    plan = build_mesh({"pp": 2, "dp": 2, "tp": 2})
    params = init_params(cfg, jax.random.key(0))
    toks = _toks()
    ref, _ = forward_with_aux(params, toks, cfg)
    from tputopo.workloads.sharding import activate

    with activate(plan):
        logits, _ = jax.jit(
            lambda p, t: pipelined_forward_with_aux(p, t, cfg, plan))(
                params, toks)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@requires_shard_map
def test_ring_attention_composes_with_pipeline():
    """Context parallelism inside pipeline stages: pp x sp x tp."""
    plan = build_mesh({"pp": 2, "sp": 2, "tp": 2})
    params = init_params(TINY, jax.random.key(1))
    toks = _toks(seed=4)
    ref, _ = forward_with_aux(params, toks, TINY)
    from tputopo.workloads.sharding import activate

    with activate(plan):
        logits, _ = jax.jit(
            lambda p, t: pipelined_forward_with_aux(p, t, TINY, plan))(
                params, toks)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
