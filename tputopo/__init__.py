"""tputopo — TPU-native topology-aware Kubernetes scheduling framework.

A ground-up rebuild of the capability set specified by the reference design
``hellolijj/gpu-topology-on-k8s`` (a Gaia-style GPU-topology scheduler,
``/root/reference/design.md``), reformulated natively for TPUs:

- The NVML pairwise P2P link matrix (design.md:25-74) becomes a regular
  ICI torus model with known chip coordinates (:mod:`tputopo.topology`).
- The greedy k-subset selector (design.md:131-190) becomes contiguous
  slice-shape enumeration with an anti-fragmentation packing policy.
- The affinity-mark scorer (design.md:192-217) becomes an analytic
  all-reduce bandwidth model over ICI/DCN links.
- The device plugin / scheduler-extender / annotation-handshake shapes
  (design.md:57-121, 223-246) are preserved — they are accelerator-agnostic.
"""

__version__ = "0.1.0"
