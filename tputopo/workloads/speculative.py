"""Speculative decoding: draft cheap, verify exact, accept in bulk.

The serving engine's decode step is HBM-bound — every new token pays one
full weight stream.  Speculative decoding amortizes that stream: a cheap
DRAFT model proposes ``gamma`` tokens autoregressively, then the target
model scores all of them in ONE batched forward (the same weight stream
that one ordinary decode step pays), and the longest prefix whose greedy
argmax agrees is committed along with the target's own next token.  Per
target stream, 1..gamma+1 tokens commit instead of exactly 1.

Lossless by construction: with greedy selection, the committed sequence
is EXACTLY the target model's greedy decode — the draft only decides how
many target steps are skipped, never what is emitted.  The parity test
pins this for arbitrary (even random, worst-case) drafts.  One numerics
caveat: the verify forward is width gamma+1 while plain decode is width
1, and XLA does not promise bitwise-equal reductions across block
shapes — at bf16, two logits within an ulp of each other can argmax
differently between the two widths.  Parity is exact at f32 (pinned by
tests).  Observed on v5e: raw bf16 weights held exact parity across 48
tokens; int8 weights flipped ONE near-tie (top-2 logit gap 0.003 on
|logits| ~3.5 — 0.1% relative), and the f32 recomputation sided with
the WIDER verify block, i.e. the speculative path was the more accurate
of the two.  A flip emits a coherent greedy-of-the-verify-block
sequence, never garbage.

TPU-first formulation:
- the draft is a leading-layer slice of the target's own stacked
  parameters (``jax.tree.map(lambda a: a[:k], params["layers"])`` — one
  model, no second checkpoint; embed/final-norm/head shared), so the
  layer scan machinery is reused verbatim at a different depth;
- the whole generate loop is ONE ``lax.while_loop`` with static shapes:
  preallocated token buffer and caches, fixed-width (gamma+1) draft
  catch-up and verify blocks, acceptance handled by masked commits.
  Junk K/V written past the committed length is overwritten before any
  query can attend it — the same invariant the serving engine's
  redirect relies on (serving.py);
- rejected-draft cache rows need no rollback: positions past the
  committed length are junk by definition and the next verify block
  rewrites them.

Two forms: :func:`spec_generate` (single sequence, one while_loop) and
:class:`SpecServingEngine` — speculative CONTINUOUS BATCHING over the
serving engine's slots, where every slot drafts and accepts
independently at its own position through one ragged verify forward
(serving.ragged_block, the T-wide primitive), with per-slot EOS and
budget caps.

The reference has no serving leg at all (SURVEY §0); this module extends
the workload layer (L5) the placement serves.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from tputopo.workloads.decode import KVCache, _block_step, _constrain_cache
from tputopo.workloads.model import ModelConfig, _rope_tables
from tputopo.workloads.serving import (DecodeState, ServingEngine,
                                       _merge_slot_cache, _slot_cache,
                                       ragged_block)


def _acceptance_row(drafts: jax.Array, targets: jax.Array
                    ) -> tuple[jax.Array, jax.Array]:
    """The speculative acceptance rule, shared by both paths: drafts
    [B, gamma] vs targets [B, gamma+1] (the target's argmax AFTER each
    verify position) -> (row [B, gamma+1], n_accept [B]).  ``row`` is
    the commit candidate — the accepted draft prefix, then the target's
    own correction token at index n_accept."""
    B, gamma = drafts.shape
    agree = targets[:, :gamma] == drafts
    n_accept = jnp.argmin(
        jnp.concatenate([agree, jnp.zeros((B, 1), bool)], axis=1), axis=1)
    row = jnp.where(jnp.arange(gamma + 1)[None, :] < n_accept[:, None],
                    jnp.concatenate([drafts, targets[:, gamma:]], axis=1),
                    targets)
    return row, n_accept


def draft_slice(params: dict, config: ModelConfig,
                draft_layers: int) -> tuple[dict, ModelConfig]:
    """The draft model: the target's first ``draft_layers`` layers with
    the embed/final-norm/head shared — a depth slice of the SAME stacked
    parameter tree (works for raw, int8-quantized, and MoE leaves, whose
    scales/tables all carry the leading layer axis)."""
    if not 0 < draft_layers < config.n_layers:
        raise ValueError(
            f"draft_layers must be in (0, {config.n_layers}), "
            f"got {draft_layers}")
    draft_params = dict(params)
    draft_params["layers"] = jax.tree.map(
        lambda a: a[:draft_layers], params["layers"])
    return draft_params, dataclasses.replace(config, n_layers=draft_layers)


@partial(jax.jit, static_argnames=("config", "draft_layers", "gamma",
                                   "max_new", "max_len"))
def spec_generate(params: dict, prompt: jax.Array, config: ModelConfig, *,
                  max_new: int, draft_layers: int, gamma: int = 4,
                  max_len: int | None = None
                  ) -> tuple[jax.Array, dict]:
    """Greedy speculative decode: prompt [1, P] -> ([1, P + max_new]
    tokens, stats).  Token-for-token identical to ``generate``'s greedy
    output; ``stats`` reports ``target_steps`` (verify forwards paid) and
    ``drafted_accepted`` (tokens committed straight from the draft) —
    tokens_per_target_stream = (max_new) / target_steps.
    """
    c = config
    B, P = prompt.shape
    if B != 1:
        raise ValueError("spec_generate is single-sequence (B=1); the "
                         "batched analog is the serving engine's slots")
    if max_new < 1:
        raise ValueError(f"max_new must be >= 1, got {max_new}")
    total = P + max_new
    # Fixed-width blocks write up to gamma tokens past the committed
    # length; give the buffers that margin.
    need = total + gamma + 1
    max_len = max(max_len or 0, need)
    draft_params, draft_cfg = draft_slice(params, c, draft_layers)
    cos, sin = _rope_tables(c, max_len)

    tokens = jnp.zeros((1, max_len), jnp.int32)
    tokens = jax.lax.dynamic_update_slice(tokens, prompt.astype(jnp.int32),
                                          (0, 0))

    # Prefill both caches on the prompt; the target's last-position logits
    # give the first committed token.
    # Same serving-mesh layout as generate/serving: KV heads over tp
    # (batch is 1 here; dp resolves to a no-op).
    tcache = _constrain_cache(KVCache.create(c, 1, max_len))
    dcache = _constrain_cache(KVCache.create(draft_cfg, 1, max_len))
    tlogits, tcache = _block_step(params, c, prompt, 0, tcache, cos, sin)
    _, dcache = _block_step(draft_params, draft_cfg, prompt, 0, dcache,
                            cos, sin)
    first = jnp.argmax(tlogits[0, -1]).astype(jnp.int32)
    tokens = tokens.at[0, P].set(first)

    def draft_one(carry, _):
        tok, cache, pos = carry
        lg, cache = _block_step(draft_params, draft_cfg, tok[None, None],
                                pos, cache, cos, sin)
        nxt = jnp.argmax(lg[0, -1]).astype(jnp.int32)
        return (nxt, cache, pos + 1), nxt

    def body(state):
        tokens, length, tcache, dcache, dlen, tsteps, accepted = state
        # 1. Draft catch-up: feed the draft every committed token it has
        # not seen, as one fixed-width block.  Entries past the real gap
        # are junk whose K/V rows are overwritten before any query can
        # attend them (they sit past the drafting frontier).
        gap_block = jax.lax.dynamic_slice(
            tokens, (0, dlen), (1, gamma + 1))
        cu_logits, dcache = _block_step(draft_params, draft_cfg, gap_block,
                                        dlen, dcache, cos, sin)
        # The first draft token is free — the catch-up block contains the
        # last committed token's position, so its logits are already here.
        d1 = jnp.argmax(cu_logits[0, length - 1 - dlen]).astype(jnp.int32)
        dlen = length  # the draft has now seen tokens[0:length]

        # 2. Draft the remaining gamma-1 tokens autoregressively.
        last = tokens[0, length - 1]
        (_, dcache, _), rest = jax.lax.scan(
            draft_one, (d1, dcache, length), None, length=gamma - 1)
        drafts = jnp.concatenate([d1[None], rest])

        # 3. Verify: ONE target forward over [last, draft_1..draft_gamma]
        # at positions length-1.. — the amortized weight stream.
        block = jnp.concatenate([last[None], drafts])[None, :]
        vlogits, tcache = _block_step(params, c, block, length - 1,
                                      tcache, cos, sin)
        targets = jnp.argmax(vlogits[0], axis=-1).astype(jnp.int32)
        # targets[i] = target's token AFTER position length-1+i; the
        # shared acceptance rule yields the commit row (accepted draft
        # prefix + the target's correction at index n_accept).
        row, n_accept = _acceptance_row(drafts[None, :], targets[None, :])
        row, n_accept = row[0], n_accept[0]

        # 4. Commit accepted drafts + the target's own next token, capped
        # by the remaining budget (never emit past total).
        commit = jnp.minimum(n_accept + 1, total - length)
        cur = jax.lax.dynamic_slice(tokens, (0, length), (1, gamma + 1))[0]
        sel = jnp.where(jnp.arange(gamma + 1) < commit, row, cur)
        tokens = jax.lax.dynamic_update_slice(tokens, sel[None, :],
                                              (0, length))
        return (tokens, length + commit, tcache, dcache, dlen,
                tsteps + 1, accepted + jnp.minimum(n_accept, commit))

    def cond(state):
        return state[1] < total

    state = (tokens, jnp.int32(P + 1), tcache, dcache, jnp.int32(P),
             jnp.int32(1), jnp.int32(0))
    tokens, length, _, _, _, tsteps, accepted = jax.lax.while_loop(
        cond, body, state)
    stats = {"target_steps": tsteps, "drafted_accepted": accepted,
             "max_new": jnp.int32(max_new)}
    return tokens[:, :total], stats


# ---- speculative continuous batching ----------------------------------------

@partial(jax.jit, static_argnames=("config", "draft_config", "gamma"))
def spec_tick(params: dict, draft_params: dict, state, dcache: KVCache,
              dlen: jax.Array, config: ModelConfig,
              draft_config: ModelConfig, eos_id: jax.Array, gamma: int):
    """One speculative tick for every active slot: draft catch-up ->
    gamma per-slot draft tokens -> ONE ragged target verify block ->
    per-slot acceptance, EOS/budget-capped commits.  Each slot commits
    1..gamma+1 tokens per target weight stream; slots accept
    independently (the whole point of doing this over the slotted
    state — a lockstep batch would advance at the worst slot's rate).

    Junk-window discipline (same invariant as decode_step): inactive
    slots' windows are redirected to the buffer tail, and every junk
    K/V row is either masked (k_pos <= q_pos) or overwritten before a
    query can attend it.  The ServingEngine buffer carries a gamma+1
    margin past the logical max_len so ACTIVE slots' verify windows
    never clamp.

    Returns (new state, new draft cache, new dlen, accepted_this_tick).
    """
    c = config
    B, buf_len = state.tokens.shape
    G1 = gamma + 1
    active = state.active
    safe = buf_len - G1  # junk-window base for inactive slots

    # 1. Draft catch-up: feed the draft every committed token it has not
    # seen (gap = length - dlen <= gamma+1 between ticks; admissions
    # reset dlen via the draft prefill).  Junk entries past the real gap
    # are overwritten by the draft steps below before any query attends
    # them.
    cu_start = jnp.where(active, jnp.minimum(dlen, safe), safe)
    gap = jax.vmap(lambda row, s: jax.lax.dynamic_slice(row, (s,), (G1,)))(
        state.tokens, cu_start)
    cu_logits, dcache = ragged_block(draft_params, draft_config, gap,
                                     cu_start, dcache)
    dlen = jnp.where(active, state.length, dlen)

    # 2. Draft gamma tokens autoregressively.  The FIRST draft token is
    # free: the catch-up block always contains the last committed token's
    # position (dlen <= length-1 <= dlen+gamma), so its logits are
    # already in cu_logits — one draft forward saved per tick.
    pos0 = jnp.where(active, jnp.maximum(state.length - 1, 0), safe)
    last = jnp.take_along_axis(state.tokens, pos0[:, None], axis=1)[:, 0]
    first_idx = jnp.clip(pos0 - cu_start, 0, gamma)
    d1 = jnp.take_along_axis(
        jnp.argmax(cu_logits, axis=-1).astype(jnp.int32),
        first_idx[:, None], axis=1)[:, 0]

    def draft_one(carry, i):
        tok, dc = carry
        lg, dc = ragged_block(draft_params, draft_config, tok[:, None],
                              pos0 + 1 + i, dc)
        nxt = jnp.argmax(lg[:, 0], axis=-1).astype(jnp.int32)
        return (nxt, dc), nxt

    (_, dcache), rest = jax.lax.scan(draft_one, (d1, dcache),
                                     jnp.arange(gamma - 1))
    drafts = jnp.concatenate([d1[:, None], rest.T], axis=1)  # [B, gamma]

    # 3. Verify: ONE target forward per slot over [last, d_1..d_gamma]
    # at positions length-1.. — the amortized weight stream.
    vblock = jnp.concatenate([last[:, None], drafts], axis=1)
    vlogits, tcache = ragged_block(params, c, vblock, pos0, state.cache)
    targets = jnp.argmax(vlogits, axis=-1).astype(jnp.int32)  # [B, G1]

    # 4. Acceptance and commit row per slot (shared rule): accepted
    # draft prefix, then the target's own correction token.
    row, n_accept = _acceptance_row(drafts, targets)
    generated = state.length - state.prompt_len
    commit = jnp.minimum(n_accept + 1, state.budget - generated)
    is_eos = row == eos_id
    eos_idx = jnp.argmax(is_eos, axis=1)
    has_eos = jnp.any(is_eos, axis=1)
    commit = jnp.where(has_eos, jnp.minimum(commit, eos_idx + 1), commit)
    commit = jnp.where(active, commit, 0)

    # 5. Masked full-row token write (no window clamping to reason about).
    idx = jnp.arange(buf_len)[None, :]
    off = idx - state.length[:, None]
    use = (off >= 0) & (off < commit[:, None]) & active[:, None]
    gathered = jnp.take_along_axis(
        row, jnp.clip(off, 0, gamma), axis=1)
    new_tokens = jnp.where(use, gathered, state.tokens)

    new_length = state.length + commit
    new_generated = new_length - state.prompt_len
    eos_committed = has_eos & (eos_idx + 1 <= commit)
    finished = active & (eos_committed | (new_generated >= state.budget)
                         | (new_length >= buf_len))
    new_state = DecodeState(
        cache=tcache,
        tokens=new_tokens,
        length=new_length,
        prompt_len=state.prompt_len,
        budget=state.budget,
        seq_id=state.seq_id,
        done=state.done | finished,
        step=state.step + 1,
    )
    accepted = jnp.sum(jnp.where(active, jnp.minimum(n_accept, commit), 0))
    return new_state, dcache, dlen, accepted


@partial(jax.jit, static_argnames=("config",))
def _draft_prefill(draft_params: dict, config: ModelConfig, dcache: KVCache,
                   slot: jax.Array, prompt: jax.Array) -> KVCache:
    """Prefill one slot of the draft cache on admission (the draft twin
    of ServingEngine's admit — cache only, no token bookkeeping)."""
    cos, sin = _rope_tables(config, dcache.k.shape[2])
    _, filled = _block_step(draft_params, config, prompt[None, :], 0,
                            _slot_cache(dcache, slot), cos, sin)
    return _merge_slot_cache(dcache, filled, slot)


class SpecServingEngine(ServingEngine):
    """Speculative continuous batching: the slotted ServingEngine with a
    draft model (a leading-layer slice of the same parameters) proposing
    gamma tokens per tick and one ragged verify forward committing
    1..gamma+1 tokens per slot per target stream.

    A subclass that replaces exactly two hooks: ``_post_admit`` (prefill
    the draft cache alongside every admission) and ``_decode_tick`` (the
    speculative tick instead of plain decode steps) — admission, harvest,
    queueing, and the run loop are the parent's.  Greedy-only (the
    lossless guarantee; sampled speculative decoding needs rejection
    sampling) and whole-bucket admission only (no chunked prefill; no
    prefix caching — its draft-cache mirroring is future work).
    """

    def __init__(self, params: dict, config: ModelConfig, *, slots: int,
                 max_len: int, prompt_pad, draft_layers: int,
                 gamma: int = 4, eos_id: int = -1, on_tokens=None) -> None:
        if gamma < 1:
            raise ValueError(f"gamma must be >= 1, got {gamma}")
        self.gamma = gamma
        self.draft_params, self.draft_cfg = draft_slice(params, config,
                                                        draft_layers)
        # buffer_margin: a slot at the logical max_len still needs a
        # non-clamping gamma+1 verify window (see _write_kv_at's
        # contract); submissions stay bounded by the logical max_len.
        super().__init__(params, config, slots=slots, max_len=max_len,
                         prompt_pad=prompt_pad, eos_id=eos_id,
                         buffer_margin=gamma + 1, on_tokens=on_tokens)
        self._dcache = _constrain_cache(
            KVCache.create(self.draft_cfg, slots, max_len + gamma + 1))
        self._dlen = jnp.zeros((slots,), jnp.int32)
        self.metrics["drafted_accepted"] = 0

    def submit(self, prompt, max_new: int, prefix: int | None = None) -> int:
        if prefix is not None:
            raise ValueError("prefix caching is not supported with "
                             "speculative serving (draft-cache mirroring "
                             "is future work)")
        return super().submit(prompt, max_new)

    def _post_admit(self, slot: int, padded, prompt_len: int) -> None:
        self._dcache = _draft_prefill(
            self.draft_params, self.draft_cfg, self._dcache,
            jnp.int32(slot), jnp.asarray(padded))
        self._dlen = self._dlen.at[slot].set(prompt_len)

    def _decode_tick(self) -> None:
        self.state, self._dcache, self._dlen, accepted = spec_tick(
            self.params, self.draft_params, self.state, self._dcache,
            self._dlen, self.config, self.draft_cfg,
            jnp.int32(self.eos_id), self.gamma)
        self.metrics["decode_steps"] += 1  # target streams paid
        self.metrics["drafted_accepted"] += int(accepted)
