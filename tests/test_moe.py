"""Expert parallelism: MoE routing math, ep-sharded execution, training.

Strategy mirrors tests/test_workloads.py: exact parity between the
capacity-dispatch fast path and a per-expert reference on shapes where no
token can be dropped, then distribution/sharding properties on the 8-device
CPU mesh (ep active), then a full MoE train-step smoke including the aux
load-balancing loss.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax_features import requires_shard_map
from tputopo.workloads.model import ModelConfig, forward_with_aux, init_params
from tputopo.workloads.moe import MoEConfig, moe_mlp, moe_mlp_reference
from tputopo.workloads.sharding import build_mesh
from tputopo.workloads.train import (
    loss_fn, make_sharded_state, make_sharded_train_step, make_train_state,
    train_step,
)

MOE_TINY = ModelConfig(
    vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=64, max_seq=64, compute_dtype=jnp.float32,
    moe=MoEConfig(n_experts=4, top_k=2, capacity_factor=2.0),
)


def _layer0(params):
    return jax.tree.map(lambda a: a[0], params["layers"]["moe"])


def test_moe_matches_reference_when_capacity_ample():
    """capacity_factor big enough that no token is dropped -> the dense
    dispatch must equal the per-expert loop exactly (same f32 math)."""
    cfg = MOE_TINY
    params = init_params(cfg, jax.random.key(0))
    p = _layer0(params)
    x = jax.random.normal(jax.random.key(1), (2, 16, cfg.d_model), jnp.float32)
    # T=16, k=2, E=4, cf=2.0 -> capacity 16 == T: nothing can overflow.
    out, aux = moe_mlp(x, p, cfg)
    ref = moe_mlp_reference(x, p, cfg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    assert float(aux) > 0


def test_moe_capacity_drops_are_bounded():
    """With a tight capacity the fast path may only differ from the
    no-drop reference on tokens it dropped — and each dropped (token, slot)
    zeroes that expert's contribution, never invents one."""
    cfg = ModelConfig(
        vocab_size=128, d_model=32, n_layers=1, n_heads=4, n_kv_heads=2,
        d_ff=64, max_seq=64, compute_dtype=jnp.float32,
        moe=MoEConfig(n_experts=4, top_k=1, capacity_factor=0.5))
    params = init_params(cfg, jax.random.key(0))
    p = _layer0(params)
    x = jax.random.normal(jax.random.key(1), (1, 32, cfg.d_model), jnp.float32)
    out, _ = moe_mlp(x, p, cfg)
    ref = moe_mlp_reference(x, p, cfg)
    out, ref = np.asarray(out)[0], np.asarray(ref)[0]
    # top_k=1: a kept token matches the reference, a dropped one is 0.
    kept = np.isclose(out, ref, rtol=2e-5, atol=2e-5).all(axis=-1)
    dropped = np.isclose(out, 0.0, atol=1e-6).all(axis=-1)
    assert (kept | dropped).all()
    assert dropped.any(), "capacity 0.5 over uniform router must drop"
    assert kept.any()


def test_moe_capacity_seating_is_slot_rank_order():
    """Seats fill in (token, slot-rank) order: with capacity C and one
    expert receiving everything, exactly the first C tokens survive."""
    cfg = ModelConfig(
        vocab_size=128, d_model=32, n_layers=1, n_heads=4, n_kv_heads=2,
        d_ff=64, max_seq=64, compute_dtype=jnp.float32,
        moe=MoEConfig(n_experts=4, top_k=1, capacity_factor=1.0))
    params = init_params(cfg, jax.random.key(0))
    p = dict(_layer0(params))
    # Router forced: every token picks expert 2.
    router = np.zeros((cfg.d_model, 4), np.float32)
    router[:, 2] = 1.0
    p["router"] = jnp.asarray(router)
    x = jnp.abs(jax.random.normal(jax.random.key(1), (1, 32, cfg.d_model))) + 0.1
    out, _ = moe_mlp(x, p, cfg)
    out = np.asarray(out)[0]
    C = cfg.moe.capacity(32)  # 32 * 1 * 1.0 / 4 = 8
    assert C == 8
    live = ~np.isclose(out, 0.0, atol=1e-6).all(axis=-1)
    assert live[:C].all() and not live[C:].any()


def test_moe_forward_aux_positive_and_bounded():
    params = init_params(MOE_TINY, jax.random.key(0))
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 128, (2, 32)))
    logits, aux = forward_with_aux(params, toks, MOE_TINY)
    assert logits.shape == (2, 32, 128)
    # Perfectly balanced top-k routing gives aux == weight * n_layers
    # (E * sum(1/E * 1/E * E) == 1 per layer); skew only raises it.
    w = MOE_TINY.moe.aux_loss_weight * MOE_TINY.n_layers
    assert float(aux) >= 0.9 * w
    assert np.isfinite(float(aux))


@requires_shard_map
def test_moe_sharded_ep_matches_unsharded():
    """dp=2 x ep=2 x tp=2 sharded MoE train step == single-device step:
    expert parallelism is layout, not math (modulo bf16-free f32 path)."""
    plan = build_mesh({"dp": 2, "ep": 2, "tp": 2})
    toks = jnp.asarray(np.random.default_rng(1).integers(0, 128, (4, 32)))

    ref_state = make_train_state(MOE_TINY, jax.random.key(2), lr=1e-2)
    ref_loss = float(loss_fn(ref_state.params, toks, MOE_TINY))

    sh_state = make_sharded_state(plan, MOE_TINY, jax.random.key(2), lr=1e-2)
    step = make_sharded_train_step(plan, MOE_TINY, lr=1e-2)
    sh_state, sh_loss = step(sh_state, toks)
    assert float(sh_loss) == pytest.approx(ref_loss, rel=1e-4)

    ref_state, _ = jax.jit(
        lambda s, t: train_step(s, t, MOE_TINY, lr=1e-2))(ref_state, toks)
    for a, b in zip(jax.tree.leaves(ref_state.params),
                    jax.tree.leaves(sh_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_moe_expert_weights_actually_sharded_over_ep():
    plan = build_mesh({"dp": 2, "ep": 2, "tp": 2})
    state = make_sharded_state(plan, MOE_TINY, jax.random.key(0))
    wg = state.params["layers"]["moe"]["w_gate"]  # [L, E, D, F]
    shard_shape = wg.sharding.shard_shape(wg.shape)
    E = MOE_TINY.moe.n_experts
    assert shard_shape[1] == E // 2, "expert axis must split over ep"
    assert shard_shape[3] == MOE_TINY.d_ff // 2, "ffn axis must split over tp"


def test_moe_training_reduces_loss():
    plan = build_mesh({"dp": 2, "ep": 2, "tp": 2})
    state = make_sharded_state(plan, MOE_TINY, jax.random.key(3), lr=5e-3)
    step = make_sharded_train_step(plan, MOE_TINY, lr=5e-3)
    toks = jnp.asarray(np.random.default_rng(2).integers(0, 128, (4, 32)))
    state, first = step(state, toks)
    for _ in range(8):
        state, loss = step(state, toks)
    assert float(loss) < float(first)


def test_reference_path_matches_per_expert_unroll():
    """VERDICT r3 #5: the batched drop-free mixture must equal the naive
    per-expert unroll it replaced, token for token."""
    cfg = MOE_TINY
    params = init_params(cfg, jax.random.key(3))
    p = _layer0(params)
    x = jax.random.normal(jax.random.key(4), (2, 6, cfg.d_model), jnp.float32)

    got = moe_mlp_reference(x, p, cfg)

    m = cfg.moe
    x32 = x.astype(jnp.float32)
    probs = jax.nn.softmax(x32 @ p["router"].astype(jnp.float32), -1)
    gates, idx = jax.lax.top_k(probs, m.top_k)
    gates = gates / jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)
    ys = jnp.stack([
        (jax.nn.silu(x32 @ p["w_gate"][e]) * (x32 @ p["w_up"][e]))
        @ p["w_down"][e]
        for e in range(m.n_experts)])
    w = (jax.nn.one_hot(idx, m.n_experts) * gates[..., None]).sum(2)
    want = jnp.einsum("bte,ebtd->btd", w, ys).astype(x.dtype)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_reference_path_hlo_is_constant_in_expert_count():
    """The decode serving path must compile O(1) in E (the old unroll was
    O(E) HLO — wrong shape at E=64)."""
    def hlo_len(n_experts):
        cfg = ModelConfig(
            vocab_size=128, d_model=32, n_layers=1, n_heads=4, n_kv_heads=2,
            d_ff=64, max_seq=64, compute_dtype=jnp.float32,
            moe=MoEConfig(n_experts=n_experts, top_k=2, capacity_factor=2.0))
        params = init_params(cfg, jax.random.key(0))
        p = _layer0(params)
        x = jnp.ones((1, 2, cfg.d_model), jnp.float32)
        fn = jax.jit(lambda x, p: moe_mlp_reference(x, p, cfg))
        return len(fn.lower(x, p).as_text())

    small, big = hlo_len(4), hlo_len(64)
    assert big < small * 2, (small, big)
