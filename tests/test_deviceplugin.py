"""Device-plugin tests: bring-up flow (SURVEY.md §3.1), Allocate flow
(§3.3), and the ASSIGNED/ASSUME_TIME handshake confirm leg
(design.md:237-246)."""

import json
import os

import pytest

from tputopo.deviceplugin import FakeKubelet, TpuDevicePlugin
from tputopo.deviceplugin import api as dp_api
from tputopo.discovery.shim import _probe_python, _to_host_probe
from tputopo.k8s import FakeApiServer, make_pod
from tputopo.k8s import objects as ko


def fake_probe(spec: str):
    env = dict(os.environ)
    env["TPUTOPO_FAKE"] = spec
    return _to_host_probe(_probe_python(env))


def make_plugin(spec="v5p:2x2x4@1", node="n1", clock=None):
    api_server = FakeApiServer()
    kubelet = FakeKubelet()
    plugin = TpuDevicePlugin(
        node_name=node, slice_id="slice-a", kubelet=kubelet,
        api_server=api_server, probe=fake_probe(spec),
        clock=clock or (lambda: 1000.0),
    )
    return plugin, kubelet, api_server


def test_bringup_registers_and_reports():
    plugin, kubelet, api_server = make_plugin()
    plugin.start()
    # Registration happened with the canonical resource name.
    assert kubelet.registrations[0].resource_name == ko.RESOURCE_CHIPS
    assert kubelet.allocatable(ko.RESOURCE_CHIPS) == 4
    # Node object was created with topology annotations.
    node = api_server.get("nodes", "n1")
    anns = node["metadata"]["annotations"]
    assert anns[ko.ANN_TOPOLOGY] == "v5p:2x2x4:wrap=000"
    assert anns[ko.ANN_HOST_COORD] == "0,0,1"  # worker 1 of 4 hosts along z
    chips = json.loads(anns[ko.ANN_CHIPS])
    assert [c["id"] for c in chips] == ["0,0,1", "0,1,1", "1,0,1", "1,1,1"]
    assert anns[ko.ANN_SLICE_ID] == "slice-a"
    assert "v5p 2x2x4" in anns[ko.ANN_TOPOLOGY_HUMAN]
    assert node["metadata"]["labels"][ko.ANN_GENERATION_LABEL] == "v5p"


def test_bringup_patches_existing_node():
    plugin, kubelet, api_server = make_plugin()
    from tputopo.k8s import make_node
    api_server.create("nodes", make_node("n1", chips=0, labels={"x": "y"}))
    plugin.start()
    node = api_server.get("nodes", "n1")
    # Pre-existing labels preserved AND the quota-classing generation label
    # lands on the patch path too (real clusters always have the Node first).
    assert node["metadata"]["labels"] == {"x": "y", ko.ANN_GENERATION_LABEL: "v5p"}
    assert ko.ANN_TOPOLOGY in node["metadata"]["annotations"]


def test_allocate_honors_extender_group_and_confirms():
    plugin, kubelet, api_server = make_plugin()
    plugin.start()
    # The extender bound a pod to this node choosing chips (0,0,1),(0,1,1).
    pod = make_pod("job-0", chips=2, node_name="n1", annotations={
        ko.ANN_GROUP: "0,0,1;0,1,1",
        ko.ANN_ASSUME_TIME: "999.0",
        ko.ANN_ASSIGNED: "false",
    })
    api_server.create("pods", pod)
    # kubelet calls Allocate with its own (possibly different) pick:
    resp = kubelet.allocate(ko.RESOURCE_CHIPS, ["1,0,1", "1,1,1"])
    env = resp.container_responses[0].envs
    # The pod annotation wins (flow ⑥), mapped to local chip indices 0,1.
    assert env["TPU_VISIBLE_CHIPS"] == "0,1"
    assert env["TPU_CHIPS_PER_HOST_BOUNDS"] == "2,2,1"
    assert env["TPU_WORKER_ID"] == "1"
    assert env["TPU_SLICE_TOPOLOGY"] == "2x2x4"
    # Device mounts for the chosen chips.
    assert [d.host_path for d in resp.container_responses[0].devices] == \
        ["/dev/accel0", "/dev/accel1"]
    # Handshake confirmed: ASSIGNED true, fresh assume time.
    fresh = api_server.get("pods", "job-0", "default")
    assert fresh["metadata"]["annotations"][ko.ANN_ASSIGNED] == "true"
    assert fresh["metadata"]["annotations"][ko.ANN_ASSUME_TIME] == "1000.0"


def test_allocate_without_pending_pod_uses_kubelet_ids():
    plugin, kubelet, api_server = make_plugin()
    plugin.start()
    resp = kubelet.allocate(ko.RESOURCE_CHIPS, ["0,0,1"])
    assert resp.container_responses[0].envs["TPU_VISIBLE_CHIPS"] == "0"


def test_allocate_oldest_pending_pod_wins():
    plugin, kubelet, api_server = make_plugin()
    plugin.start()
    # Both assumptions live (within the 60 s TTL of clock=1000): oldest wins.
    for name, t, group in [("new", "990", "0,0,1"), ("old", "950", "0,1,1")]:
        api_server.create("pods", make_pod(name, chips=1, node_name="n1",
                          annotations={ko.ANN_GROUP: group,
                                       ko.ANN_ASSUME_TIME: t,
                                       ko.ANN_ASSIGNED: "false"}))
    kubelet.allocate(ko.RESOURCE_CHIPS, ["1,1,1"])
    assert api_server.get("pods", "old", "default")["metadata"]["annotations"][
        ko.ANN_ASSIGNED] == "true"
    assert api_server.get("pods", "new", "default")["metadata"]["annotations"][
        ko.ANN_ASSIGNED] == "false"


def test_allocate_skips_expired_assumption():
    """An assumption older than the TTL must not be confirmed by a late
    Allocate — the extender already treats those chips as free and may have
    re-promised them (the bind-vs-allocate race, SURVEY.md §5.2)."""
    plugin, kubelet, api_server = make_plugin()
    plugin.start()
    api_server.create("pods", make_pod("stale", chips=1, node_name="n1",
                      annotations={ko.ANN_GROUP: "0,0,1",
                                   ko.ANN_ASSUME_TIME: "100",  # 900 s old
                                   ko.ANN_ASSIGNED: "false"}))
    resp = kubelet.allocate(ko.RESOURCE_CHIPS, ["1,1,1"])
    # Stale pod NOT confirmed; kubelet ids honored (chip 1,1,1 is unreserved
    # because the only annotation holding it... holds 0,0,1, which is stale).
    assert api_server.get("pods", "stale", "default")["metadata"][
        "annotations"][ko.ANN_ASSIGNED] == "false"
    assert resp.container_responses[0].envs["TPU_VISIBLE_CHIPS"] == "3"


def test_allocate_refuses_kubelet_ids_reserved_by_live_assumption():
    """The kubelet's arbitrary pick must not raid chips a still-valid
    assignment reserves for another pod."""
    plugin, kubelet, api_server = make_plugin()
    plugin.start()
    api_server.create("pods", make_pod("holder", chips=2, node_name="n1",
                      annotations={ko.ANN_GROUP: "0,0,1;0,1,1",
                                   ko.ANN_ASSUME_TIME: "990",
                                   ko.ANN_ASSIGNED: "false"}))
    # Request size 1 doesn't match holder's group (2), so no pending pod is
    # found — the fallback must still respect holder's reservation.
    with pytest.raises(ValueError, match="reserved"):
        kubelet.allocate(ko.RESOURCE_CHIPS, ["0,0,1"])


def test_health_flip_propagates_to_kubelet():
    plugin, kubelet, api_server = make_plugin()
    plugin.start()
    assert kubelet.allocatable(ko.RESOURCE_CHIPS) == 4
    plugin.set_health("0,0,1", healthy=False)
    assert kubelet.allocatable(ko.RESOURCE_CHIPS) == 3
    assert kubelet.devices["0,0,1"].health == dp_api.UNHEALTHY
    plugin.set_health("0,0,1", healthy=True)
    assert kubelet.allocatable(ko.RESOURCE_CHIPS) == 4
    with pytest.raises(KeyError):
        plugin.set_health("9,9,9", True)


def test_allocate_rejects_foreign_chip():
    plugin, kubelet, api_server = make_plugin()
    plugin.start()
    with pytest.raises(ValueError):
        kubelet.allocate(ko.RESOURCE_CHIPS, ["0,0,0"])  # chip on worker 0, not 1


def test_failed_probe_refuses_to_start():
    env = {k: v for k, v in os.environ.items() if k != "TPUTOPO_FAKE"}
    env.pop("TPU_ACCELERATOR_TYPE", None)
    bad = _to_host_probe(_probe_python(env))
    with pytest.raises(RuntimeError):
        TpuDevicePlugin("n0", "s", FakeKubelet(), FakeApiServer(), probe=bad)


# ---- GetPreferredAllocation (VERDICT r2 #8) ---------------------------------

def test_preferred_allocation_picks_adjacent_and_antifragments():
    from tests.cluster import probe_for
    from tputopo.deviceplugin.api import FakeKubelet
    from tputopo.k8s import FakeApiServer

    plugin = TpuDevicePlugin(
        node_name="n", slice_id="s", kubelet=FakeKubelet(),
        api_server=FakeApiServer(), probe=probe_for("v5p:2x2x1@0"),
        clock=lambda: 0.0)
    avail = ["0,0,0", "0,1,0", "1,1,0"]  # L-shape: corner pair is diagonal
    pair = plugin.preferred_allocation(avail, [], 2)
    assert pair in (["0,0,0", "0,1,0"], ["0,1,0", "1,1,0"])  # adjacent only
    # must_include is honored.
    assert plugin.preferred_allocation(avail, ["1,1,0"], 2) == [
        "0,1,0", "1,1,0"]
    # k=1 Singular policy: take the loner, preserve the adjacent pair.
    # (0,1,0) has two available neighbors; the ends have one each.
    one = plugin.preferred_allocation(avail, [], 1)
    assert one != ["0,1,0"]
    # Full-size request returns everything.
    assert plugin.preferred_allocation(avail, [], 3) == sorted(avail)


def test_preferred_allocation_input_validation():
    from tests.cluster import probe_for
    from tputopo.deviceplugin.api import FakeKubelet
    from tputopo.k8s import FakeApiServer

    plugin = TpuDevicePlugin(
        node_name="n", slice_id="s", kubelet=FakeKubelet(),
        api_server=FakeApiServer(), probe=probe_for("v5p:2x2x1@0"),
        clock=lambda: 0.0)
    with pytest.raises(ValueError, match="not on node"):
        plugin.preferred_allocation(["9,9,9"], [], 1)
    with pytest.raises(ValueError, match="missing from available"):
        plugin.preferred_allocation(["0,0,0"], ["0,1,0"], 1)
    with pytest.raises(ValueError, match="cannot pick"):
        plugin.preferred_allocation(["0,0,0", "0,1,0"], [], 3)


def test_preferred_allocation_avoids_reserved_chips():
    """Chips a bound-but-unconfirmed pod reserves are steered around, so
    the kubelet's pick survives Allocate's reserved-chip check."""
    from tests.cluster import probe_for
    from tputopo.deviceplugin.api import FakeKubelet
    from tputopo.k8s import FakeApiServer, make_pod

    api = FakeApiServer()
    plugin = TpuDevicePlugin(
        node_name="n", slice_id="s", kubelet=FakeKubelet(),
        api_server=api, probe=probe_for("v5p:2x2x1@0"),
        clock=lambda: 1000.0)
    api.create("pods", make_pod(
        "pending", chips=2, node_name="n",
        annotations={ko.ANN_GROUP: "0,0,0;0,1,0",
                     ko.ANN_ASSUME_TIME: "995", ko.ANN_ASSIGNED: "false"}))
    everything = ["0,0,0", "0,1,0", "1,0,0", "1,1,0"]
    # A size matching the live assumption returns ITS group: Allocate will
    # mount exactly that group, so any other answer would desync the
    # kubelet's device accounting from the mounted chips.
    assert plugin.preferred_allocation(everything, [], 2) == [
        "0,0,0", "0,1,0"]
    # No matching assumption (size 1): steer around the reserved pair so
    # the pick survives Allocate's reserved-chip check.
    assert plugin.preferred_allocation(everything, [], 1)[0] in (
        "1,0,0", "1,1,0")
    # When only reserved chips can cover the request, fall back to them
    # (Allocate remains the authority).
    assert len(plugin.preferred_allocation(everything, [], 4)) == 4
