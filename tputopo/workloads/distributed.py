"""Multi-host process bootstrap: ``jax.distributed`` from the gang's env.

The scheduler places a multi-host gang (pods sharing ``tpu.dev/gang-id``)
on a contiguous host box — but JAX on TPU is one *process per host*, and
those processes must rendezvous (``jax.distributed.initialize``) before
``jax.devices()`` spans the slice and collectives can ride ICI/DCN.  The
reference leaves everything inside the container to the workload
(SURVEY.md §1 L5); here the bootstrap is part of the framework: every
workload CLI entry calls :func:`initialize_from_env` first, which is a
no-op for single-process jobs and a full rendezvous for gangs.

Env contract (all have k8s-native defaults, see
``deploy/examples/job-gang-4x4.yaml``):

- ``TPUTOPO_COORDINATOR`` — ``host:port`` of the rank-0 process (in k8s: a
  headless Service name + the job's pod index 0, e.g.
  ``llama-dp4-0.llama-dp4:8476``).  Required when num_processes > 1.
- ``TPUTOPO_NUM_PROCESSES`` (alias ``TPUTOPO_GANG_SIZE``) — gang size;
  defaults to 1 (single-process).  Must be set explicitly in the gang's
  Job template — there is no implicit k8s-label default.
- ``TPUTOPO_PROCESS_ID`` — this process's rank.  When num_processes > 1
  and unset, falls back to ``JOB_COMPLETION_INDEX`` (k8s Indexed Job, the
  gang example's mode), then ``TPU_WORKER_ID`` / ``CLOUD_TPU_TASK_ID``
  (the host ordinals the device plugin and stock Cloud TPU VMs inject —
  the same chain discovery/shim.py resolves).  Single-process jobs ignore
  the fallbacks entirely: the device plugin injects ``TPU_WORKER_ID``
  into EVERY container, and a 1-pod job on a non-zero host must not be
  misread as rank 1 of 1.

Ranks must be dense 0..n-1 and agree with the coordinator's own index —
the k8s Indexed Job provides exactly that for free.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

DEFAULT_PORT = 8476


@dataclass(frozen=True)
class ProcessGroup:
    """The resolved multi-process identity of this workload container."""

    coordinator: str | None
    num_processes: int
    process_id: int

    @property
    def single(self) -> bool:
        return self.num_processes <= 1


def _int_env(env: dict, *names: str) -> int | None:
    for name in names:
        raw = env.get(name, "").strip()
        if raw:
            try:
                return int(raw)
            except ValueError:
                raise ValueError(f"{name} must be an integer, got {raw!r}")
    return None


def process_group_from_env(env: dict | None = None) -> ProcessGroup:
    """Resolve (coordinator, num_processes, process_id) per the module
    contract; raises on inconsistent configuration instead of letting a
    half-configured gang hang in rendezvous."""
    env = dict(os.environ if env is None else env)
    num = _int_env(env, "TPUTOPO_NUM_PROCESSES", "TPUTOPO_GANG_SIZE")
    if num is None:
        num = 1
    if num > 1:
        pid = _int_env(env, "TPUTOPO_PROCESS_ID", "JOB_COMPLETION_INDEX",
                       "TPU_WORKER_ID", "CLOUD_TPU_TASK_ID")
    else:
        # Only the explicit variable counts for single-process jobs: the
        # device plugin injects TPU_WORKER_ID (its host ordinal) into
        # every container, and a 1-pod job on worker 1 is still rank 0.
        pid = _int_env(env, "TPUTOPO_PROCESS_ID")
    if pid is None:
        pid = 0
    coord = env.get("TPUTOPO_COORDINATOR", "").strip() or None
    if coord is not None and ":" not in coord:
        coord = f"{coord}:{DEFAULT_PORT}"
    if num < 1:
        raise ValueError(f"num_processes must be >= 1, got {num}")
    if not 0 <= pid < num:
        raise ValueError(
            f"process_id {pid} out of range for {num} processes (ranks "
            "must be dense 0..n-1 — is JOB_COMPLETION_INDEX wired?)")
    if num > 1 and coord is None:
        raise ValueError(
            "TPUTOPO_NUM_PROCESSES > 1 needs TPUTOPO_COORDINATOR "
            "(rank-0 'host:port'; in k8s a headless Service name, see "
            "deploy/examples/job-gang-4x4.yaml)")
    return ProcessGroup(coordinator=coord, num_processes=num, process_id=pid)


def initialize_from_env(env: dict | None = None, **kwargs) -> ProcessGroup:
    """Rendezvous the gang if this is a multi-process job; no-op otherwise.

    Call BEFORE the first jax backend touch (the same before-first-touch
    rule the dry-run entry enforces).  Extra kwargs pass through to
    ``jax.distributed.initialize`` (e.g.
    ``initialization_timeout`` for a fail-loud bound instead of the
    default block — design.md:109's posture applied to rendezvous).
    """
    group = process_group_from_env(env)
    if not group.single:
        import jax

        jax.distributed.initialize(
            coordinator_address=group.coordinator,
            num_processes=group.num_processes,
            process_id=group.process_id, **kwargs)
    return group
